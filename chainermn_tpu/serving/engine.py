"""Continuous-batching decode engine over the static KV-cache path.

The offline :func:`chainermn_tpu.models.generate` decodes ONE fixed batch
start-to-finish; a traffic-facing server cannot wait for the slowest
request before admitting the next. This engine owns a fixed pool of
``n_slots`` cache slots inside one persistent static-shape KV cache
(:func:`~chainermn_tpu.models.transformer.init_kv_caches`-backed) and
exposes exactly two compiled device programs:

- ``prefill``: run one request's (padded) prompt through the model,
  writing its K/V into ONE slot of the shared cache and sampling the first
  generated token — admission cost is one prefill, independent of every
  other slot's progress;
- ``decode_step``: advance ALL slots one token per call, each at its OWN
  sequence position (the per-slot ``[B]`` position form of
  ``update_cache_and_attend``); retired/free slots ride along masked by
  ``jnp.where`` so shapes never change and nothing recompiles.

Why this is correct without ever zeroing a slot between requests: the
causal position mask only admits cache rows at positions ``<= q_pos``, and
every such row was either written by THIS request's prefill (rows
``< prompt_len``) or overwritten by one of its decode steps (each step
writes its query row before attending). Stale K/V from a previous tenant
of the slot — and the padding rows a short prompt leaves behind — sit at
positions the mask excludes until the exact step that overwrites them.
The engine-level parity test (staggered admissions vs solo ``generate()``,
token-for-token) pins this.

Per-request sampling parity: each slot carries its own PRNG key and draws
through the SAME ``_sampler`` split sequence as a solo ``generate()`` call
(one split at prefill, one per decode step), via a per-slot vmap — so a
request's tokens are independent of which other requests share the batch.

Tensor-parallel decode reuses the ``_generate_tp_fn`` pattern: both
programs are traced inside ``comm.shard_map`` with the cache's head axis
sharded over the mesh (``P(None, None, axis)`` at rest), and a
vocab-parallel head's local logits are ``all_gather``-ed before sampling —
the scheduler drives TP decode through the identical slot API.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chainermn_tpu.extensions.profiling import Watchdog
from chainermn_tpu.models.transformer import (
    _sampler,
    init_kv_caches,
)
from chainermn_tpu.monitor import RecompileGuard, annotate
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.resilience.faults import inject


class ServingEngine:
    """Slot-pool KV-cache decode engine (mechanism only — admission policy,
    EOS retirement, and per-request bookkeeping live in
    :class:`~chainermn_tpu.serving.scheduler.FCFSScheduler`).

    Parameters
    ----------
    model : TransformerLM
        Built for inference: ``sequence_axis=None``; MoE via
        ``moe_impl='gshard'``; ``tensor_axis`` set requires ``comm``.
    params : pytree
        Model parameters (the engine never mutates them).
    n_slots : int
        Cache slots == max concurrently-decoding requests. The decode
        program's batch dimension; fixed at construction.
    prefill_len : int
        Every prompt is right-padded to this length so prefill compiles
        ONCE. Padding rows write K/V the causal mask hides until decode
        overwrites them (module docstring); longer prompts are rejected.
    cache_len : int, optional
        Per-slot KV capacity (prompt + generated); defaults to
        ``model.max_len``. A request needs ``len(prompt) + max_new <=
        cache_len``.
    temperature / top_k / top_p : sampler configuration shared by every
        request (the compiled programs bake it in, exactly like
        ``generate()``'s lru-cache key).
    comm : communicator, optional
        Required iff ``model.tensor_axis`` is set: both programs then run
        inside its ``shard_map`` with head-sharded caches.
    watchdog : Watchdog or float, optional
        Hang detection around every device program call (prefill AND the
        all-slots decode step). Default **off**. A float builds a
        ``Watchdog(timeout=...)`` (abort mode — die loudly, the
        ``global_except_hook`` stance); pass a configured ``Watchdog``
        (e.g. ``on_timeout='warn'``) for report-only. On fire it dumps
        thread stacks + the monitor flight recorder (last events incl.
        slot admits/retires, per-device memory), so a wedged collective
        in serving aborts with evidence instead of hanging the client
        thread forever.
    """

    def __init__(self, model, params, *, n_slots: int, prefill_len: int,
                 cache_len: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, comm=None,
                 watchdog: Optional[Union[Watchdog, float]] = None):
        if model.sequence_axis is not None:
            raise ValueError(
                "serving decode does not support sequence-sharded models: "
                "rebuild with sequence_axis=None for inference"
            )
        if model.moe_experts and model.moe_impl != "gshard":
            raise ValueError(
                "serving decode supports MoE only via moe_impl='gshard' — "
                "rebuild the model with moe_impl='gshard' (same params)"
            )
        if model.tensor_axis is not None and comm is None:
            raise ValueError(
                "tensor-parallel serving needs comm= (the decode programs "
                "run inside the communicator's shard_map)"
            )
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        cache_len = cache_len or model.max_len
        if not 0 < prefill_len <= cache_len:
            raise ValueError(
                f"prefill_len must be in (0, cache_len={cache_len}], got "
                f"{prefill_len}"
            )
        if cache_len > model.max_len:
            raise ValueError(
                f"cache_len {cache_len} exceeds model.max_len "
                f"{model.max_len}"
            )
        self.model = model
        self.params = params
        self.n_slots = int(n_slots)
        self.prefill_len = int(prefill_len)
        self.cache_len = int(cache_len)
        self._comm = comm
        self._sample = _sampler(float(temperature), int(top_k), float(top_p))
        if watchdog is not None and not isinstance(watchdog, Watchdog):
            watchdog = Watchdog(timeout=float(watchdog))
        self.watchdog = watchdog
        self._events = get_event_log()
        labels = {"engine": "serving"}
        reg = get_registry()
        self._c_prefills = reg.counter("serving_prefills_total", labels)
        self._c_decode_steps = reg.counter("serving_decode_steps_total",
                                           labels)
        self._c_restarts = reg.counter("serving_engine_restarts_total",
                                       labels)

        if model.tensor_axis is not None:
            self._init_tp_caches(comm)
            self._prefill_fn, self._decode_fn = self._build_tp_fns(comm)
        else:
            self.caches = init_kv_caches(model, self.n_slots, self.cache_len)
            self._prefill_fn, self._decode_fn = self._build_fns()

        # host-side slot mirror: the scheduler reads/writes through the
        # occupy/release API; the decode program consumes these as [B]
        # device operands each step (tiny transfers, static shapes)
        self._token = np.zeros((self.n_slots,), np.int32)
        self._pos = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self._keys = jnp.zeros((self.n_slots, 2), jnp.uint32)
        self.free_slots = set(range(self.n_slots))

        # recompile tracking: the zero-recompile invariant as live
        # telemetry (compile/recompile events + recompiles_total counter),
        # checked after every device call — not only in tests
        self._guard = RecompileGuard()
        self._guard.watch("serving_prefill", self._prefill_fn)
        self._guard.watch("serving_decode", self._decode_fn)

    def _watched(self, label: str):
        """Watchdog context for one device-program call (no-op when hang
        detection is off)."""
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.step(label)

    # ------------------------------------------------------------------ #
    # program construction                                                #
    # ------------------------------------------------------------------ #

    def _prefill_body(self, vocab_gather=None):
        """Shared prefill trace: slice the slot out of the pooled cache,
        run the prompt through the model against it, splice the updated
        slot back, sample the first token from the last REAL position."""
        model, sample = self.model, self._sample

        def body(params, caches, tokens, slot, length, key):
            with annotate("chainermn.prefill"):
                return body_inner(params, caches, tokens, slot, length, key)

        def body_inner(params, caches, tokens, slot, length, key):
            slot_c = [
                {k: lax.dynamic_slice_in_dim(c[k], slot, 1, axis=0)
                 for k in ("k", "v")}
                for c in caches
            ]
            logits, slot_c = model.apply(params, tokens, 0,
                                         kv_caches=slot_c)
            caches = [
                {k: lax.dynamic_update_slice_in_dim(c[k], s[k], slot, axis=0)
                 for k in ("k", "v")}
                for c, s in zip(caches, slot_c)
            ]
            # logits of the last PROMPT token, not the last padded row
            lg = lax.dynamic_slice_in_dim(logits, length - 1, 1, axis=1)[:, 0]
            if vocab_gather is not None:
                lg = vocab_gather(lg)
            nxt, key = sample(lg, key)
            return caches, nxt[0], key

        return body

    def _decode_body(self, vocab_gather=None):
        """Shared decode trace: one token for EVERY slot, per-slot
        positions, per-slot sampler keys (each slot draws exactly like a
        B=1 ``generate()`` so batching never perturbs a request)."""
        model, sample = self.model, self._sample

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, caches, tokens, pos, active, keys):
            with annotate("chainermn.decode"):
                return body_inner(params, caches, tokens, pos, active, keys)

        def body_inner(params, caches, tokens, pos, active, keys):
            lg, caches = model.apply(params, tokens[:, None], pos[:, None],
                                     kv_caches=caches)
            lg = lg[:, 0]
            if vocab_gather is not None:
                lg = vocab_gather(lg)
            nxt, keys = jax.vmap(slot_sample)(lg, keys)
            # free/retired slots ride along masked — shapes never change
            nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
            return caches, nxt, keys

        return body

    def _build_fns(self):
        prefill = jax.jit(self._prefill_body(), donate_argnums=(1,))
        decode = jax.jit(self._decode_body(), donate_argnums=(1,))
        return prefill, decode

    def _init_tp_caches(self, comm):
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self.model.tensor_axis
        n_tp = comm.mesh.shape[axis]
        if self.model.n_heads % n_tp:
            raise ValueError(
                f"n_heads {self.model.n_heads} not divisible by "
                f"tensor-axis size {n_tp}"
            )
        shard = NamedSharding(comm.mesh, P(None, None, axis))
        self.caches = jax.device_put(
            init_kv_caches(self.model, self.n_slots, self.cache_len), shard)

    def _build_tp_fns(self, comm):
        from jax.sharding import PartitionSpec as P

        axis = self.model.tensor_axis
        gather = None
        if self.model.vocab_parallel_head:
            def gather(lg):
                return lax.all_gather(lg, axis, axis=-1, tiled=True)

        cache_spec = [{"k": P(None, None, axis), "v": P(None, None, axis)}
                      for _ in range(self.model.n_layers)]
        prefill = jax.jit(comm.shard_map(
            self._prefill_body(gather),
            in_specs=(P(), cache_spec, P(), P(), P(), P()),
            out_specs=(cache_spec, P(), P()),
            check_vma=False,
        ), donate_argnums=(1,))
        decode = jax.jit(comm.shard_map(
            self._decode_body(gather),
            in_specs=(P(), cache_spec, P(), P(), P(), P()),
            out_specs=(cache_spec, P(), P()),
            check_vma=False,
        ), donate_argnums=(1,))
        return prefill, decode

    # ------------------------------------------------------------------ #
    # slot API (host side)                                                #
    # ------------------------------------------------------------------ #

    @property
    def active_slots(self) -> int:
        return int(self._active.sum())

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> None:
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len > self.prefill_len:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds prefill_len="
                f"{self.prefill_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt_len + max_new_tokens > self.cache_len:
            raise ValueError(
                f"{prompt_len} prompt + {max_new_tokens} new tokens exceed "
                f"cache_len={self.cache_len}"
            )

    def prefill(self, prompt: np.ndarray, rng) -> tuple[int, int]:
        """Admit one prompt into a free slot: runs the compiled prefill,
        returns ``(slot, first_token)``. ``rng`` is the request's own PRNG
        key (its sampler split sequence matches a solo ``generate()``).
        Raises ``RuntimeError`` when no slot is free — admission control
        is the scheduler's job, not a silent queue here."""
        if not self.free_slots:
            raise RuntimeError("no free slot (scheduler admitted too many)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate_request(len(prompt), 1)
        slot = min(self.free_slots)  # deterministic pick: stable tests/replay
        padded = np.zeros((1, self.prefill_len), np.int32)
        padded[0, : len(prompt)] = prompt
        with self._watched("serving prefill"), \
                annotate("chainermn.serving_prefill"):
            # fault cut-point INSIDE the watchdog window: an injected hang
            # here exercises exactly the wedge hang detection exists for
            inject("serving.prefill", slot=slot, prompt_len=len(prompt))
            self.caches, first, key = self._prefill_fn(
                self.params, self.caches, jnp.asarray(padded),
                jnp.int32(slot), jnp.int32(len(prompt)), rng)
            first = int(first)
        self.free_slots.discard(slot)
        self._token[slot] = first
        self._pos[slot] = len(prompt)
        self._active[slot] = True
        self._keys = self._keys.at[slot].set(key)
        self._c_prefills.inc()
        self._events.emit("prefill", slot=slot, prompt_len=len(prompt))
        self._guard.check()
        return slot, first

    def decode_step(self) -> dict[int, int]:
        """Advance every active slot one token (ONE compiled call for the
        whole pool); returns ``{slot: token}`` for the active slots. No-op
        ({}) when nothing is active."""
        if not self._active.any():
            return {}
        # the fetch (np.asarray) is inside the watchdog window on purpose:
        # a wedged collective hangs exactly there, and that is the hang
        # the serving watchdog exists to turn into a loud abort
        with self._watched("serving decode_step"), \
                annotate("chainermn.serving_decode"):
            inject("serving.decode", active=int(self._active.sum()))
            self.caches, nxt, self._keys = self._decode_fn(
                self.params, self.caches, jnp.asarray(self._token),
                jnp.asarray(self._pos), jnp.asarray(self._active),
                self._keys)
            nxt = np.asarray(nxt)
        self._c_decode_steps.inc()
        self._events.emit("decode_step", active=int(self._active.sum()))
        self._guard.check()
        out = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            tok = int(nxt[slot])
            self._token[slot] = tok
            self._pos[slot] += 1
            out[slot] = tok
        return out

    def slot_tokens_used(self, slot: int) -> int:
        """Current sequence depth of a slot (prompt + generated so far)."""
        return int(self._pos[slot]) + 1 if self._active[slot] else 0

    def release(self, slot: int) -> None:
        """Retire a slot (EOS / length / cancellation). The cache is NOT
        zeroed: the causal position mask makes stale rows unreachable to
        the next tenant (module docstring — pinned by the slot-reuse
        parity test)."""
        if slot in self.free_slots:
            return
        self._active[slot] = False
        self.free_slots.add(slot)

    def restart(self) -> None:
        """Warm restart after an engine-side failure: fresh KV caches and
        cleared host slot mirrors, SAME compiled programs (the new arrays
        have identical shapes/shardings, so nothing recompiles — pinned by
        the restart test). Needed because a failed call may have consumed
        the donated cache buffers; params are never donated and survive.
        The scheduler drives this from its exception boundary; every
        restart is a counted, event-logged recovery."""
        if self.model.tensor_axis is not None:
            self._init_tp_caches(self._comm)
        else:
            self.caches = init_kv_caches(self.model, self.n_slots,
                                         self.cache_len)
        self._token[:] = 0
        self._pos[:] = 0
        self._active[:] = False
        self._keys = jnp.zeros((self.n_slots, 2), jnp.uint32)
        self.free_slots = set(range(self.n_slots))
        self._c_restarts.inc()
        self._events.emit("engine_restart")

    # ------------------------------------------------------------------ #
    # observability                                                       #
    # ------------------------------------------------------------------ #

    def compile_counts(self) -> dict[str, int]:
        """Executable counts of the two device programs — the
        zero-recompile invariant is ``{'prefill': 1, 'decode': 1}`` after
        warmup, asserted by tests and reported by the serving benchmark."""
        return {
            "prefill": int(self._prefill_fn._cache_size()),
            "decode": int(self._decode_fn._cache_size()),
        }


__all__ = ["ServingEngine"]
