"""Serving observability: TTFT, per-token latency, throughput, queue depth
and slot occupancy — the serving counterpart of the training side's
``extensions.StepTimer``/``collective_stats`` layer, reporting through the
same :func:`chainermn_tpu.extensions.latency_report` percentile convention
so training and serving benchmark records stay field-compatible.

All timestamps are caller-supplied ``time.perf_counter()`` values (the
scheduler owns the clock); this module only aggregates, so it is trivially
testable and thread-agnostic (the scheduler serializes all calls).
"""

from __future__ import annotations

from typing import Optional

from chainermn_tpu.extensions import latency_report


class ServingMetrics:
    """Aggregate serving statistics.

    Latency definitions (the standard inference-serving ones):

    - **TTFT** (time to first token): request submission -> its first
      generated token (queue wait + prefill; the admission-policy number).
    - **TPOT** (time per output token): gap between consecutive tokens of
      the SAME request (decode-step cadence; the streaming-smoothness
      number). First tokens don't contribute (they're TTFT).
    - **tokens/s**: generated tokens over the span between the first and
      last recorded token across all requests (engine-level throughput;
      0.0 until two tokens exist).

    Gauges (queue depth, slot occupancy) are sampled once per scheduler
    step and reported as means — occupancy is the fraction of the slot
    pool decoding, the continuous-batching utilization number.
    """

    def __init__(self, n_slots: int) -> None:
        self.n_slots = n_slots
        self.requests_submitted = 0
        self.requests_completed = 0
        self.requests_cancelled = 0
        self.tokens_generated = 0
        self._ttft: list[float] = []
        self._tpot: list[float] = []
        self._queue_depth: list[int] = []
        self._occupancy: list[float] = []
        self._t_first_token: Optional[float] = None
        self._t_last_token: Optional[float] = None

    # ------------------------------------------------------------------ #
    # recording (scheduler-driven)                                        #
    # ------------------------------------------------------------------ #

    def record_submit(self) -> None:
        self.requests_submitted += 1

    def record_first_token(self, t_submit: float, t_token: float) -> None:
        self._ttft.append(t_token - t_submit)
        self._record_token_time(t_token)
        self.tokens_generated += 1

    def record_token(self, t_prev_token: float, t_token: float) -> None:
        self._tpot.append(t_token - t_prev_token)
        self._record_token_time(t_token)
        self.tokens_generated += 1

    def record_done(self, cancelled: bool = False) -> None:
        if cancelled:
            self.requests_cancelled += 1
        else:
            self.requests_completed += 1

    def record_step(self, queue_depth: int, active_slots: int) -> None:
        self._queue_depth.append(queue_depth)
        self._occupancy.append(active_slots / self.n_slots)

    def _record_token_time(self, t: float) -> None:
        if self._t_first_token is None:
            self._t_first_token = t
        self._t_last_token = t

    # ------------------------------------------------------------------ #
    # reporting                                                           #
    # ------------------------------------------------------------------ #

    @property
    def tokens_per_sec(self) -> float:
        if self._t_first_token is None or self._t_last_token is None:
            return 0.0
        span = self._t_last_token - self._t_first_token
        if span <= 0.0:
            return 0.0
        # the first token opens the span, the rest fill it
        return (self.tokens_generated - 1) / span

    def report(self) -> dict:
        out = {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_cancelled": self.requests_cancelled,
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_per_sec, 2),
            "n_slots": self.n_slots,
        }
        out.update(latency_report(self._ttft, "ttft"))
        out.update(latency_report(self._tpot, "tpot"))
        if self._queue_depth:
            out["queue_depth_mean"] = round(
                sum(self._queue_depth) / len(self._queue_depth), 3)
        if self._occupancy:
            out["slot_occupancy_mean"] = round(
                sum(self._occupancy) / len(self._occupancy), 3)
        return out


__all__ = ["ServingMetrics"]
