"""Serving observability: TTFT, per-token latency, throughput, queue depth
and slot occupancy — the serving counterpart of the training side's
``extensions.StepTimer``/``collective_stats`` layer.

Since the monitor subsystem landed, this class keeps NO private sample
lists: every series lives in the process-wide
:class:`chainermn_tpu.monitor.MetricsRegistry` (labelled ``instance=N``
per scheduler so concurrent/successive schedulers never mix), which makes
the same numbers scrapeable through ``monitor.exposition()`` and
embeddable via ``monitor.snapshot()`` while :meth:`report` stays
field-compatible with the PR-1 records (``ttft_p50_s`` etc. via the same
:func:`chainermn_tpu.extensions.latency_report` convention). First-token
recordings also emit ``first_token`` events into the flight recorder, so
a TTFT outlier in a report can be traced to the specific ``slot_admit``
events around it.

All timestamps are caller-supplied ``time.perf_counter()`` values (the
scheduler owns the clock); this module only aggregates, so it is trivially
testable and thread-agnostic (the scheduler serializes all calls).
"""

from __future__ import annotations

import itertools
from typing import Optional

import numpy as np

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.extensions import latency_report
from chainermn_tpu.monitor import EventLog, MetricsRegistry
from chainermn_tpu.monitor._state import get_event_log, get_registry

_instance_ids = itertools.count()


class ServingMetrics:
    """Aggregate serving statistics.

    Latency definitions (the standard inference-serving ones):

    - **TTFT** (time to first token): request submission -> its first
      generated token (queue wait + prefill; the admission-policy number).
    - **TPOT** (time per output token): gap between consecutive tokens of
      the SAME request (decode-step cadence; the streaming-smoothness
      number). First tokens don't contribute (they're TTFT).
    - **tokens/s**: generated tokens over the span between the first and
      last recorded token across all requests (engine-level throughput;
      0.0 until two tokens exist).

    Gauges (queue depth, slot occupancy) are sampled once per scheduler
    step and reported as mean + p50/p99 — occupancy is the fraction of
    the slot pool decoding, the continuous-batching utilization number;
    its p99 says whether the pool ever actually fills under the offered
    load, which the mean alone hides.
    """

    def __init__(self, n_slots: int, *,
                 registry: Optional[MetricsRegistry] = None,
                 events: Optional[EventLog] = None) -> None:
        self.n_slots = n_slots
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        labels = {"instance": str(next(_instance_ids))}
        reg = self._registry
        self._c_submitted = reg.counter(
            "serving_requests_submitted_total", labels)
        self._c_completed = reg.counter(
            "serving_requests_completed_total", labels)
        self._c_cancelled = reg.counter(
            "serving_requests_cancelled_total", labels)
        # degradation counters (resilience layer): overload rejections at
        # submit, deadline sheds from the queue, engine-failure erroreds,
        # and warm engine restarts this scheduler drove
        self._c_rejected = reg.counter(
            "serving_requests_rejected_total", labels)
        self._c_shed = reg.counter("serving_requests_shed_total", labels)
        self._c_errored = reg.counter(
            "serving_requests_errored_total", labels)
        self._c_restarts = reg.counter(
            "serving_scheduler_restarts_total", labels)
        self._c_tokens = reg.counter("serving_tokens_total", labels)
        self._h_ttft = reg.histogram("serving_ttft_seconds", labels, unit="s")
        self._h_tpot = reg.histogram("serving_tpot_seconds", labels, unit="s")
        self._h_queue = reg.histogram("serving_queue_depth", labels)
        self._h_occ = reg.histogram("serving_slot_occupancy", labels)
        # admission fast path (PR 5): how full each batched prefill call
        # ran, and what fraction of each admitted prompt the prefix cache
        # covered (0.0 on a miss — so the mean IS the amortized discount,
        # and the >0 fraction is the hit rate)
        self._h_batch = reg.histogram("prefill_batch_size", labels)
        self._h_cached = reg.histogram("cached_prefix_frac", labels)
        self._g_queue = reg.gauge("serving_queue_depth_now", labels)
        self._g_active = reg.gauge("serving_active_slots", labels)
        # paged-KV series (PR 7): store occupancy gauges sampled per step,
        # preemptions (pool ran dry / injected append fault -> requeue),
        # and how many blocks each retired request's whole life took —
        # the "memory per request" distribution dense slots can't see
        self._g_kv_used = reg.gauge("kv_blocks_in_use", labels)
        self._g_kv_free = reg.gauge("kv_blocks_free", labels)
        self._c_preempt = reg.counter("kv_preemptions_total", labels)
        self._h_req_blocks = reg.histogram("kv_blocks_per_request", labels)
        # speculative decode (PR 12): per-round accept-length histogram
        # plus draft-economy counters — accepted/proposed IS the live
        # accept rate the drafter choice is judged by
        self._c_spec_proposed = reg.counter(
            "spec_tokens_proposed_total", labels)
        self._c_spec_accepted = reg.counter(
            "spec_tokens_accepted_total", labels)
        self._h_spec_accept = reg.histogram("spec_accept_length", labels)
        # overload robustness (PR 18): per-class queue depth (the
        # batch-behind-interactive split), class-labelled preemptions
        # (did batch really evict first?), and per-tenant brownout sheds
        self._g_class_queue = {
            cls: reg.gauge("serving_class_queue_depth",
                           dict(labels, priority=cls))
            for cls in ("interactive", "batch")
        }
        self._c_class_preempt = {
            cls: reg.counter("serving_class_preemptions_total",
                             dict(labels, priority=cls))
            for cls in ("interactive", "batch")
        }
        self._t_first_token: Optional[float] = None
        self._t_last_token: Optional[float] = None
        # EWMA TTFT (alpha=0.2): the routing layer's cheap "how slow is
        # this replica right now" signal — O(1), no percentile math on
        # the admission path
        self.ttft_ewma: Optional[float] = None
        # per-trace critical path (the tracing layer): phase-attributed
        # time per retired request, plus the single worst request's full
        # breakdown — the "where did the p99 go" exhibit in report()
        self._labels = labels
        self._worst_trace: Optional[dict] = None
        # continuous-telemetry hook (attach_health): a zero-arg callable
        # returning this instance's current HealthScore as a JSON dict;
        # report() embeds it so the health verdict rides every record
        self._health_fn = None
        # cost-accounting hook (attach_costs): the scheduler's per-tenant
        # CostLedger; report() embeds its rendered breakdown as "costs"
        self._costs = None

    # ------------------------------------------------------------------ #
    # recording (scheduler-driven)                                        #
    # ------------------------------------------------------------------ #

    def record_submit(self) -> None:
        self._c_submitted.inc()

    def record_first_token(self, t_submit: float, t_token: float,
                           req_id: Optional[int] = None,
                           cached_frac: Optional[float] = None) -> None:
        ttft = t_token - t_submit
        self._h_ttft.observe(ttft)
        self.ttft_ewma = (ttft if self.ttft_ewma is None
                          else 0.8 * self.ttft_ewma + 0.2 * ttft)
        self._record_token_time(t_token)
        self._c_tokens.inc()
        if cached_frac is not None:
            self._h_cached.observe(cached_frac)
        # the flight-recorder hook: a TTFT outlier names its request, so
        # it can be joined against the surrounding slot_admit events (and
        # its cached fraction says whether the prefix cache helped it)
        self._events.emit("first_token", req=req_id,
                          ttft_s=round(ttft, 6),
                          **({} if cached_frac is None
                             else {"cached_frac": round(cached_frac, 4)}))

    def record_admission(self, batch_size: int) -> None:
        """One admission device call admitted ``batch_size`` requests —
        the batched-prefill occupancy series."""
        self._h_batch.observe(batch_size)

    def record_token(self, t_prev_token: float, t_token: float) -> None:
        self._h_tpot.observe(t_token - t_prev_token)
        self._record_token_time(t_token)
        self._c_tokens.inc()

    def record_done(self, cancelled: bool = False) -> None:
        (self._c_cancelled if cancelled else self._c_completed).inc()

    def record_rejected(self) -> None:
        self._c_rejected.inc()

    def record_shed(self) -> None:
        self._c_shed.inc()

    def record_errored(self) -> None:
        self._c_errored.inc()

    def record_restart(self) -> None:
        self._c_restarts.inc()

    def record_kv_pool(self, in_use: int, free: int) -> None:
        """Paged-store occupancy, sampled once per scheduler step."""
        self._g_kv_used.set(in_use)
        self._g_kv_free.set(free)

    def record_preemption(self, priority: Optional[str] = None) -> None:
        """A decoding request was evicted back to the queue (block pool
        dry, or an injected ``serving.kv_append`` fault contained).
        ``priority`` feeds the per-class split — the batch-preempts-
        first contract is asserted against these counters."""
        self._c_preempt.inc()
        if priority in self._c_class_preempt:
            self._c_class_preempt[priority].inc()

    def record_tenant_shed(self, tenant: str) -> None:
        """A brownout L4 shed dropped one of ``tenant``'s queued
        requests (lazily-created per-tenant counter, same pattern as the
        ``trace_phase_seconds`` labelled histograms)."""
        self._registry.counter(
            "serving_tenant_sheds_total",
            dict(self._labels, tenant=str(tenant))).inc()

    def record_request_blocks(self, n_blocks: int) -> None:
        """Store blocks a retiring request's table referenced."""
        self._h_req_blocks.observe(n_blocks)

    def record_spec_window(self, proposed: int, accepted: int,
                           lengths: list) -> None:
        """One speculative verify round's accounting, drained from
        :meth:`~chainermn_tpu.serving.engine.ServingEngine
        .pop_spec_window`: totals feed the draft-economy counters, each
        slot's accept length feeds the histogram."""
        self._c_spec_proposed.inc(proposed)
        self._c_spec_accepted.inc(accepted)
        for a in lengths:
            self._h_spec_accept.observe(a)

    def record_trace(self, req_id: int, breakdown: dict) -> None:
        """One retired request's span-tree breakdown (built by
        :meth:`~chainermn_tpu.monitor.trace.Trace.breakdown`): each phase
        feeds a ``trace_phase_seconds{phase=}`` histogram (so queue wait
        vs prefill vs decode distributions are scrapeable), and the
        slowest request so far is kept whole as the critical-path
        exemplar."""
        phases = breakdown.get("phases_s", {})
        for phase, dur in phases.items():
            self._registry.histogram(
                "trace_phase_seconds", dict(self._labels, phase=phase),
                unit="s").observe(dur)
        total = breakdown.get("total_s", 0.0)
        if (self._worst_trace is None
                or total > self._worst_trace.get("total_s", 0.0)):
            self._worst_trace = dict(breakdown, req=req_id)

    def record_step(self, queue_depth: int, active_slots: int,
                    batch_depth: int = 0) -> None:
        self._h_queue.observe(queue_depth)
        self._h_occ.observe(active_slots / self.n_slots)
        self._g_queue.set(queue_depth)
        self._g_active.set(active_slots)
        self._g_class_queue["batch"].set(batch_depth)
        self._g_class_queue["interactive"].set(queue_depth - batch_depth)

    def _record_token_time(self, t: float) -> None:
        if self._t_first_token is None:
            self._t_first_token = t
        self._t_last_token = t

    # ------------------------------------------------------------------ #
    # reporting                                                           #
    # ------------------------------------------------------------------ #

    @property
    def instance(self) -> str:
        """This scheduler's ``instance=`` label value — the key the
        continuous-telemetry collector uses to find this instance's
        series in the shared registry."""
        return self._labels["instance"]

    def attach_health(self, fn) -> None:
        """Attach a zero-arg callable returning the current
        :class:`~chainermn_tpu.monitor.health.HealthScore` JSON for this
        instance (wired by :func:`~chainermn_tpu.monitor.health.
        fleet_health`); :meth:`report` then carries a ``health`` block.
        Detach with ``attach_health(None)``."""
        self._health_fn = fn

    def attach_costs(self, ledger) -> None:
        """Attach the scheduler's :class:`~chainermn_tpu.monitor.costs.
        CostLedger`; :meth:`report` then carries a ``costs`` block (per-
        tenant device/block/queue seconds + goodput + conservation) and
        the fleet layer pools :meth:`~chainermn_tpu.monitor.costs.
        CostLedger.payload` across replicas. Detach with
        ``attach_costs(None)``."""
        self._costs = ledger

    @property
    def costs(self):
        """The attached cost ledger, or None (accounting disabled)."""
        return self._costs

    @property
    def requests_submitted(self) -> int:
        return self._c_submitted.value

    @property
    def requests_completed(self) -> int:
        return self._c_completed.value

    @property
    def requests_cancelled(self) -> int:
        return self._c_cancelled.value

    @property
    def requests_rejected(self) -> int:
        return self._c_rejected.value

    @property
    def requests_shed(self) -> int:
        return self._c_shed.value

    @property
    def requests_errored(self) -> int:
        return self._c_errored.value

    @property
    def engine_restarts(self) -> int:
        return self._c_restarts.value

    @property
    def tokens_generated(self) -> int:
        return self._c_tokens.value

    @property
    def tokens_per_sec(self) -> float:
        if self._t_first_token is None or self._t_last_token is None:
            return 0.0
        span = self._t_last_token - self._t_first_token
        if span <= 0.0:
            return 0.0
        # the first token opens the span, the rest fill it
        return (self.tokens_generated - 1) / span

    def payload(self) -> dict:
        """This scheduler's series in the
        :meth:`~chainermn_tpu.monitor.registry.MetricsRegistry.
        _rank_payload` shape, keyed by PLAIN metric names (no ``instance``
        label) — so a fleet router can pool N replicas' metrics with
        :func:`~chainermn_tpu.monitor.registry.merge_rank_payloads`
        exactly the way ``aggregate(comm)`` pools ranks: counters sum,
        gauges mean, histogram reservoirs concatenate into fleet-wide
        p50/p99."""
        hists = {
            "serving_ttft_seconds": self._h_ttft,
            "serving_tpot_seconds": self._h_tpot,
            "serving_queue_depth": self._h_queue,
            "serving_slot_occupancy": self._h_occ,
        }
        return {
            "counters": {
                "serving_requests_submitted_total": self.requests_submitted,
                "serving_requests_completed_total": self.requests_completed,
                "serving_requests_cancelled_total": self.requests_cancelled,
                "serving_requests_rejected_total": self.requests_rejected,
                "serving_requests_shed_total": self.requests_shed,
                "serving_requests_errored_total": self.requests_errored,
                "serving_scheduler_restarts_total": self.engine_restarts,
                "serving_tokens_total": self.tokens_generated,
            },
            "gauges": {
                "serving_queue_depth_now": float(self._g_queue.value),
                "serving_active_slots": float(self._g_active.value),
            },
            "hist": {
                name: {"unit": h.unit, "count": h.count, "sum": h.sum,
                       "samples": h.samples}
                for name, h in hists.items()
            },
        }

    def report(self) -> dict:
        out = {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "requests_cancelled": self.requests_cancelled,
            "requests_rejected": self.requests_rejected,
            "requests_shed": self.requests_shed,
            "requests_errored": self.requests_errored,
            "engine_restarts": self.engine_restarts,
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_per_sec, 2),
            "n_slots": self.n_slots,
        }
        out.update(latency_report(self._h_ttft.samples, "ttft"))
        out.update(latency_report(self._h_tpot.samples, "tpot"))
        cached = self._h_cached.samples
        if cached:
            t = np.asarray(cached, np.float64)
            out["cached_prefix_frac_mean"] = round(float(t.mean()), 4)
            out["prefix_hit_rate"] = round(float((t > 0).mean()), 4)
        batch = self._h_batch.samples
        if batch:
            t = np.asarray(batch, np.float64)
            out["prefill_batch_size_mean"] = round(float(t.mean()), 3)
            out["prefill_batch_size_max"] = int(t.max())
        for hist, prefix in ((self._h_queue, "queue_depth"),
                             (self._h_occ, "slot_occupancy")):
            samples = hist.samples
            if not samples:
                continue
            t = np.asarray(samples, np.float64)
            out[f"{prefix}_mean"] = round(float(t.mean()), 3)
            out[f"{prefix}_p50"] = round(float(np.percentile(t, 50)), 3)
            out[f"{prefix}_p99"] = round(float(np.percentile(t, 99)), 3)
        req_blocks = self._h_req_blocks.samples
        if req_blocks:   # paged engines only — dense reports stay as-is
            t = np.asarray(req_blocks, np.float64)
            out["kv_blocks_per_request_mean"] = round(float(t.mean()), 3)
            out["kv_blocks_per_request_max"] = int(t.max())
            out["kv_preemptions"] = int(self._c_preempt.value)
            out["kv_blocks_in_use"] = int(self._g_kv_used.value)
            out["kv_blocks_free"] = int(self._g_kv_free.value)
        spec_prop = int(self._c_spec_proposed.value)
        if spec_prop:   # speculative engines only
            spec_acc = int(self._c_spec_accepted.value)
            out["spec_tokens_proposed"] = spec_prop
            out["spec_tokens_accepted"] = spec_acc
            out["spec_accept_rate"] = round(spec_acc / spec_prop, 4)
            accept = self._h_spec_accept.samples
            if accept:
                t = np.asarray(accept, np.float64)
                out["spec_accept_length_mean"] = round(float(t.mean()), 3)
        if self._worst_trace is not None:
            # the slowest traced request's full phase attribution — the
            # compact "where the p99 TTFT went" answer, per trace
            out["critical_path"] = self._worst_trace
        if self._health_fn is not None:
            try:
                out["health"] = self._health_fn()
            except Exception as e:  # noqa: BLE001 — reporting never raises
                out["health"] = {"error": f"{type(e).__name__}: {e}"}
        if self._costs is not None:
            try:
                out["costs"] = self._costs.report()
            except Exception as e:  # noqa: BLE001 — reporting never raises
                out["costs"] = {"error": f"{type(e).__name__}: {e}"}
        if sanitizer.enabled():
            # lock-hold / contention accounting (sanitizer runs only):
            # which lock the serving path actually spends its time in
            holds = sanitizer.hold_stats()
            if holds:
                out["lock_hold_seconds"] = {
                    name: {"count": s["count"],
                           "total_s": round(s["total_s"], 6),
                           "max_s": round(s["max_s"], 6)}
                    for name, s in holds.items()
                }
            contended = sanitizer.contention_counts()
            if contended:
                out["lock_contended"] = contended
        return out


__all__ = ["ServingMetrics"]
