"""``chainermn_tpu.serving`` — continuous-batching inference over the
static KV-cache decode path.

The training side of the framework ends at offline decoding
(:func:`chainermn_tpu.models.generate`: one fixed batch, start to finish).
This package is the traffic-facing counterpart — the ROADMAP's
"serving heavy traffic" axis — built from four layers:

- :class:`~chainermn_tpu.serving.engine.ServingEngine` — mechanism: a
  fixed pool of cache slots in one persistent static-shape KV cache, two
  compiled programs (per-slot ``prefill``, all-slots ``decode_step``),
  zero recompiles after warmup, tensor-parallel via ``comm.shard_map``;
- :class:`~chainermn_tpu.serving.scheduler.FCFSScheduler` — policy: FCFS
  admission into freed slots between decode steps, request state machine,
  EOS/length retirement, cancellation;
- :class:`~chainermn_tpu.serving.metrics.ServingMetrics` — observability:
  TTFT/TPOT percentiles, tokens/s, queue depth, slot occupancy (the same
  reporting convention as ``extensions.StepTimer``);
- :class:`~chainermn_tpu.serving.client.ServingClient` — the in-process
  front: background engine thread, blocking and per-token streaming APIs.

Correctness invariant (pinned in ``tests/serving_tests``): requests
admitted at staggered times into the shared slot pool produce
token-for-token the same outputs as isolated ``generate()`` calls with
the same params and rng.
"""

from chainermn_tpu.serving.client import ServingClient
from chainermn_tpu.serving.engine import ServingEngine
from chainermn_tpu.serving.metrics import ServingMetrics
from chainermn_tpu.serving.scheduler import (
    DeadlineExceededError,
    EngineFailed,
    FCFSScheduler,
    QueueFullError,
    Request,
    RequestState,
)

__all__ = [
    "DeadlineExceededError",
    "EngineFailed",
    "FCFSScheduler",
    "QueueFullError",
    "Request",
    "RequestState",
    "ServingClient",
    "ServingEngine",
    "ServingMetrics",
]
