"""``chainermn_tpu.serving`` — continuous-batching inference over the
static KV-cache decode path.

The training side of the framework ends at offline decoding
(:func:`chainermn_tpu.models.generate`: one fixed batch, start to finish).
This package is the traffic-facing counterpart — the ROADMAP's
"serving heavy traffic" axis — built from four layers:

- :class:`~chainermn_tpu.serving.engine.ServingEngine` — mechanism: a
  fixed pool of cache slots in one persistent static-shape KV cache, a
  small fixed family of compiled programs (bucketed batched ``prefill``
  — one program per padded-length bucket admitting up to
  ``prefill_batch`` requests per call — the all-slots ``decode_step``,
  and the prefix-copy pair), zero recompiles after :meth:`warmup`,
  tensor-parallel via ``comm.shard_map``;
- :class:`~chainermn_tpu.serving.prefix_cache.PrefixCacheIndex` — prefix
  KV reuse: a host-side ref-counted trie over token blocks backed by a
  device block store; on admission the longest cached prefix is copied
  slot-locally and only the uncached suffix prefills (LRU eviction on
  ref-zero leaves). With ``ServingEngine(paged=True)`` the SAME store
  becomes the single KV substrate (:class:`~chainermn_tpu.serving.
  prefix_cache.BlockPool`): decode slots address it through block
  tables, hits are zero-copy shared entries, and admission is budgeted
  in blocks instead of worst-case slot regions;
- :class:`~chainermn_tpu.serving.scheduler.FCFSScheduler` — policy: FCFS
  admission into freed slots between decode steps (cost-aware grouping:
  same-bucket batches preferring shared cached prefixes, bounded prefill
  interleave per decode step), request state machine, EOS/length
  retirement, cancellation;
- :class:`~chainermn_tpu.serving.metrics.ServingMetrics` — observability:
  TTFT/TPOT percentiles, tokens/s, queue depth, slot occupancy (the same
  reporting convention as ``extensions.StepTimer``);
- :class:`~chainermn_tpu.serving.client.ServingClient` — the in-process
  front: background engine thread, blocking and per-token streaming APIs.

Correctness invariant (pinned in ``tests/serving_tests``): requests
admitted at staggered times into the shared slot pool produce
token-for-token the same outputs as isolated ``generate()`` calls with
the same params and rng.

Everything here is ONE engine — one slot pool, one mesh, one failure
domain. The multi-replica tier (N engines behind a prefix-affinity,
occupancy-aware router with replica-level failover) is
:mod:`chainermn_tpu.fleet`, which drives these classes unchanged.
"""

from chainermn_tpu.serving.client import ServingClient
from chainermn_tpu.serving.engine import (
    AdmitPlan,
    EngineStateError,
    ServingEngine,
)
from chainermn_tpu.serving.fairness import (
    BrownoutPolicy,
    FairAdmission,
)
from chainermn_tpu.serving.metrics import ServingMetrics
from chainermn_tpu.serving.prefix_cache import (
    BlockPool,
    PrefixCacheIndex,
    PrefixMatch,
)
from chainermn_tpu.serving.scheduler import (
    DeadlineExceededError,
    EngineFailed,
    FCFSScheduler,
    QueueFullError,
    Request,
    RequestState,
)
from chainermn_tpu.serving.speculative import SpeculativeConfig

__all__ = [
    "AdmitPlan",
    "BlockPool",
    "BrownoutPolicy",
    "DeadlineExceededError",
    "EngineFailed",
    "EngineStateError",
    "FCFSScheduler",
    "FairAdmission",
    "PrefixCacheIndex",
    "PrefixMatch",
    "QueueFullError",
    "Request",
    "RequestState",
    "ServingClient",
    "ServingEngine",
    "ServingMetrics",
    "SpeculativeConfig",
]
