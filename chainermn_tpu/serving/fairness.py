"""Overload-robustness policies: weighted-fair admission and brownout.

Two policy objects the scheduler (and fleet controller) consult, kept
deliberately mechanism-free — they *pick* and *gate*, the scheduler
*acts*:

- :class:`FairAdmission` — priority-class + weighted deficit-round-robin
  tenant selection over the scheduler's existing FIFO queue.  ``batch``
  requests are only eligible once every ``interactive`` request is
  drained; within a class, tenants take turns by DRR over token budgets
  (cost = prompt tokens + requested new tokens), with each tenant's
  quantum scaled by an *effective weight*: its configured base weight
  shrunk by its measured device-second share (the PR 17 ``CostLedger``
  feed), so a noisy neighbor's overconsumption directly shrinks its
  admission share.  Selection only reorders *admission*; a request's
  token stream is a pure function of (prompt, rng), so replay parity is
  untouched.

- :class:`BrownoutPolicy` — a reversible, edge-triggered degradation
  ladder between "healthy" and "scale up".  Levels are cataloged and
  strictly ordered; each is entered/exited one step at a time under
  hysteresis and recorded as a ``brownout_step`` event plus the
  ``brownout_level`` gauge:

  == =======================  ==========================================
  L1 ``pause_batch``          stop admitting the batch class
  L2 ``single_token_decode``  drop decode_window / speculative k to the
                              always-warmed single-token decode step
                              (no recompile: ``warmup`` always traces it)
  L3 ``max_new_cap``          tighten the effective max_new_tokens
                              ceiling for in-flight + future requests
  L4 ``shed_lowest_tenant``   shed the lowest-effective-weight tenant's
                              queued work with a Retry-After hint
  == =======================  ==========================================

  The policy can self-drive from queue depth (:meth:`auto_observe`,
  scheduler-owned instances) or be stepped explicitly by the fleet
  controller (:meth:`step_up` / :meth:`step_down` /
  :meth:`relieve`) which supplies its own sensor hysteresis — a
  controller-owned policy is constructed with ``queue_high=None`` so
  exactly one party applies hysteresis.

Import-light on purpose: stdlib + sanitizer + monitor spine, no jax —
the fleet controller imports this module from a jax-free context.
"""

from __future__ import annotations

import time
from typing import Iterable, Mapping, Optional, Sequence

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry

#: The two admission classes. Anything else is rejected at submit().
PRIORITY_CLASSES = ("interactive", "batch")

#: Ladder actions by level (index 0 = healthy). Cataloged here so tests,
#: docs, and the controller name levels consistently.
BROWNOUT_LEVELS = (
    "healthy",
    "pause_batch",
    "single_token_decode",
    "max_new_cap",
    "shed_lowest_tenant",
)


def request_cost(req) -> float:
    """DRR cost of admitting ``req``: prompt tokens + requested budget.

    Charged up front — admission is what reserves slot + KV capacity,
    and the reservation is sized by max_new_tokens, not by what the
    request eventually uses."""
    return float(len(req.prompt) + int(req.max_new_tokens))


class FairAdmission:
    """Weighted deficit-round-robin head selection over a FIFO queue.

    Stateless with respect to the queue itself (the scheduler keeps its
    one guarded deque; this object only *picks* an element), stateful
    in the DRR sense: per-tenant deficit counters and the round-robin
    ring persist across calls so short requests from a light tenant
    interleave fairly with long requests from a heavy one.
    """

    def __init__(self, *, tenant_weights: Optional[Mapping] = None,
                 quantum_tokens: float = 32.0,
                 share_floor: float = 0.05) -> None:
        self._lock = sanitizer.make_lock("FairAdmission._lock", leaf=True)
        self._quantum = float(quantum_tokens)
        self._floor = float(share_floor)
        with self._lock:
            self._weights = dict(tenant_weights or {})
            self._shares: dict = {}      # tenant -> device-second fraction
            self._deficit: dict = {}     # tenant -> accumulated tokens
            self._ring: list = []        # tenants in first-seen order
            self._last_served: Optional[str] = None

    # -- weight / share feeds ------------------------------------------
    def set_weight(self, tenant: str, weight: float) -> None:
        with self._lock:
            self._weights[str(tenant)] = float(weight)

    def set_shares(self, device_seconds: Mapping) -> None:
        """Feed measured per-tenant device-seconds (CostLedger
        ``tenant_device_seconds()``); normalized to fractions here."""
        total = float(sum(device_seconds.values()))
        with self._lock:
            if total <= 0.0:
                self._shares = {}
            else:
                self._shares = {str(t): float(v) / total
                                for t, v in device_seconds.items()}

    def base_weight(self, tenant: str) -> float:
        with self._lock:
            return float(self._weights.get(tenant, 1.0))

    def tenant_share(self, tenant: str) -> float:
        with self._lock:
            return float(self._shares.get(tenant, 0.0))

    def effective_weight(self, tenant: str) -> float:
        """Base weight shrunk by measured consumption, floored so a
        dominant tenant is throttled, never starved."""
        with self._lock:
            return self._effective_locked(tenant)

    def _effective_locked(self, tenant: str) -> float:
        base = float(self._weights.get(tenant, 1.0))
        share = float(self._shares.get(tenant, 0.0))
        return base * max(self._floor, 1.0 - share)

    def lowest_weight_tenant(self, tenants: Iterable) -> Optional[str]:
        """The brownout L4 shed victim: lowest effective weight, ties
        broken by name for determinism."""
        with self._lock:
            pool = sorted(set(str(t) for t in tenants))
            if not pool:
                return None
            return min(pool, key=lambda t: (self._effective_locked(t), t))

    # -- selection ------------------------------------------------------
    def select(self, queue: Sequence, *, allow_batch: bool = True):
        """Pick the next request to admit from ``queue`` (not removed).

        Strict class order first — ``interactive`` before ``batch``,
        and ``batch`` only when ``allow_batch`` (brownout L1 clears it).
        Within the class, weighted DRR over the tenants with queued
        work: each pass tops every active tenant's deficit up by
        ``quantum * effective_weight`` and serves the first whose
        deficit covers its head-of-line cost. Returns ``None`` when
        nothing is eligible."""
        with self._lock:
            return self._select_locked(list(queue), allow_batch)

    def _select_locked(self, queue: list, allow_batch: bool):
        heads: dict = {}
        have_interactive = any(
            getattr(r, "priority", "interactive") != "batch"
            for r in queue)
        if not have_interactive and not allow_batch:
            return None
        want_batch = not have_interactive
        for req in queue:
            is_batch = getattr(req, "priority", "interactive") == "batch"
            if is_batch != want_batch:
                continue
            heads.setdefault(str(req.tenant), req)
        if not heads:
            return None

        # ring maintenance: first-seen order, idle tenants lose credit
        for t in heads:
            if t not in self._ring:
                self._ring.append(t)
        for t in list(self._deficit):
            if t not in heads:
                del self._deficit[t]

        active = [t for t in self._ring if t in heads]
        if self._last_served in active:
            i = active.index(self._last_served) + 1
            active = active[i:] + active[:i]
        if len(active) == 1:
            self._last_served = active[0]
            return heads[active[0]]

        rates = {t: self._quantum * self._effective_locked(t)
                 for t in active}
        max_cost = max(request_cost(heads[t]) for t in active)
        min_rate = max(1e-6, min(rates.values()))
        bound = int(max_cost / min_rate) + 2
        for _ in range(bound):
            for t in active:
                self._deficit[t] = self._deficit.get(t, 0.0) + rates[t]
                head = heads[t]
                if self._deficit[t] >= request_cost(head):
                    self._deficit[t] -= request_cost(head)
                    self._last_served = t
                    return head
        # unreachable by construction; fall back to arrival order
        oldest = min(heads.values(), key=lambda r: r.id)
        self._last_served = str(oldest.tenant)
        return oldest

    def to_json(self) -> dict:
        with self._lock:
            return {
                "weights": dict(self._weights),
                "shares": {t: round(v, 6) for t, v in self._shares.items()},
                "deficit": {t: round(v, 3)
                            for t, v in self._deficit.items()},
                "quantum_tokens": self._quantum,
                "share_floor": self._floor,
            }


class BrownoutPolicy:
    """The degradation ladder (see module docstring for the levels).

    Drives itself from queue depth when ``queue_high`` is set
    (scheduler-owned), or is stepped explicitly via ``step_up`` /
    ``step_down`` / ``relieve`` when ``queue_high`` is ``None``
    (controller-owned — the controller brings its own hysteresis).
    Every transition is edge-triggered: one ``brownout_step`` event per
    level change, gauge updated, never re-emitted while holding."""

    def __init__(self, *, max_level: int = 4,
                 queue_high: Optional[float] = 8.0,
                 up_after_s: float = 0.5, down_after_s: float = 2.0,
                 cooldown_s: float = 0.5,
                 max_new_cap: Optional[int] = 32,
                 labels: Optional[Mapping] = None) -> None:
        if not 1 <= int(max_level) <= len(BROWNOUT_LEVELS) - 1:
            raise ValueError(f"max_level must be 1..4, got {max_level}")
        self._lock = sanitizer.make_lock("BrownoutPolicy._lock", leaf=True)
        self.max_level = int(max_level)
        self.queue_high = None if queue_high is None else float(queue_high)
        self.up_after_s = float(up_after_s)
        self.down_after_s = float(down_after_s)
        self.cooldown_s = float(cooldown_s)
        self.max_new_cap = None if max_new_cap is None else int(max_new_cap)
        self._events = get_event_log()
        self._g_level = get_registry().gauge("brownout_level",
                                             dict(labels or {}))
        self._g_level.set(0)
        with self._lock:
            self._level = 0
            self._pressure_since: Optional[float] = None
            self._calm_since: Optional[float] = None
            self._last_change: Optional[float] = None
            self._steps = 0
            self._last_reason = ""

    # -- state reads (torn reads fine: single int) ---------------------
    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def pause_batch(self) -> bool:
        return self.level >= 1

    @property
    def force_single_token(self) -> bool:
        return self.level >= 2

    @property
    def effective_max_new_cap(self) -> Optional[int]:
        if self.level >= 3:
            return self.max_new_cap
        return None

    @property
    def shed_lowest(self) -> bool:
        return self.level >= 4 and self.max_level >= 4

    @property
    def saturated(self) -> bool:
        return self.level >= self.max_level

    # -- transitions ----------------------------------------------------
    def step_up(self, reason: str, now: Optional[float] = None) -> bool:
        """One level deeper into brownout; False when already saturated."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._level >= self.max_level:
                return False
            prev = self._level
            self._level += 1
            self._note_change_locked(now, reason)
            level = self._level
        self._emit_step(level, prev, "up", reason)
        return True

    def step_down(self, reason: str, now: Optional[float] = None) -> bool:
        """One level back toward healthy; False when already at 0."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._level <= 0:
                return False
            prev = self._level
            self._level -= 1
            self._note_change_locked(now, reason)
            level = self._level
        self._emit_step(level, prev, "down", reason)
        return True

    def relieve(self, reason: str = "capacity_arrived",
                now: Optional[float] = None) -> int:
        """Unwind the whole ladder (capacity arrived); returns the
        number of levels exited, one cataloged event each."""
        steps = 0
        while self.step_down(reason, now=now):
            steps += 1
        return steps

    def _note_change_locked(self, now: float, reason: str) -> None:
        self._last_change = now
        self._pressure_since = None
        self._calm_since = None
        self._steps += 1
        self._last_reason = str(reason)

    def _emit_step(self, level: int, prev: int, direction: str,
                   reason: str) -> None:
        self._g_level.set(level)
        self._events.emit("brownout_step", level=level, prev=prev,
                          direction=direction,
                          action=BROWNOUT_LEVELS[max(level, prev)],
                          reason=str(reason))

    # -- self-driving hysteresis ---------------------------------------
    def auto_observe(self, queue_depth: float,
                     now: Optional[float] = None) -> None:
        """Scheduler-side drive: sustained queue pressure steps up,
        sustained calm steps down, one level per cooldown window. No-op
        for controller-owned policies (``queue_high is None``)."""
        if self.queue_high is None:
            return
        now = time.monotonic() if now is None else float(now)
        pressure = float(queue_depth) >= self.queue_high
        with self._lock:
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                sustained = now - self._pressure_since >= self.up_after_s
                cooled = (self._last_change is None
                          or now - self._last_change >= self.cooldown_s)
                go_up = sustained and cooled and self._level < self.max_level
            else:
                self._pressure_since = None
                if self._calm_since is None:
                    self._calm_since = now
                sustained = now - self._calm_since >= self.down_after_s
                go_up = False
                go_down = sustained and self._level > 0
        if pressure:
            if go_up:
                self.step_up(f"queue_depth>={self.queue_high:g}", now=now)
        elif go_down:
            self.step_down("queue_drained", now=now)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "action": BROWNOUT_LEVELS[self._level],
                "max_level": self.max_level,
                "pause_batch": self._level >= 1,
                "force_single_token": self._level >= 2,
                "max_new_cap": (self.max_new_cap
                                if self._level >= 3 else None),
                "steps": self._steps,
                "last_reason": self._last_reason,
            }


__all__ = [
    "BROWNOUT_LEVELS",
    "BrownoutPolicy",
    "FairAdmission",
    "PRIORITY_CLASSES",
    "request_cost",
]
