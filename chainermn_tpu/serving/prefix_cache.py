"""Host-side ref-counted prefix index for KV reuse across requests.

Continuous batching (PR 1) made *decode* cheap — one compiled call advances
every slot — but admission still pays one full-length prefill per request,
even when ten queued prompts share the same system-prompt prefix. This
module is the host half of closing that gap (the vLLM/SGLang-style prefix
cache): a trie over fixed-size token *blocks* whose nodes own block slots
in a device-side KV store (the engine's ``[n_blocks, block_size, heads,
d_head]`` buffers per layer). On admission the scheduler asks for the
longest cached prefix; the engine copies the matched blocks slot-locally
with a compiled-once gather program and prefills only the uncached suffix.

Design points:

- **Block granularity.** A node caches exactly ``block_size`` tokens, so
  matches are multiples of ``block_size`` and the device copy programs have
  static shapes (one executable each, ever). A prompt inserts only its
  *full* blocks; the ragged tail is never cached.
- **Ref-counting.** ``match`` pins the matched chain (tail refcount +1)
  until the engine has copied the blocks into the request's slot
  (``release``); ``plan_insert`` pins the attachment point until the copy
  commits or aborts. Eviction only ever takes *leaf* nodes with refcount
  zero, so a pinned tail protects its whole chain (ancestors have
  children) and an in-flight copy can never read a reused block.
- **LRU eviction.** When an insert needs more blocks than are free, the
  least-recently-used ref-zero leaves are evicted (hits refresh the whole
  matched path). Partial allocations are fine — caching a prompt's first
  few blocks is still useful.
- **Correctness rides on the engine's masking argument.** The copy
  programs move whole padded block spans; rows past the real prefix are
  garbage the causal position mask hides until the tenant's own
  prefill/decode overwrites them (see ``engine.py``'s module docstring).
  Token parity vs solo ``generate()`` is pinned in
  ``tests/serving_tests/test_prefix_cache.py``.

This module is **pure host state** (numpy + the monitor spine; no jax):
the trie, the block free-list, and the hit/eviction telemetry. The device
store and its copy programs live in :class:`~chainermn_tpu.serving.engine.
ServingEngine`, which drives this index through ``match`` / ``release`` /
``plan_insert`` / ``commit_insert`` / ``abort_insert`` from the single
scheduler thread (this class is intentionally not thread-safe).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from chainermn_tpu.monitor._state import get_event_log, get_registry


class _Node:
    """One cached block: ``block_size`` tokens -> one device store block."""

    __slots__ = ("key", "block", "parent", "children", "refs", "last_use")

    def __init__(self, key, block, parent):
        self.key = key            # tuple of block_size token ints
        self.block = block        # index into the device block store
        self.parent = parent
        self.children: dict = {}
        self.refs = 0             # active matches/insert-plans pinning here
        self.last_use = 0


@dataclass
class PrefixMatch:
    """A pinned longest-cached-prefix result. ``length`` tokens
    (= ``len(block_ids) * block_size``) of the prompt are covered by
    ``block_ids`` in the device store; the holder must ``release()`` it
    back to the index once the blocks have been copied slot-locally."""

    nodes: list
    length: int
    block_ids: list
    released: bool = False


@dataclass
class InsertPlan:
    """Blocks allocated for a pending insert (device copy not yet done).
    ``start_block`` is the first NEW block's index within the prompt —
    blocks before it were already cached; ``row_starts`` are the matching
    slot-cache row offsets the engine's insert program copies from.
    ``commit`` links the nodes; ``abort`` returns the blocks to the free
    list."""

    parent: object
    keys: list
    block_ids: list
    start_block: int
    row_starts: list = field(default_factory=list)
    closed: bool = False


class PrefixCacheIndex:
    """Ref-counted trie over token blocks mapping prefixes to device KV
    block ids (module docstring). Drive from ONE thread (the scheduler's).

    Parameters
    ----------
    n_blocks : total block slots in the device store (capacity).
    block_size : tokens per block; matches/inserts are multiples of this.
    """

    def __init__(self, n_blocks: int, block_size: int) -> None:
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self._root = _Node(None, -1, None)
        self._free = list(range(self.n_blocks - 1, -1, -1))  # pop() -> 0, 1, ...
        self._clock = itertools.count(1)
        self._events = get_event_log()
        reg = get_registry()
        self._c_hits = reg.counter("prefix_cache_hits_total")
        self._c_misses = reg.counter("prefix_cache_misses_total")
        self._c_evictions = reg.counter("prefix_cache_evictions_total")
        self._c_inserted = reg.counter("prefix_cache_inserted_blocks_total")
        # per-instance stats (the registry counters are process-cumulative;
        # tests and bench want THIS cache's numbers)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.inserted_blocks = 0

    # ------------------------------------------------------------------ #
    # lookup                                                              #
    # ------------------------------------------------------------------ #

    def _key(self, tokens: np.ndarray, i: int) -> tuple:
        bs = self.block_size
        return tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def match(self, tokens, max_blocks: Optional[int] = None
              ) -> Optional[PrefixMatch]:
        """Longest cached prefix of ``tokens``, pinned; ``None`` on miss.

        The match never covers the whole prompt (at most
        ``(len - 1) // block_size`` blocks): at least one real token must
        remain for the suffix prefill to produce the first sampled token's
        logits — the same trick vLLM uses. ``max_blocks`` caps further
        (the engine shrinks matches that would not leave room for a
        prefill bucket inside ``cache_len``)."""
        tokens = np.asarray(tokens).reshape(-1)
        cap = (len(tokens) - 1) // self.block_size
        if max_blocks is not None:
            cap = min(cap, max_blocks)
        node, nodes = self._root, []
        for i in range(cap):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            nodes.append(child)
            node = child
        if not nodes:
            self.misses += 1
            self._c_misses.inc()
            return None
        nodes[-1].refs += 1
        t = next(self._clock)
        for nd in nodes:
            nd.last_use = t
        self.hits += 1
        self._c_hits.inc()
        return PrefixMatch(nodes=nodes,
                           length=len(nodes) * self.block_size,
                           block_ids=[nd.block for nd in nodes])

    def missing_blocks(self, tokens) -> int:
        """How many of ``tokens``' full blocks are NOT yet cached — the
        engine's insert cost/benefit probe (no allocation, no pinning, no
        LRU touch)."""
        tokens = np.asarray(tokens).reshape(-1)
        total = len(tokens) // self.block_size
        node, i = self._root, 0
        while i < total:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            node, i = child, i + 1
        return total - i

    def release(self, match: PrefixMatch) -> None:
        """Unpin a match (idempotent) — its blocks become evictable again
        once no other holder pins them."""
        if match is None or match.released:
            return
        match.released = True
        match.nodes[-1].refs -= 1

    # ------------------------------------------------------------------ #
    # insertion                                                           #
    # ------------------------------------------------------------------ #

    def plan_insert(self, tokens) -> Optional[InsertPlan]:
        """Allocate blocks for the not-yet-cached full blocks of
        ``tokens`` (evicting LRU ref-zero leaves as needed) and pin the
        attachment node. Returns ``None`` when nothing new would be cached
        (already present, no full block, or zero blocks allocatable). The
        caller copies KV device-side then ``commit_insert``s (or
        ``abort_insert``s on failure)."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        total = len(tokens) // bs
        node, i = self._root, 0
        t = next(self._clock)
        while i < total:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            child.last_use = t
            node, i = child, i + 1
        if i >= total:
            return None
        node.refs += 1                    # pin the attachment point
        blocks = self._alloc(total - i)
        if not blocks:
            node.refs -= 1
            return None
        return InsertPlan(
            parent=node,
            keys=[self._key(tokens, i + j) for j in range(len(blocks))],
            block_ids=blocks, start_block=i,
            row_starts=[(i + j) * bs for j in range(len(blocks))],
        )

    def commit_insert(self, plan: InsertPlan) -> None:
        if plan.closed:
            return
        plan.closed = True
        node = plan.parent
        node.refs -= 1
        t = next(self._clock)
        for key, block in zip(plan.keys, plan.block_ids):
            child = _Node(key, block, node)
            child.last_use = t
            node.children[key] = child
            node = child
        n = len(plan.block_ids)
        self.inserted_blocks += n
        self._c_inserted.inc(n)
        self._events.emit("prefix_insert", blocks=n,
                          depth=plan.start_block + n,
                          used=self.used_blocks)

    def abort_insert(self, plan: InsertPlan) -> None:
        if plan.closed:
            return
        plan.closed = True
        plan.parent.refs -= 1
        self._free.extend(plan.block_ids)

    # ------------------------------------------------------------------ #
    # eviction / capacity                                                 #
    # ------------------------------------------------------------------ #

    def _evictable(self):
        """All ref-zero leaves (iterative walk; the store is small)."""
        out, stack = [], [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and not node.children and not node.refs:
                out.append(node)
        return out

    def _alloc(self, n: int) -> list:
        out = []
        while len(out) < n:
            if self._free:
                out.append(self._free.pop())
                continue
            victims = self._evictable()
            if not victims:
                break                      # partial allocation is fine
            victim = min(victims, key=lambda nd: nd.last_use)
            del victim.parent.children[victim.key]
            self._free.append(victim.block)
            self.evictions += 1
            self._c_evictions.inc()
            self._events.emit("prefix_evict", block=victim.block,
                              age=victim.last_use)
        return out

    def clear(self) -> None:
        """Drop every cached prefix and free every block — the engine
        calls this from ``restart()`` together with rebuilding the device
        store, because a trie naming blocks of a discarded store would
        hand out KV that no longer exists."""
        self._root = _Node(None, -1, None)
        self._free = list(range(self.n_blocks - 1, -1, -1))

    # ------------------------------------------------------------------ #
    # stats                                                               #
    # ------------------------------------------------------------------ #

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate, 4),
            "evictions": self.evictions,
            "inserted_blocks": self.inserted_blocks,
            "used_blocks": self.used_blocks,
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
        }


__all__ = ["InsertPlan", "PrefixCacheIndex", "PrefixMatch"]
