"""Host-side ref-counted prefix index for KV reuse across requests.

Continuous batching (PR 1) made *decode* cheap — one compiled call advances
every slot — but admission still pays one full-length prefill per request,
even when ten queued prompts share the same system-prompt prefix. This
module is the host half of closing that gap (the vLLM/SGLang-style prefix
cache): a trie over fixed-size token *blocks* whose nodes own block slots
in a device-side KV store (the engine's ``[n_blocks, block_size, heads,
d_head]`` buffers per layer). On admission the scheduler asks for the
longest cached prefix; the engine either copies the matched blocks
slot-locally (the legacy dense path's compiled gather) or — in **paged**
mode — simply references them from the request's block table (sharing,
no copy), and prefills only the uncached suffix.

Design points:

- **Block granularity.** A node caches exactly ``block_size`` tokens, so
  matches are multiples of ``block_size`` and the device copy programs have
  static shapes (one executable each, ever). A prompt inserts only its
  *full* blocks; the ragged tail is never cached.
- **Ref-counting, two levels.** Trie-level pins (``_Node.refs``): ``match``
  pins the matched chain (tail refcount +1) until the holder is done with
  it (``release``); ``plan_insert`` pins the attachment point until the
  copy commits or aborts. Eviction only ever takes *leaf* nodes with
  refcount zero, so a pinned tail protects its whole chain. Pool-level
  refcounts (:class:`BlockPool`): each holder of a block — the trie node,
  and in paged mode every decode slot whose table references it — holds
  one reference; a block returns to the free list only at refcount zero,
  so evicting a trie node while a slot still reads its block merely
  *defers* the free until that slot retires.
- **LRU eviction.** When an insert needs more blocks than are free, the
  least-recently-used ref-zero leaves are evicted (hits refresh the whole
  matched path). Partial allocations are fine — caching a prompt's first
  few blocks is still useful.
- **Shared-pool (paged) mode.** Pass ``pool=`` to make the trie allocate
  from the same :class:`BlockPool` the engine's decode slots draw from:
  inserts then *adopt* a slot's already-resident blocks
  (:meth:`insert_shared` — zero device copies), and
  :meth:`evictable_blocks` tells the scheduler how many blocks an
  admission could reclaim on top of the free list.
- **Correctness rides on the engine's masking argument.** Copied or
  shared block spans may carry garbage rows past the real prefix; the
  causal position mask hides them until the tenant's own prefill/decode
  overwrites them (see ``engine.py``'s module docstring). Token parity vs
  solo ``generate()`` is pinned in ``tests/serving_tests``.

This module is **pure host state** (numpy + the monitor spine; no jax):
the trie, the block pool, and the hit/eviction telemetry. The device
store and its programs live in :class:`~chainermn_tpu.serving.engine.
ServingEngine`, which drives this index through ``match`` / ``release`` /
``plan_insert`` / ``commit_insert`` / ``abort_insert`` /
``insert_shared`` from the single scheduler thread (this class is
intentionally not thread-safe).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry


class BlockPool:
    """Ref-counted allocator over the device block store's slots (host
    bookkeeping only — the arrays live in the engine).

    ``reserve_scratch=True`` pins block 0 as the **scratch block**: never
    allocated, the well-known target for writes that must land nowhere
    (inactive batch rows, positions beyond a slot's allocated span). The
    paged engine points every unused block-table entry at it.

    A block is *allocated* with refcount 1 (:meth:`alloc`); additional
    holders :meth:`incref`, and :meth:`decref` returns it to the free
    list only when the last holder lets go — which is what lets a trie
    eviction and a decode slot disagree about a block's lifetime without
    ever handing out KV that someone still reads."""

    def __init__(self, n_blocks: int, *, reserve_scratch: bool = False):
        lo = 1 if reserve_scratch else 0
        if n_blocks < lo + 1:
            raise ValueError(
                f"n_blocks must be >= {lo + 1}, got {n_blocks}")
        self.n_blocks = int(n_blocks)
        self.scratch: Optional[int] = 0 if reserve_scratch else None
        self._lo = lo
        self._free = list(range(self.n_blocks - 1, lo - 1, -1))
        self._refs = np.zeros(self.n_blocks, np.int64)
        # single-writer contract, enforced at runtime: two threads
        # observed inside a mutator concurrently raise GuardViolation
        self._mut = sanitizer.mutation_guard("BlockPool")

    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the scratch block)."""
        return self.n_blocks - self._lo

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    def refs(self, block: int) -> int:
        return int(self._refs[block])

    def alloc(self) -> Optional[int]:
        """One free block at refcount 1, or ``None`` when the pool is dry
        (the caller may then evict trie leaves and retry)."""
        with self._mut:
            if not self._free:
                return None
            block = self._free.pop()
            self._refs[block] = 1
            return block

    def incref(self, block: int) -> None:
        with self._mut:
            self._refs[block] += 1

    def decref(self, block: int) -> None:
        with self._mut:
            self._refs[block] -= 1
            if self._refs[block] == 0:
                self._free.append(block)
            elif self._refs[block] < 0:
                raise RuntimeError(
                    f"block {block} over-released (refcount went negative)")

    def reset(self) -> None:
        """Everything free, all refcounts dropped — the engine's warm
        ``restart()`` path (device store is rebuilt alongside)."""
        with self._mut:
            self._free = list(range(self.n_blocks - 1, self._lo - 1, -1))
            self._refs[:] = 0


class _Node:
    """One cached block: ``block_size`` tokens -> one device store block."""

    __slots__ = ("key", "block", "parent", "children", "refs", "last_use")

    def __init__(self, key, block, parent):
        self.key = key            # tuple of block_size token ints
        self.block = block        # index into the device block store
        self.parent = parent
        self.children: dict = {}
        self.refs = 0             # active matches/insert-plans pinning here
        self.last_use = 0


@dataclass
class PrefixMatch:
    """A pinned longest-cached-prefix result. ``length`` tokens
    (= ``len(block_ids) * block_size``) of the prompt are covered by
    ``block_ids`` in the device store; the holder must ``release()`` it
    back to the index once the blocks have been copied slot-locally (or,
    paged mode, referenced from the slot's table)."""

    nodes: list
    length: int
    block_ids: list
    released: bool = False


@dataclass
class InsertPlan:
    """Blocks allocated for a pending insert (device copy not yet done).
    ``start_block`` is the first NEW block's index within the prompt —
    blocks before it were already cached; ``row_starts`` are the matching
    slot-cache row offsets the engine's insert program copies from.
    ``commit`` links the nodes; ``abort`` returns the blocks to the free
    list."""

    parent: object
    keys: list
    block_ids: list
    start_block: int
    row_starts: list = field(default_factory=list)
    closed: bool = False


class PrefixCacheIndex:
    """Ref-counted trie over token blocks mapping prefixes to device KV
    block ids (module docstring). Drive from ONE thread (the scheduler's).

    Parameters
    ----------
    n_blocks : total block slots in the device store (capacity). Ignored
        when ``pool`` is given (the pool already knows).
    block_size : tokens per block; matches/inserts are multiples of this.
    pool : optional shared :class:`BlockPool` — paged mode, where decode
        slots and the trie draw from one store. Default: a private pool
        of ``n_blocks`` (the legacy dense-engine configuration).
    """

    def __init__(self, n_blocks: int, block_size: int,
                 pool: Optional[BlockPool] = None) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if pool is None:
            if n_blocks < 1:
                raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
            pool = BlockPool(n_blocks)
            self._pool_private = True
        else:
            self._pool_private = False
        self.pool = pool
        self.n_blocks = pool.n_blocks
        self.block_size = int(block_size)
        self._root = _Node(None, -1, None)
        self._clock = itertools.count(1)
        # single-writer contract (same as BlockPool): the scheduler
        # thread owns all trie mutation; enforced when the sanitizer is on
        self._mut = sanitizer.mutation_guard("PrefixCacheIndex")
        self._events = get_event_log()
        reg = get_registry()
        self._c_hits = reg.counter("prefix_cache_hits_total")
        self._c_misses = reg.counter("prefix_cache_misses_total")
        self._c_evictions = reg.counter("prefix_cache_evictions_total")
        self._c_inserted = reg.counter("prefix_cache_inserted_blocks_total")
        # per-instance stats (the registry counters are process-cumulative;
        # tests and bench want THIS cache's numbers)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.inserted_blocks = 0

    # ------------------------------------------------------------------ #
    # lookup                                                              #
    # ------------------------------------------------------------------ #

    def _key(self, tokens: np.ndarray, i: int) -> tuple:
        bs = self.block_size
        return tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def match(self, tokens, max_blocks: Optional[int] = None
              ) -> Optional[PrefixMatch]:
        """Longest cached prefix of ``tokens``, pinned; ``None`` on miss.

        The match never covers the whole prompt (at most
        ``(len - 1) // block_size`` blocks): at least one real token must
        remain for the suffix prefill to produce the first sampled token's
        logits — the same trick vLLM uses. ``max_blocks`` caps further
        (the engine shrinks matches that would not leave room for a
        prefill bucket inside ``cache_len``)."""
        tokens = np.asarray(tokens).reshape(-1)
        cap = (len(tokens) - 1) // self.block_size
        if max_blocks is not None:
            cap = min(cap, max_blocks)
        with self._mut:
            node, nodes = self._root, []
            for i in range(cap):
                child = node.children.get(self._key(tokens, i))
                if child is None:
                    break
                nodes.append(child)
                node = child
            if not nodes:
                self.misses += 1
                self._c_misses.inc()
                return None
            nodes[-1].refs += 1
            t = next(self._clock)
            for nd in nodes:
                nd.last_use = t
            self.hits += 1
            self._c_hits.inc()
            return PrefixMatch(nodes=nodes,
                               length=len(nodes) * self.block_size,
                               block_ids=[nd.block for nd in nodes])

    def missing_blocks(self, tokens) -> int:
        """How many of ``tokens``' full blocks are NOT yet cached — the
        engine's insert cost/benefit probe (no allocation, no pinning, no
        LRU touch)."""
        tokens = np.asarray(tokens).reshape(-1)
        total = len(tokens) // self.block_size
        node, i = self._root, 0
        while i < total:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            node, i = child, i + 1
        return total - i

    def ngram_continuation(self, tokens, k: int) -> Optional[list]:
        """Model-free continuation probe for the speculative n-gram
        drafter: if ``tokens`` walks the trie cleanly — every full block
        present, and the ragged tail a prefix of exactly ONE child key —
        propose up to ``k`` of the tokens a cached prompt says come next
        (the tail key's remainder, then deeper blocks while the path
        stays unambiguous). Returns ``None`` when the trie has no
        unambiguous opinion.

        Read-only on purpose: no pins, no LRU touch, no hit/miss
        counting — a probe must never change eviction order or skew the
        admission-path hit rate. Staleness is harmless: the result is a
        *draft*, and the target-model verify step rejects anything the
        real distribution disagrees with."""
        if k <= 0:
            return None
        tokens = np.asarray(tokens, np.int64).reshape(-1)
        bs = self.block_size
        node = self._root
        for i in range(len(tokens) // bs):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                return None
            node = child
        tail = tuple(int(t) for t in tokens[(len(tokens) // bs) * bs:])
        out: list = []
        if tail:
            matches = [key for key in node.children
                       if key[: len(tail)] == tail]
            if len(matches) != 1:
                return None
            key = matches[0]
            out.extend(key[len(tail):])
            node = node.children[key]
        while len(out) < k and len(node.children) == 1:
            (key, node), = node.children.items()
            out.extend(key)
        return out[:k] if out else None

    def release(self, match: PrefixMatch) -> None:
        """Unpin a match (idempotent) — its blocks become evictable again
        once no other holder pins them."""
        if match is None or match.released:
            return
        with self._mut:
            match.released = True
            match.nodes[-1].refs -= 1

    # ------------------------------------------------------------------ #
    # insertion                                                           #
    # ------------------------------------------------------------------ #

    def plan_insert(self, tokens) -> Optional[InsertPlan]:
        """Allocate blocks for the not-yet-cached full blocks of
        ``tokens`` (evicting LRU ref-zero leaves as needed) and pin the
        attachment node. Returns ``None`` when nothing new would be cached
        (already present, no full block, or zero blocks allocatable). The
        caller copies KV device-side then ``commit_insert``s (or
        ``abort_insert``s on failure)."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        total = len(tokens) // bs
        with self._mut:
            node, i = self._root, 0
            t = next(self._clock)
            while i < total:
                child = node.children.get(self._key(tokens, i))
                if child is None:
                    break
                child.last_use = t
                node, i = child, i + 1
            if i >= total:
                return None
            node.refs += 1                # pin the attachment point
            blocks = self.alloc_blocks(total - i)
            if not blocks:
                node.refs -= 1
                return None
        return InsertPlan(
            parent=node,
            keys=[self._key(tokens, i + j) for j in range(len(blocks))],
            block_ids=blocks, start_block=i,
            row_starts=[(i + j) * bs for j in range(len(blocks))],
        )

    def commit_insert(self, plan: InsertPlan) -> None:
        if plan.closed:
            return
        with self._mut:
            plan.closed = True
            node = plan.parent
            node.refs -= 1
            t = next(self._clock)
            for key, block in zip(plan.keys, plan.block_ids):
                child = _Node(key, block, node)
                child.last_use = t
                node.children[key] = child
                node = child
            n = len(plan.block_ids)
            self.inserted_blocks += n
        self._c_inserted.inc(n)
        self._events.emit("prefix_insert", blocks=n,
                          depth=plan.start_block + n,
                          used=self.used_blocks)

    def abort_insert(self, plan: InsertPlan) -> None:
        if plan.closed:
            return
        with self._mut:
            plan.closed = True
            plan.parent.refs -= 1
            for block in plan.block_ids:
                self.pool.decref(block)

    def insert_shared(self, tokens, block_ids) -> int:
        """Paged-mode zero-copy insert: **adopt** already-resident blocks.
        ``block_ids[j]`` must hold the KV of the prompt's ``j``-th full
        block (a freshly prefilled slot's table entries do, by
        construction). Links trie nodes for the not-yet-cached tail of
        full blocks, increfing each adopted block — the trie becomes a
        co-owner alongside the donor slot, and the block outlives the
        donor's retirement. No device work at all: under the unified
        store, caching a prefix IS bookkeeping. Returns blocks adopted."""
        tokens = np.asarray(tokens).reshape(-1)
        bs = self.block_size
        total = min(len(tokens) // bs, len(block_ids))
        with self._mut:
            node, i = self._root, 0
            t = next(self._clock)
            while i < total:
                child = node.children.get(self._key(tokens, i))
                if child is None:
                    break
                child.last_use = t
                node, i = child, i + 1
            adopted = 0
            for j in range(i, total):
                block = int(block_ids[j])
                self.pool.incref(block)
                child = _Node(self._key(tokens, j), block, node)
                child.last_use = t
                node.children[child.key] = child
                node = child
                adopted += 1
        if adopted:
            self.inserted_blocks += adopted
            self._c_inserted.inc(adopted)
            self._events.emit("prefix_insert", blocks=adopted, depth=total,
                              used=self.used_blocks, shared=True)
        return adopted

    # ------------------------------------------------------------------ #
    # eviction / capacity                                                 #
    # ------------------------------------------------------------------ #

    def _evictable(self):
        """All ref-zero leaves (iterative walk; the store is small)."""
        out, stack = [], [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and not node.children and not node.refs:
                out.append(node)
        return out

    def alloc_blocks(self, n: int) -> list:
        """Up to ``n`` blocks from the pool, evicting LRU ref-zero leaves
        when the free list runs dry (a partial result is fine). Shared by
        trie inserts and — paged mode — the engine's slot admissions and
        lazy block appends, so both compete under the same LRU policy."""
        out = []
        with self._mut:
            while len(out) < n:
                block = self.pool.alloc()
                if block is not None:
                    out.append(block)
                    continue
                victims = self._evictable()
                if not victims:
                    break                  # partial allocation is fine
                victim = min(victims, key=lambda nd: nd.last_use)
                del victim.parent.children[victim.key]
                # may not free the block immediately: a paged decode slot
                # still referencing it keeps it alive until that slot
                # retires
                self.pool.decref(victim.block)
                self.evictions += 1
                self._c_evictions.inc()
                self._events.emit("prefix_evict", block=victim.block,
                                  age=victim.last_use)
        return out

    # kept as the historical internal name (engine/test callers predate
    # the shared-pool refactor)
    _alloc = alloc_blocks

    def alloc_blocks_atomic(self, n: int) -> Optional[list]:
        """All-or-nothing :meth:`alloc_blocks`: exactly ``n`` blocks, or
        ``None`` with every partially-allocated block already returned to
        the pool. The KV-migration import and chunked-prefill staging
        paths allocate through this — both must leave the pool untouched
        on a shortfall, because their fallback (decode at the source /
        retry the admission next step) assumes nothing was consumed."""
        out = self.alloc_blocks(int(n))
        if len(out) < int(n):
            for block in out:
                self.pool.decref(block)
            return None
        return out

    def evictable_blocks(self) -> int:
        """How many blocks eviction could *actually return to the free
        list* right now: nodes in fully-unpinned subtrees whose block has
        no other holder (pool refcount 1). The scheduler's block-budget
        admission counts these on top of ``pool.free_blocks`` — a cached
        but idle prefix is reclaimable capacity, not spent capacity."""
        pool = self.pool

        def walk(node):
            unpinned = node is self._root or node.refs == 0
            count = 0
            for child in node.children.values():
                child_ok, child_count = walk(child)
                count += child_count
                unpinned = unpinned and child_ok
            if (node is not self._root and unpinned
                    and pool.refs(node.block) == 1):
                count += 1
            return unpinned, count

        return walk(self._root)[1]

    def clear(self) -> None:
        """Drop every cached prefix and release every trie-held block —
        the engine calls this from ``restart()`` together with rebuilding
        the device store, because a trie naming blocks of a discarded
        store would hand out KV that no longer exists. A private pool is
        reset wholesale (the legacy behavior — uncommitted plan blocks
        reclaimed too); a shared pool only gives back the trie's own
        references (the engine resets the pool itself after dropping the
        slot tables)."""
        with self._mut:
            self._root = _Node(None, -1, None)
            if self._pool_private:
                self.pool.reset()

    # ------------------------------------------------------------------ #
    # stats                                                               #
    # ------------------------------------------------------------------ #

    @property
    def used_blocks(self) -> int:
        """Allocated blocks in the pool. With a private pool this is the
        trie's own footprint (legacy meaning); with a shared pool it
        counts decode-slot blocks too (the whole store's occupancy)."""
        return self.pool.used_blocks

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate, 4),
            "evictions": self.evictions,
            "inserted_blocks": self.inserted_blocks,
            "used_blocks": self.used_blocks,
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
        }


__all__ = ["BlockPool", "InsertPlan", "PrefixCacheIndex", "PrefixMatch"]
