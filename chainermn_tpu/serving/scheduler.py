"""FCFS admission + request lifecycle over the slot-pool engine.

The engine (:mod:`chainermn_tpu.serving.engine`) is pure mechanism: it
advances whatever occupies its slots. This module is the policy layer — a
first-come-first-served queue whose requests move through

    QUEUED -> PREFILL -> DECODE -> DONE            (or CANCELLED)

One :meth:`FCFSScheduler.step` is one engine round: fill every freed slot
from the queue (one prefill each — prefill interleaves with decode at step
granularity, the classic continuous-batching schedule), advance all active
slots one token, deliver tokens to per-request streams, and retire slots
whose request hit EOS or its token budget. Retirement frees the slot for
the NEXT step's admissions, so the pool refills without ever waiting for
the whole batch to finish — the property that separates this from the
offline ``generate()`` path.

Thread model: ``submit``/``cancel`` are safe from any thread (they only
touch the locked queue and request state); ``step`` must be driven from
ONE thread — the engine's device state is not concurrent. The in-process
:class:`~chainermn_tpu.serving.client.ServingClient` owns that thread.
"""

from __future__ import annotations

import enum
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import numpy as np

from chainermn_tpu.monitor import annotate
from chainermn_tpu.monitor._state import get_event_log
from chainermn_tpu.serving.metrics import ServingMetrics


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    DONE = "done"
    CANCELLED = "cancelled"


@dataclass
class Request:
    """One inference request and its full lifecycle state. Created by
    :meth:`FCFSScheduler.submit`; treat as read-only outside the scheduler
    (``wait()``/``output`` are the consumer surface)."""

    prompt: np.ndarray
    max_new_tokens: int
    rng: object = None                 # per-request PRNG key (solo-parity)
    stream_cb: Optional[Callable[[int], None]] = None
    id: int = -1
    state: RequestState = RequestState.QUEUED
    slot: int = -1
    tokens: list = field(default_factory=list)
    error: Optional[BaseException] = None
    t_submit: float = 0.0
    t_last_token: float = 0.0
    _done: threading.Event = field(default_factory=threading.Event)

    @property
    def finished(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED)

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` tokens (the ``generate()``-shaped
        result, without its trailing pad)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until DONE/CANCELLED (or error); True if finished."""
        ok = self._done.wait(timeout)
        if self.error is not None:
            raise self.error
        return ok


class FCFSScheduler:
    """First-come-first-served continuous-batching scheduler.

    ``eos_id``: a request retires as soon as it samples this token (the
    EOS is kept as its last token — matching ``generate(eos_id=...)``,
    whose masked buffer holds the EOS then pads). Length retirement
    (``max_new_tokens``) applies either way. Both are host-side policy
    BETWEEN engine steps; inside the compiled programs shapes never
    change (see the engine's ``jnp.where`` masking).
    """

    def __init__(self, engine, *, eos_id: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None) -> None:
        self.engine = engine
        self.eos_id = eos_id
        self.metrics = metrics or ServingMetrics(engine.n_slots)
        self._events = get_event_log()
        self._queue: deque[Request] = deque()
        self._by_slot: dict[int, Request] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count()

    # ------------------------------------------------------------------ #
    # submission surface (any thread)                                     #
    # ------------------------------------------------------------------ #

    def submit(self, prompt, max_new_tokens: int, *, rng=None,
               stream_cb: Optional[Callable[[int], None]] = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.engine.validate_request(len(prompt), max_new_tokens)
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            stream_cb=stream_cb,
        )
        req.t_submit = time.perf_counter()
        with self._lock:
            req.id = next(self._ids)
            self._queue.append(req)
            self.metrics.record_submit()
        self._events.emit("submit", req=req.id, prompt_len=len(prompt),
                          max_new=int(max_new_tokens))
        return req

    def cancel(self, req: Request) -> bool:
        """Cancel a request: dequeued if still QUEUED, slot freed if
        decoding. False if it already finished."""
        with self._lock:
            if req.finished:
                return False
            if req.state is RequestState.QUEUED:
                try:
                    self._queue.remove(req)
                except ValueError:
                    return False
            elif req.slot >= 0:
                self.engine.release(req.slot)
                self._by_slot.pop(req.slot, None)
            # else: prefill in flight (no slot yet) — the step() admission
            # path sees the CANCELLED state and releases the slot itself
            req.state = RequestState.CANCELLED
            self.metrics.record_done(cancelled=True)
        self._events.emit("slot_retire", req=req.id, slot=req.slot,
                          reason="cancelled")
        req._done.set()
        return True

    @property
    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue) or bool(self._by_slot)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # ------------------------------------------------------------------ #
    # the scheduling loop (one driving thread)                            #
    # ------------------------------------------------------------------ #

    def step(self) -> int:
        """One continuous-batching round; returns tokens emitted (0 when
        idle). Admissions first — freed slots refill BEFORE the decode
        step, so a retirement's slot never sits idle for a step."""
        emitted = 0
        # 1. admission: one prefill per free slot, FCFS
        with annotate("chainermn.serving_admit"):
            while self.engine.free_slots:
                with self._lock:
                    if not self._queue:
                        break
                    req = self._queue.popleft()
                    req.state = RequestState.PREFILL
                slot, first = self.engine.prefill(req.prompt, req.rng)
                now = time.perf_counter()
                with self._lock:
                    if req.state is RequestState.CANCELLED:
                        # cancelled while its prefill was in flight (it had
                        # no slot yet, so cancel() left the release to us)
                        self.engine.release(slot)
                        continue
                    req.slot = slot
                    self._by_slot[slot] = req
                    req.state = RequestState.DECODE
                self._events.emit("slot_admit", req=req.id, slot=slot,
                                  prompt_len=len(req.prompt),
                                  queue_depth=self.queue_depth)
                self.metrics.record_first_token(req.t_submit, now,
                                                req_id=req.id)
                self._deliver(req, first, now)
                emitted += 1
        # 2. decode: every active slot, one token, one compiled call
        for slot, tok in self.engine.decode_step().items():
            req = self._by_slot.get(slot)
            if req is None:            # released mid-flight (cancelled)
                continue
            now = time.perf_counter()
            self.metrics.record_token(req.t_last_token, now)
            self._deliver(req, tok, now)
            emitted += 1
        self.metrics.record_step(self.queue_depth, self.engine.active_slots)
        return emitted

    def run_until_idle(self, max_steps: Optional[int] = None) -> int:
        """Drive ``step()`` until queue and slots drain; returns total
        tokens emitted. The offline convenience loop (tests, benchmarks);
        online serving drives ``step()`` from the client thread instead."""
        total = 0
        steps = 0
        while self.has_work:
            total += self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return total

    # ------------------------------------------------------------------ #
    # internals                                                           #
    # ------------------------------------------------------------------ #

    def _deliver(self, req: Request, tok: int, now: float) -> None:
        req.tokens.append(int(tok))
        req.t_last_token = now
        if req.stream_cb is not None:
            try:
                req.stream_cb(int(tok))
            except Exception:
                pass  # a consumer's callback must not kill the engine loop
        hit_eos = self.eos_id is not None and int(tok) == self.eos_id
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            self._retire(req, "eos" if hit_eos else "length")

    def _retire(self, req: Request, reason: str) -> None:
        with self._lock:
            if req.finished:   # a concurrent cancel() won the race
                return
            self.engine.release(req.slot)
            self._by_slot.pop(req.slot, None)
            req.state = RequestState.DONE
            self.metrics.record_done()
        self._events.emit("slot_retire", req=req.id, slot=req.slot,
                          reason=reason, tokens=len(req.tokens))
        req._done.set()


__all__ = ["FCFSScheduler", "Request", "RequestState"]
