"""FCFS admission + request lifecycle over the slot-pool engine.

The engine (:mod:`chainermn_tpu.serving.engine`) is pure mechanism: it
advances whatever occupies its slots. This module is the policy layer — a
first-come-first-served queue whose requests move through

    QUEUED -> PREFILL -> DECODE -> DONE      (or CANCELLED, or ERRORED)

One :meth:`FCFSScheduler.step` is one engine round: shed expired QUEUED
requests, fill freed slots from the queue (prefill interleaves with
decode at step granularity, the classic continuous-batching schedule),
advance all active slots one token, deliver tokens to per-request
streams, and retire slots whose request hit EOS or its token budget.
Retirement frees the slot for the NEXT step's admissions, so the pool
refills without ever waiting for the whole batch to finish — the property
that separates this from the offline ``generate()`` path.

Cost-aware admission (the PR-5 fast path): when the engine has batched
prefill, a bucket ladder, or the prefix cache enabled, admissions are
built as **groups** — the head of the queue anchors a group, the queue is
scanned for companions whose (prefix-discounted) padded suffix lands in
the SAME bucket, companions sharing the head's cached prefix are
preferred, and the whole group admits in ONE batched device call
(:meth:`ServingEngine.admit_batch`: per-member prefix fetch + one bucket
prefill). Decode stall is bounded: at most ``max_prefills_per_step``
prefill calls interleave per decode step (default 1 in cost-aware mode;
unbounded in the legacy single-request configuration, whose behavior —
including the ``serving.prefill`` fault cut-point and per-request retry —
is preserved exactly).

Block-budget admission (paged engines): when the engine runs the paged
KV store, a group member admits only if its WORST-CASE block growth
(``blocks_needed(prompt, max_new)``) fits ``free + evictable −
reserved`` — an unaffordable head is put back QUEUED (FCFS preserved)
instead of being allowed to starve mid-decode later. Before each decode
step the scheduler appends blocks for slots crossing block boundaries;
a genuinely dry pool (or an injected ``serving.kv_append`` fault)
preempts the LOWEST-priority (newest) request back to the queue — its
re-admission replays prompt+rng from scratch, reproducing the identical
token stream — rather than failing anyone or burning a restart.

Chunked prefill (PR 19): with ``chunk_tokens_per_step=N`` on a paged
engine, a long prompt whose suffix exceeds ``N`` tokens admits as a
**chunked** prefill instead of one monolithic device call — the engine
stages the slot (:meth:`ServingEngine.begin_chunked`) and the request
enters ``PREFILLING``; each subsequent step advances exactly ONE chunk
(:meth:`_advance_chunks`) through the same compiled bucket programs the
batched path uses (zero recompiles), interleaved with every decode step,
so a 1k-token prompt no longer stalls in-flight decodes for its whole
prefill. The final chunk samples with the request's own rng (one
admission split — token parity with the unchunked path and with a solo
``generate()``), commits the slot, and the request proceeds to DECODE
exactly as if it had admitted unchunked.

KV migration (disaggregated prefill/decode tiers): when a supervising
layer sets :attr:`migrate_cb`, a request that just completed its prefill
(chunked or not) is offered for handover — the slot's KV blocks are read
out host-side (:meth:`ServingEngine.export_slot_kv`) and the callback
decides placement. On ``True`` the SAME :class:`Request` object now
belongs to the destination scheduler (:meth:`enqueue_migrated` /
``_pending_imports``; its ``stream_cb``/trace/``_done`` ride along, so
consumers never notice the move) and the source frees the slot; on
``False`` — or any export/handshake failure — the request simply keeps
decoding in place. Never a lost request, by construction.

Graceful degradation (the resilience layer):

- **Bounded admission** — ``max_queue`` rejects overload at submit time
  with :class:`QueueFullError` instead of queueing unboundedly (the
  caller sees backpressure immediately; a shed deep in the queue later
  helps nobody).
- **Deadlines** — a request carrying ``deadline_s`` (or the scheduler's
  ``default_deadline_s``) that is still QUEUED past its deadline is shed:
  terminal ``ERRORED`` with a stored :class:`DeadlineExceededError`, so
  ``wait()`` raises instead of blocking on work that will never start.
- **Engine exception boundary** — a raised device call fails every
  in-flight request loudly (``ERRORED`` with the exception stored; no
  ``wait()`` ever hangs on a dead engine), then — ``restart_on_error``,
  the default — warm-restarts the engine (fresh caches and slot mirrors,
  SAME compiled programs) and keeps serving the queue. Restarts are
  bounded by ``max_restarts``; past the budget the exception propagates.
- **Admission retry** — an optional
  :class:`~chainermn_tpu.resilience.retry.RetryPolicy` around each
  prefill absorbs transient faults before they count as engine failures.

Every transition is observable: ``reject`` / ``shed`` / ``engine_error``
/ ``engine_restart`` events in the flight recorder and matching
``ServingMetrics`` registry counters. Every submission also opens a
request-scoped :class:`~chainermn_tpu.monitor.trace.Trace` that rides
the request end to end — ``queue`` (submit -> popped), ``admit`` (host
planning), ``prefill`` (the batched device call, attributed to every
group member with bucket/batch/cached labels), one ``decode_step`` span
per decode call it participates in, closed at retire/shed/error with the
reason. Shed and errored requests are retained regardless of the
tracer's sampling, lifecycle events carry ``trace=`` ids, the watchdog
window around every device call is labelled with the in-flight
request/trace ids, and each retired trace's critical-path breakdown
feeds ``ServingMetrics.report()["critical_path"]``.

Thread model: ``submit``/``cancel`` are safe from any thread (they only
touch the locked queue and request state); ``step`` must be driven from
ONE thread — the engine's device state is not concurrent. The in-process
:class:`~chainermn_tpu.serving.client.ServingClient` owns that thread.
"""

from __future__ import annotations

import enum
import itertools
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor import annotate
from chainermn_tpu.monitor._state import get_event_log
from chainermn_tpu.monitor.costs import CostLedger
from chainermn_tpu.monitor.trace import NULL_TRACE, get_tracer
from chainermn_tpu.resilience.cutpoints import SERVING_ADMIT_FAIR
from chainermn_tpu.resilience.faults import inject
from chainermn_tpu.resilience.retry import RetryPolicy
from chainermn_tpu.serving.engine import EngineStateError
from chainermn_tpu.serving.fairness import (
    BrownoutPolicy,
    FairAdmission,
    PRIORITY_CLASSES,
)
from chainermn_tpu.serving.metrics import ServingMetrics


class QueueFullError(RuntimeError):
    """Submission rejected: the bounded admission queue is at capacity.

    ``retry_after_s`` is the machine-readable backpressure hint (scaled
    by queue depth at rejection time) a well-behaved client should wait
    before retrying — the fleet edge surfaces it end to end."""

    def __init__(self, msg: str = "", *,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(TimeoutError):
    """The request spent its deadline queued (or decoding) and was shed.
    Carries the same structured ``retry_after_s`` hint as
    :class:`QueueFullError`."""

    def __init__(self, msg: str = "", *,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    PREFILLING = "prefilling"   # chunked prefill in progress (owns a slot)
    DECODE = "decode"
    DONE = "done"
    CANCELLED = "cancelled"
    ERRORED = "errored"


class EngineFailed(RuntimeError):
    """Stored on requests that were in flight when the engine raised (the
    original engine exception is the ``__cause__``)."""


@dataclass(eq=False)
class Request:
    """One inference request and its full lifecycle state. Created by
    :meth:`FCFSScheduler.submit`; treat as read-only outside the scheduler
    (``wait()``/``output``/``stream()`` are the consumer surface).

    ``eq=False``: requests compare by identity. The generated
    field-wise ``__eq__`` would compare ndarray prompts (ambiguous
    truth value) the moment ``deque.remove`` / ``in`` walks past a
    same-shape neighbor — fair admission removes mid-queue elements, so
    identity semantics are load-bearing, not just faster."""

    prompt: np.ndarray
    max_new_tokens: int
    rng: object = None                 # per-request PRNG key (solo-parity)
    stream_cb: Optional[Callable[[int], None]] = None
    # cost-attribution label (PR 17): rides the request end to end and
    # keys the ledger's per-tenant aggregates; with fair admission on it
    # also keys the DRR budget this request draws from
    tenant: str = "default"
    # admission class (PR 18): "interactive" admits first and is
    # preempted last; "batch" only admits once interactive is drained
    priority: str = "interactive"
    id: int = -1
    state: RequestState = RequestState.QUEUED
    slot: int = -1
    tokens: list = field(default_factory=list)
    error: Optional[BaseException] = None
    deadline_s: Optional[float] = None
    t_submit: float = 0.0
    t_deadline: Optional[float] = None
    t_last_token: float = 0.0
    # when the request last (re-)entered the queue — the cost ledger's
    # queue-wait clock, reset on preempt/defer (t_submit stays the TTFT
    # anchor and is never touched)
    _t_enqueue: float = 0.0
    # engine weight version this request decodes on, stamped at slot
    # commit (None until admitted, or on engines without versioning)
    weight_version: Optional[int] = None
    # request-scoped trace context: rides the request through queue ->
    # admit -> prefill -> decode -> retire (NULL_TRACE when tracing off)
    trace: object = NULL_TRACE
    _span_queue: object = None
    _span_admit: object = None
    _done: threading.Event = field(default_factory=threading.Event)

    @property
    def finished(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED,
                              RequestState.ERRORED)

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` tokens (the ``generate()``-shaped
        result, without its trailing pad). An ERRORED request re-raises
        its stored exception instead of returning a silent partial."""
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until DONE/CANCELLED/ERRORED; True if finished. An
        ERRORED request re-raises its stored exception in the caller."""
        ok = self._done.wait(timeout)
        if self.error is not None:
            raise self.error
        return ok

    def stream(self, poll_s: float = 0.01) -> Iterator[int]:
        """Yield generated tokens as they arrive; returns at a terminal
        state — re-raising the stored exception for ERRORED requests, so
        a streaming consumer hears about the failure instead of seeing a
        quietly truncated stream. (``tokens`` is append-only, so the
        index scan is safe against the engine thread.)"""
        i = 0
        while True:
            while i < len(self.tokens):
                yield self.tokens[i]
                i += 1
            if self._done.is_set():
                while i < len(self.tokens):
                    yield self.tokens[i]
                    i += 1
                if self.error is not None:
                    raise self.error
                return
            self._done.wait(poll_s)


class SwapTicket:
    """Handle for one pending weight swap (see
    :meth:`FCFSScheduler.request_swap`). ``wait()`` blocks until the
    scheduler's driving thread executed (or failed) the swap; ``result``
    holds the swap fn's return value, ``error`` the exception if it
    raised — a failed swap leaves the engine on its prior weights (the
    swap fn validates before assigning), so the ticket is the only place
    the failure surfaces."""

    def __init__(self, fn: Callable[[], object]) -> None:
        self.fn = fn
        self.result: object = None
        self.error: Optional[BaseException] = None
        self.t_request = time.perf_counter()
        self.t_executed: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the swap executed; re-raises the swap's exception
        in the caller. True when it completed within ``timeout``."""
        ok = self._done.wait(timeout)
        if self.error is not None:
            raise self.error
        return ok

    @property
    def fence_s(self) -> Optional[float]:
        """Wall time the swap spent fenced (request -> execution)."""
        if self.t_executed is None:
            return None
        return self.t_executed - self.t_request


class KvReuseTicket:
    """Handle for one pending fleet KV-reuse operation served on the
    scheduler's driving thread between steps (a prefix export for
    cross-replica sharing, or a mid-decode rebalance handover). The
    requesting thread ``wait()``s with a bounded timeout; a timeout or
    ``None``/``False`` result decays to the do-nothing fallback
    (re-prefill / decode in place) — the ticket never blocks the drive
    loop and never fails a request."""

    def __init__(self, kind: str, **kw) -> None:
        self.kind = kind
        self.kw = kw
        self.result: object = None
        self._done = threading.Event()

    def resolve(self, result: object) -> None:
        self.result = result
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> object:
        """Block until served (or ``timeout``); the result, else None."""
        if not self._done.wait(timeout):
            return None
        return self.result


class FCFSScheduler:
    """First-come-first-served continuous-batching scheduler.

    ``eos_id``: a request retires as soon as it samples this token (the
    EOS is kept as its last token — matching ``generate(eos_id=...)``,
    whose masked buffer holds the EOS then pads). Length retirement
    (``max_new_tokens``) applies either way. Both are host-side policy
    BETWEEN engine steps; inside the compiled programs shapes never
    change (see the engine's ``jnp.where`` masking).

    Degradation knobs (module docstring): ``max_queue``,
    ``default_deadline_s``, ``retry`` (prefill admission),
    ``restart_on_error``/``max_restarts``.
    """

    def __init__(self, engine, *, eos_id: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 restart_on_error: bool = True,
                 max_restarts: int = 8,
                 max_prefills_per_step: Optional[int] = None,
                 tracer=None, cost_accounting: bool = True,
                 fair=None, tenant_weights=None,
                 brownout: Optional[BrownoutPolicy] = None,
                 chunk_tokens_per_step: Optional[int] = None) -> None:
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if chunk_tokens_per_step is not None and chunk_tokens_per_step < 1:
            raise ValueError(
                f"chunk_tokens_per_step must be >= 1, got "
                f"{chunk_tokens_per_step}")
        self.engine = engine
        self.eos_id = eos_id
        self.metrics = metrics or ServingMetrics(engine.n_slots)
        # per-tenant resource ledger (PR 17): splits every measured
        # device interval across the requests that shared it. Pure
        # host-side dict arithmetic — default ON; ``cost_accounting=
        # False`` strips even that (the bench's overhead baseline).
        self.costs: Optional[CostLedger] = None
        if cost_accounting:
            self.costs = CostLedger(instance=self.metrics.instance)
            self.metrics.attach_costs(self.costs)
        self._t_block_sample: Optional[float] = None
        self.max_queue = max_queue
        self.default_deadline_s = default_deadline_s
        self._retry = retry
        self._restart_on_error = restart_on_error
        self._max_restarts = int(max_restarts)
        self._restarts = 0
        # cost-aware mode: batched admission groups + bounded prefill
        # interleave. Auto-on when the engine has any of the fast-path
        # features; the legacy single-request configuration keeps filling
        # the whole pool per step (unbounded), exactly as before.
        self._cost_aware = (engine.prefill_batch > 1
                            or len(engine.prefill_buckets) > 1
                            or engine.prefix_enabled)
        if max_prefills_per_step is None:
            max_prefills_per_step = 1 if self._cost_aware else None
        self._max_prefills = max_prefills_per_step
        self._events = get_event_log()
        # request-scoped tracing: every submission opens a Trace that
        # rides the request through its whole lifecycle; the tracer's
        # sampling (and forced retention on shed/error) decides what the
        # ring keeps. NULL_TRACE when tracing is disabled.
        self._tracer = tracer if tracer is not None else get_tracer()
        # weighted-fair admission (PR 18): OFF by default — plain FIFO,
        # exactly as before. ``fair=True`` (or passing tenant_weights)
        # turns on class-ordered weighted-DRR selection; an existing
        # FairAdmission instance is accepted for sharing/inspection.
        if fair is None:
            fair = tenant_weights is not None
        if isinstance(fair, FairAdmission):
            self._fair: Optional[FairAdmission] = fair
        elif fair:
            self._fair = FairAdmission(tenant_weights=tenant_weights)
        else:
            self._fair = None
        # brownout ladder (PR 18): consulted every step when present —
        # pauses batch, forces single-token decode, caps max_new, sheds
        self._brownout = brownout
        # chunked prefill (PR 19): only meaningful on a paged engine with
        # the chunked path built; harmless (never triggers) elsewhere
        self._chunk_tokens = (int(chunk_tokens_per_step)
                              if chunk_tokens_per_step is not None else None)
        # KV migration handover hook: ``cb(req, payload) -> bool`` set by
        # a supervising layer (the fleet router's disaggregated tiers).
        # On True the callback took ownership of the request; None = off.
        self.migrate_cb: Optional[Callable] = None
        self._lock = sanitizer.make_lock("FCFSScheduler._lock")
        # sanitizer-guarded: mutating either without _lock held raises
        # when the runtime sanitizer is on (lock-discipline, enforced)
        self._queue: deque[Request] = sanitizer.guarded(
            deque(), lock=self._lock, name="FCFSScheduler._queue")
        self._by_slot: dict[int, Request] = sanitizer.guarded(
            {}, lock=self._lock, name="FCFSScheduler._by_slot")
        # slot -> request mid-chunked-prefill (disjoint from _by_slot:
        # a PREFILLING slot takes no decode token and appends no blocks)
        self._prefilling: dict[int, Request] = sanitizer.guarded(
            {}, lock=self._lock, name="FCFSScheduler._prefilling")
        # migrated-in requests awaiting a slot: (req, kv payload) pairs,
        # admitted FCFS at step() start once the engine can take them
        self._pending_imports: deque = sanitizer.guarded(
            deque(), lock=self._lock, name="FCFSScheduler._pending_imports")
        # fleet KV-reuse operations awaiting the drive thread: prefix
        # share exports/imports and mid-decode rebalance handovers (all
        # device work, so only step() may serve them)
        self._pending_kv_reuse: deque = sanitizer.guarded(
            deque(), lock=self._lock, name="FCFSScheduler._pending_kv_reuse")
        self._ids = itertools.count()
        self._pending_swap: Optional[SwapTicket] = None

    # ------------------------------------------------------------------ #
    # submission surface (any thread)                                     #
    # ------------------------------------------------------------------ #

    def submit(self, prompt, max_new_tokens: int, *, rng=None,
               stream_cb: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default",
               priority: str = "interactive") -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.engine.validate_request(len(prompt), max_new_tokens)
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {PRIORITY_CLASSES}, "
                f"got {priority!r}")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            stream_cb=stream_cb, deadline_s=deadline_s,
            tenant=str(tenant), priority=str(priority),
        )
        req.t_submit = time.perf_counter()
        req._t_enqueue = req.t_submit
        if deadline_s is not None:
            req.t_deadline = req.t_submit + float(deadline_s)
        with self._lock:
            if (self.max_queue is not None
                    and len(self._queue) >= self.max_queue):
                self.metrics.record_rejected()
                self._events.emit("reject", prompt_len=len(prompt),
                                  queue_depth=len(self._queue))
                raise QueueFullError(
                    f"admission queue full ({self.max_queue} queued); "
                    "retry later or raise max_queue",
                    retry_after_s=self._retry_after_locked(),
                )
            req.id = next(self._ids)
            self._queue.append(req)
            self.metrics.record_submit()
        # the trace opens HERE (admitted to the queue): root span =
        # submit -> retire; first child = queue wait, closed when the
        # request is popped for admission
        req.trace = self._tracer.trace(
            "request", kind="serving", req=req.id, prompt_len=len(prompt),
            max_new=int(max_new_tokens))
        req._span_queue = req.trace.start_span("queue")
        self._events.emit("submit", req=req.id, prompt_len=len(prompt),
                          max_new=int(max_new_tokens),
                          **self._trace_label(req))
        return req

    @staticmethod
    def _trace_label(req: Request) -> dict:
        """``{"trace": id}`` when the request is traced, else ``{}`` —
        the join key flight-recorder events carry so dumps line up
        against exported span trees."""
        return {"trace": req.trace.trace_id} if req.trace.enabled else {}

    def cancel(self, req: Request) -> bool:
        """Cancel a request: dequeued if still QUEUED, slot freed if
        decoding. False if it already finished."""
        with self._lock:
            if req.finished:
                return False
            if req.state is RequestState.QUEUED:
                try:
                    self._queue.remove(req)
                except ValueError:
                    # not in the queue: a migrated-in request awaiting a
                    # slot? (mid-handover requests belong to nobody yet
                    # and report un-cancellable, same as the ValueError)
                    for i, (r, _) in enumerate(self._pending_imports):
                        if r is req:
                            del self._pending_imports[i]
                            break
                    else:
                        return False
            elif req.state is RequestState.PREFILLING:
                # mid-chunked-prefill: the driving thread owns the slot's
                # staged chunk state — it sees CANCELLED at the next
                # chunk tick and releases the slot itself (releasing here
                # would race the in-flight chunk's commit)
                pass
            elif req.slot >= 0:
                self.engine.release(req.slot)
                self._by_slot.pop(req.slot, None)
            # else: prefill in flight (no slot yet) — the step() admission
            # path sees the CANCELLED state and releases the slot itself
            req.state = RequestState.CANCELLED
            self.metrics.record_done(cancelled=True)
        if self.costs is not None:
            self.costs.finalize(req.id)
        self._events.emit("slot_retire", req=req.id, slot=req.slot,
                          reason="cancelled", **self._trace_label(req))
        req.trace.finish(reason="cancelled")
        req._done.set()
        return True

    @property
    def has_work(self) -> bool:
        with self._lock:
            return (bool(self._queue) or bool(self._by_slot)
                    or bool(self._prefilling)
                    or bool(self._pending_imports)
                    or bool(self._pending_kv_reuse)
                    or self._pending_swap is not None)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def engine_restarts(self) -> int:
        """Warm restarts this scheduler has driven (for reports/tests)."""
        return self._restarts

    # ------------------------------------------------------------------ #
    # supervisor surface (the fleet layer)                                 #
    # ------------------------------------------------------------------ #

    def drain_queued(self) -> list:
        """Remove and return every QUEUED request — the fleet failover
        hook: a supervising layer re-routes the drained work to a healthy
        replica instead of letting it wait on a scheduler whose engine
        just failed. Each drained request keeps state QUEUED (the caller
        owns it now); its trace is closed with ``reason="rerouted"`` —
        the re-submission opens a fresh one on the target replica."""
        with self._lock:
            drained = list(self._queue)
            self._queue.clear()
            # migrated-in work still waiting for a slot is QUEUED work
            # too: it never started decoding HERE, so the supervising
            # layer replays it (prompt + rng) on a healthy replica —
            # kill-mid-migration loses nothing
            drained.extend(req for req, _ in self._pending_imports)
            self._pending_imports.clear()
            # pending KV-reuse tickets resolve empty-handed NOW: a share
            # handshake waiting on this dead replica must decay to
            # re-prefill immediately, not after its full timeout
            reuse = list(self._pending_kv_reuse)
            self._pending_kv_reuse.clear()
        for ticket in reuse:
            ticket.resolve(None)
        for req in drained:
            if self.costs is not None:
                self.costs.finalize(req.id)
            if req._span_queue is not None:
                req.trace.end_span(req._span_queue)
                req._span_queue = None
            req.trace.finish(reason="rerouted")
        return drained

    def fail_inflight(self, e: BaseException) -> None:
        """Public supervisor boundary: fail every in-flight request loudly
        (terminal ERRORED, ``wait()`` re-raises) WITHOUT restarting the
        engine — the caller (a replica supervisor) owns the warm-restart /
        quarantine decision one level up. Idempotent per request: work
        already errored by the step's own exception boundary is left
        untouched."""
        with self._lock:
            has_inflight = bool(self._by_slot) or bool(self._prefilling)
            ticket, self._pending_swap = self._pending_swap, None
        if ticket is not None:
            # a publisher waiting on this ticket must hear about the
            # death instead of hanging on a fence that will never drain
            ticket.error = EngineFailed(
                "engine failed while a weight swap was fenced")
            ticket.error.__cause__ = e
            ticket.t_executed = time.perf_counter()
            ticket._done.set()
        if has_inflight:
            restart, self._restart_on_error = self._restart_on_error, False
            try:
                self._engine_failure(e)
            finally:
                self._restart_on_error = restart

    def request_swap(self, fn: Callable[[], object]) -> SwapTicket:
        """Enqueue a weight swap to run on the scheduler's driving thread
        at the next safe point (thread-safe; the publisher's entry point).

        The swap is a *version fence*: while a ticket is pending, NO new
        admissions happen — every in-flight request completes (or
        retires) entirely on the weights it started with — and once the
        slot pool drains, ``fn`` executes between decode steps on the one
        thread that touches the engine. Queued requests admit after the
        swap, on the new weights; the fence wait shows up as a ``swap``
        span in their traces. Only one swap may be pending at a time.
        """
        ticket = SwapTicket(fn)
        with self._lock:
            if self._pending_swap is not None:
                raise RuntimeError(
                    "a weight swap is already pending on this scheduler")
            self._pending_swap = ticket
        self._events.emit("swap_fence", queue_depth=self.queue_depth)
        return ticket

    # ------------------------------------------------------------------ #
    # the scheduling loop (one driving thread)                            #
    # ------------------------------------------------------------------ #

    def step(self) -> int:
        """One continuous-batching round; returns tokens emitted (0 when
        idle). Shedding, then admissions — freed slots refill BEFORE the
        decode step, so a retirement's slot never sits idle for a step."""
        emitted = 0
        self._shed_expired()
        self._policy_tick()
        # 0. version fence: while a swap is pending, admissions pause so
        # every in-flight request finishes on the weights it started
        # with; once the pool drains the swap runs HERE, between device
        # calls, on the one thread that owns the engine
        with self._lock:
            swapping = self._pending_swap is not None
            if (swapping and not self._by_slot and not self._prefilling
                    and not self._pending_imports):
                ticket, self._pending_swap = self._pending_swap, None
                swapping = False
            else:
                ticket = None
        if ticket is not None:
            self._execute_swap(ticket)
        # 1. admission: one group (>= 1 same-bucket requests, one device
        # call) per iteration, FCFS-anchored; bounded prefill interleave
        # in cost-aware mode so a deep queue can't stall decode.
        # Migrated-in requests admit first: their device time is already
        # spent elsewhere, they only need a slot + one scatter. They
        # admit even through a swap fence — they STARTED on the current
        # weights elsewhere, so they must finish on them here (the fence
        # simply waits for them like any other in-flight work).
        # Fleet KV-reuse operations (prefix share export/import,
        # rebalance handover) run first: a shared prefix landed here must
        # be trie-resident BEFORE this step's fresh admissions match
        self._serve_kv_reuse()
        self._admit_imports()
        with annotate("chainermn.serving_admit"):
            calls = 0
            while not swapping and self.engine.free_slots and (
                    self._max_prefills is None or calls < self._max_prefills):
                group = self._next_group()
                if not group:
                    break
                calls += 1
                emitted += self._admit_group(group)
        # 1a. chunked prefill: advance the oldest PREFILLING request by
        # exactly ONE chunk — the bounded slice of prefill work that
        # interleaves with this step's decode. Runs through a swap fence
        # too: a staged chunked admission already started on the current
        # weights, so the fence waits for it rather than stranding it
        emitted += self._advance_chunks()
        # 1b. paged: make sure every active slot can take this step's
        # token — lazily append blocks for slots crossing a block
        # boundary, preempting (requeueing, not failing) the lowest-
        # priority request when the pool runs dry
        if getattr(self.engine, "paged", False):
            self._ensure_decode_blocks()
        # 2. decode: every active slot, one compiled call — one token per
        # slot on the legacy path, up to k+1 (speculative) / decode_window
        # tokens per slot on the multi-token rounds
        # GIL-atomic snapshot for cost attribution (same contract as
        # _flight_ctx): who occupied which slot when the decode launched
        rows_snapshot = list(self._by_slot.items())  # graftlint: unguarded-ok
        # brownout L2: bypass decode_window / speculative rounds and run
        # the always-warmed single-token decode step — less work per
        # call, zero recompiles (warmup traces _decode_fn regardless)
        force_single = (self._brownout is not None
                        and self._brownout.force_single_token)
        t_dec0 = time.perf_counter()
        try:
            if force_single:
                decoded = {
                    slot: [tok] for slot, tok in
                    self.engine.decode_step(ctx=self._flight_ctx()).items()}
            else:
                decoded = self.engine.decode_round(ctx=self._flight_ctx())
        except Exception as e:  # noqa: BLE001 — degradation boundary
            if not self._engine_failure(e):
                raise
            decoded = {}
        t_dec1 = time.perf_counter()
        if self.costs is not None and rows_snapshot and decoded:
            # split the shared decode call across the n_slots rows the
            # compiled program actually ran; slots with no request book
            # as `idle`, rejected speculative drafts as `wasted`.
            # Under brownout L2 the speculative window never ran, so the
            # (stale) last_spec_slots must not attribute draft cost here.
            spec_info = (self.engine.last_spec_slots
                         if (not force_single
                             and getattr(self.engine, "spec_enabled", False))
                         else {})
            rows = []
            for slot, req in rows_snapshot:
                if slot in spec_info:
                    kd, a = spec_info[slot]
                    rows.append((req.id, req.tenant, a + 1, kd - a))
                else:
                    rows.append((req.id, req.tenant,
                                 max(len(decoded.get(slot, ())), 1), 0))
            self.costs.record_decode(t_dec1 - t_dec0,
                                     n_rows=self.engine.n_slots, rows=rows)
        if self.costs is not None and getattr(self.engine, "paged", False):
            # block-seconds: integral of blocks held over wall time,
            # sampled once per step; shared prefix blocks split by live
            # refcount so a popular prefix isn't billed N times over
            if self._t_block_sample is not None and rows_snapshot:
                self.costs.record_block_seconds(
                    t_dec1 - self._t_block_sample,
                    [(req.tenant, self.engine.slot_block_shares(slot))
                     for slot, req in rows_snapshot])
            self._t_block_sample = t_dec1
        for slot, toks in decoded.items():
            for tok in toks:
                # dict.get is GIL-atomic and a concurrent cancel() is
                # handled by the None check — taking _lock per token would
                # serialize the decode loop against the submit path for
                # nothing. Re-fetched per token: EOS/length retirement can
                # fire MID-window, and the window's tail past it must be
                # dropped, not delivered to the next slot tenant.
                req = self._by_slot.get(slot)  # graftlint: unguarded-ok
                if req is None or req.finished:
                    break              # released / retired mid-window
                now = time.perf_counter()
                self.metrics.record_token(req.t_last_token, now)
                # the shared decode call, attributed to every participant:
                # one decode_step span per request per step (token index in
                # the labels), bounded by the trace's span cap
                req.trace.add_span("decode_step", t_dec0, t_dec1,
                                   token=len(req.tokens))
                self._deliver(req, tok, now)
                emitted += 1
        if getattr(self.engine, "spec_enabled", False):
            window = self.engine.pop_spec_window()
            if window is not None:
                self.metrics.record_spec_window(*window)
        # deferred prefix-cache inserts run AFTER this step's tokens were
        # delivered (off the TTFT path) and before the next step can
        # reuse a donor slot
        self.engine.flush_inserts()
        with self._lock:
            depth = len(self._queue)
            batch_depth = sum(1 for r in self._queue
                              if r.priority == "batch")
        self.metrics.record_step(depth, self.engine.active_slots,
                                 batch_depth=batch_depth)
        if getattr(self.engine, "paged", False):
            self.metrics.record_kv_pool(*self.engine.kv_pool_stats())
        if self.costs is not None:
            self.costs.flush()
        return emitted

    def run_until_idle(self, max_steps: Optional[int] = None) -> int:
        """Drive ``step()`` until queue and slots drain; returns total
        tokens emitted. The offline convenience loop (tests, benchmarks);
        online serving drives ``step()`` from the client thread instead."""
        total = 0
        steps = 0
        while self.has_work:
            total += self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return total

    # ------------------------------------------------------------------ #
    # admission internals                                                 #
    # ------------------------------------------------------------------ #

    def _next_group(self) -> list:
        """Pop the next admission group: the queue head anchors it (FCFS —
        no starvation), then companions whose (prefix-discounted) padded
        suffix lands in the SAME bucket join, companions sharing the
        head's cached prefix first, until the group hits the engine's
        ``prefill_batch`` or the free-slot count. Returns ``[(req, plan),
        ...]``; every selected request is moved to PREFILL, every
        unselected candidate's plan is cancelled (match unpinned)."""
        eng = self.engine
        paged = getattr(eng, "paged", False)
        cap = min(eng.prefill_batch, len(eng.free_slots))
        with self._lock:
            head = self._pop_head_locked()
        if head is None:
            return []
        self._span_to_admit(head)
        # chaos boundary: an injected fault at the fair-admit pick fails
        # ONLY the picked request (terminal ERRORED, no stranded waiter)
        # — every decoding slot keeps decoding, the queue keeps serving
        try:
            inject(SERVING_ADMIT_FAIR, req=head.id, tenant=head.tenant,
                   priority=head.priority)
        except Exception as e:  # noqa: BLE001 — containment boundary
            self._fail_group([head], e)
            return []
        plan = eng.plan_admission(head.prompt, head.rng,
                                  max_new=head.max_new_tokens)
        # block-budget admission (paged): admit only what free + evictable
        # blocks cover at WORST-CASE growth — an over-admitted request
        # would fail mid-decode later; a deferred one just stays QUEUED
        # until retirements return blocks (FCFS order preserved)
        budget = None
        if paged:
            budget = eng.kv_blocks_admittable()
            need = eng.blocks_needed(len(head.prompt),
                                     head.max_new_tokens, plan.start)
            if need > budget:
                self._defer_admission(head, plan, need, budget)
                return []
            budget -= need
        # chunked prefill: a long suffix admits as a staged chunk
        # schedule instead of one monolithic device call — the same
        # block-budget gate above already cleared its worst-case growth.
        # plan_chunks returns None when chunking doesn't apply (suffix
        # fits one chunk, or a frontier outgrows every bucket): fall
        # through to the ordinary one-shot admission
        if (paged and self._chunk_tokens is not None
                and len(head.prompt) - plan.start > self._chunk_tokens
                and hasattr(eng, "plan_chunks")):
            chunks = eng.plan_chunks(plan, self._chunk_tokens)
            if chunks is not None:
                self._begin_chunked(head, plan, chunks)
                return []
        group = [(head, plan)]
        if cap <= 1:
            return group
        with self._lock:
            candidates = list(self._queue)
        scored = []
        for idx, req in enumerate(candidates):
            # companions ride the head's class: a batch request must not
            # slip into an interactive group (it would dodge both the
            # batch-after-interactive gate and brownout's batch pause)
            if req.priority != head.priority:
                continue
            p = eng.plan_admission(req.prompt, req.rng,
                                   max_new=req.max_new_tokens)
            if p.bucket != plan.bucket:
                eng.cancel_plan(p)
                continue
            shares = (plan.match is not None and p.match is not None
                      and p.match.nodes[0] is plan.match.nodes[0])
            scored.append((0 if shares else 1, idx, req, p))
        scored.sort(key=lambda t: (t[0], t[1]))
        for rank, (_, _, req, p) in enumerate(scored):
            need = (eng.blocks_needed(len(req.prompt), req.max_new_tokens,
                                      p.start) if paged else 0)
            if rank < cap - 1 and (budget is None or need <= budget):
                with self._lock:
                    try:
                        self._queue.remove(req)   # lost a cancel() race?
                    except ValueError:
                        eng.cancel_plan(p)
                        continue
                    req.state = RequestState.PREFILL
                self._span_to_admit(req)
                group.append((req, p))
                if budget is not None:
                    budget -= need
            else:
                eng.cancel_plan(p)
        return group

    def _pop_head_locked(self) -> Optional[Request]:
        """Pick + remove the next admission candidate (lock held by the
        caller). Plain FIFO ``popleft`` by default — byte-identical to
        the pre-fairness scheduler; with fair admission on, the
        class-ordered weighted-DRR policy picks instead. Brownout L1
        holds the ``batch`` class back on both paths."""
        if not self._queue:
            return None
        allow_batch = not (self._brownout is not None
                           and self._brownout.pause_batch)
        if self._fair is not None:
            head = self._fair.select(self._queue, allow_batch=allow_batch)
            if head is None:
                return None
            self._queue.remove(head)
        elif allow_batch:
            head = self._queue.popleft()
        else:
            head = next((r for r in self._queue
                         if r.priority != "batch"), None)
            if head is None:
                return None
            self._queue.remove(head)
        head.state = RequestState.PREFILL
        return head

    def _defer_admission(self, req: Request, plan, need: int,
                         available: int) -> None:
        """Paged admission gate tripped: put the request BACK at the
        queue head (FCFS — it admits first once blocks free up) instead
        of letting it fail mid-decode later. The pinned plan is
        released; the wait shows up in the request's ``queue`` span."""
        self.engine.cancel_plan(plan)
        if req._span_admit is not None:
            req.trace.end_span(req._span_admit)
            req._span_admit = None
        req._span_queue = req.trace.start_span("queue")
        req._t_enqueue = time.perf_counter()
        with self._lock:
            req.state = RequestState.QUEUED
            self._queue.appendleft(req)
        self._events.emit("kv_admit_defer", req=req.id, need=need,
                          available=available, **self._trace_label(req))

    def _span_to_admit(self, req: Request) -> None:
        """Queue wait is over: close the request's ``queue`` span and open
        ``admit`` (host-side planning + group assembly, closed when the
        prefill device call starts)."""
        if req._span_queue is not None:
            req.trace.end_span(req._span_queue)
            req._span_queue = None
        req._span_admit = req.trace.start_span("admit")
        if self.costs is not None:
            # wall-clock wait since the last (re-)enqueue — a preempted
            # request's second wait books again, on purpose: the tenant
            # really did wait twice
            self.costs.record_queue_wait(
                req.tenant, time.perf_counter() - req._t_enqueue)

    def _flight_ctx(self) -> dict:
        """Request/trace identity of the in-flight slots — the labels the
        engine threads into its watchdog window so a hang dump names WHO
        was decoding, not just that decode wedged."""
        # GIL-atomic snapshot; labels-only consumer tolerates staleness
        reqs = list(self._by_slot.values())  # graftlint: unguarded-ok
        if not reqs:
            return {}
        ctx = {"reqs": [r.id for r in reqs]}
        traces = [r.trace.trace_id for r in reqs if r.trace.enabled]
        if traces:
            ctx["traces"] = traces
        return ctx

    def _admit_group(self, group: list) -> int:
        """Drive one group through the engine (legacy single-request path
        when nothing batched/cached is in play — preserving the PR-1
        ``serving.prefill`` cut-point and retry semantics exactly), then
        commit each member. Returns first tokens emitted."""
        reqs = [r for r, _ in group]
        plans = [p for _, p in group]
        legacy = (len(group) == 1 and plans[0].match is None
                  and not self.engine.prefix_enabled)
        ctx = {"reqs": [r.id for r in reqs]}
        traces = [r.trace.trace_id for r in reqs if r.trace.enabled]
        if traces:
            ctx["traces"] = traces
        t_pre0 = time.perf_counter()
        for req in reqs:               # planning done; the device call next
            if req._span_admit is not None:
                req.trace.end_span(req._span_admit)
                req._span_admit = None
        try:
            if legacy:
                self.engine.cancel_plan(plans[0])
                req = reqs[0]
                if self._retry is not None:
                    results = [self._retry.call(
                        self.engine.prefill, req.prompt, req.rng,
                        op="serving.prefill", ctx=ctx)]
                else:
                    results = [self.engine.prefill(req.prompt, req.rng,
                                                   ctx=ctx)]
            else:
                if self._retry is not None:
                    results = self._retry.call(
                        self.engine.admit_batch, plans,
                        op="serving.prefill_batch", ctx=ctx)
                else:
                    results = self.engine.admit_batch(plans, ctx=ctx)
        except Exception as e:  # noqa: BLE001 — degradation boundary
            if not legacy and not isinstance(e, EngineStateError):
                # the device state is intact (admit_batch re-raises as
                # EngineStateError when a failure consumed its donated
                # buffers): only this group is lost — error its members,
                # every decoding slot keeps decoding, no restart burned
                self._fail_group(reqs, e)
                return 0
            if not self._engine_failure(e, admitting=reqs):
                raise
            return 0  # engine restarted: keep serving the queue
        t_pre1 = time.perf_counter()
        if self.costs is not None:
            # one shared device call, split by token share: the compiled
            # program always runs the full prefill_batch x bucket grid,
            # so empty rows and intra-row padding book as `padding`
            self.costs.record_prefill(
                t_pre1 - t_pre0, bucket=plans[0].bucket,
                batch_rows=self.engine.prefill_batch,
                members=[(req.id, req.tenant,
                          len(req.prompt) - plan.start)
                         for req, plan in group])
        emitted = 0
        self.metrics.record_admission(len(group))
        for (req, plan), (slot, first) in zip(group, results):
            now = time.perf_counter()
            # the shared batched device call, attributed to every member
            req.trace.add_span("prefill", t_pre0, t_pre1,
                               bucket=plan.bucket, batch=len(group),
                               cached=plan.start, slot=slot)
            with self._lock:
                if req.state is RequestState.CANCELLED:
                    # cancelled while its prefill was in flight (it had
                    # no slot yet, so cancel() left the release to us)
                    self.engine.release(slot)
                    continue
                req.slot = slot
                self._by_slot[slot] = req
                req.state = RequestState.DECODE
                # stamp the engine weight version this request will
                # decode on — the fence guarantees it never changes
                # between here and retirement
                req.weight_version = getattr(
                    self.engine, "weight_version", None)
            self._events.emit("slot_admit", req=req.id, slot=slot,
                              prompt_len=len(req.prompt),
                              bucket=plan.bucket, cached=plan.start,
                              queue_depth=self.queue_depth,
                              **self._trace_label(req))
            self.metrics.record_first_token(req.t_submit, now,
                                            req_id=req.id,
                                            cached_frac=plan.cached_frac)
            self._deliver(req, first, now)
            emitted += 1
            if not req.finished:
                # prefill done in one shot — a disaggregated fleet may
                # still want the decode phase elsewhere
                self._maybe_migrate(req, slot)
        return emitted

    def _execute_swap(self, ticket: SwapTicket) -> None:
        """Run a fenced weight swap on the driving thread (pool already
        drained). A raising swap fn surfaces ONLY on the ticket — the
        engine keeps its prior weights (the fn validates before
        assigning), the queue keeps being served."""
        t0 = time.perf_counter()
        try:
            ticket.result = ticket.fn()
        except Exception as e:  # noqa: BLE001 — surfaced on the ticket
            ticket.error = e
        t1 = time.perf_counter()
        ticket.t_executed = t1
        with self._lock:
            waiting = list(self._queue)
        for req in waiting:
            # the fence held these requests back: make the wait visible
            # in their traces as the swap window itself
            req.trace.add_span("swap", t0, t1,
                               ok=ticket.error is None)
        self._events.emit(
            "swap_exec", ok=ticket.error is None,
            fence_s=round(t1 - ticket.t_request, 6),
            queue_depth=len(waiting),
            **({"error": type(ticket.error).__name__}
               if ticket.error is not None else {}))
        ticket._done.set()

    def _fail_group(self, reqs: list, e: BaseException) -> None:
        """A batched admission failed with the engine intact: the group's
        requests error terminally (``wait()`` re-raises — no stranded
        waiters), every other slot keeps decoding, no restart burned."""
        with self._lock:
            for req in reqs:
                if req.finished:
                    continue
                failure = EngineFailed(
                    f"batched admission failed for request {req.id}: "
                    f"{type(e).__name__}: {e}")
                failure.__cause__ = e
                req.error = failure
                req.state = RequestState.ERRORED
                self.metrics.record_errored()
        self._events.emit("admission_error", error=type(e).__name__,
                          detail=str(e)[:200], group=len(reqs),
                          traces=[r.trace.trace_id for r in reqs
                                  if r.trace.enabled])
        for req in reqs:
            if self.costs is not None:
                self.costs.finalize(req.id)
            req.trace.mark_error(type(e).__name__)
            req.trace.finish(reason="admission_error")
            req._done.set()

    # ------------------------------------------------------------------ #
    # chunked prefill + KV migration (PR 19)                              #
    # ------------------------------------------------------------------ #

    def _begin_chunked(self, req: Request, plan, chunks: list) -> None:
        """Stage ``req`` as a chunked admission: the engine claims a slot
        and allocates the prompt's blocks up front (the block-budget gate
        already cleared worst-case growth), the request enters
        ``PREFILLING``, and :meth:`_advance_chunks` runs one chunk per
        step from here on. The plan is consumed either way; a transient
        staging failure re-queues the head at the FRONT (FCFS preserved,
        it retries next step)."""
        eng = self.engine
        try:
            slot = eng.begin_chunked(plan, chunks)
        except Exception as e:  # noqa: BLE001 — containment boundary
            if req._span_admit is not None:
                req.trace.end_span(req._span_admit)
                req._span_admit = None
            req._span_queue = req.trace.start_span("queue")
            req._t_enqueue = time.perf_counter()
            with self._lock:
                req.state = RequestState.QUEUED
                self._queue.appendleft(req)
            self._events.emit("kv_admit_defer", req=req.id,
                              error=type(e).__name__,
                              **self._trace_label(req))
            return
        with self._lock:
            if req.state is RequestState.CANCELLED:
                # cancelled while staging (it had no slot yet, so
                # cancel() left the release to us)
                eng.release(slot)
                return
            req.state = RequestState.PREFILLING
            req.slot = slot
            self._prefilling[slot] = req
            req.weight_version = getattr(eng, "weight_version", None)
        if req._span_admit is not None:
            req.trace.end_span(req._span_admit)
            req._span_admit = None
        self._events.emit("slot_admit", req=req.id, slot=slot,
                          prompt_len=len(req.prompt),
                          bucket=chunks[0][2], cached=plan.start,
                          chunks=len(chunks),
                          queue_depth=self.queue_depth,
                          **self._trace_label(req))

    def _advance_chunks(self) -> int:
        """Advance the OLDEST ``PREFILLING`` request by exactly one chunk
        (one bounded device call per step — decode stall stays capped at
        one chunk regardless of prompt length). The final chunk commits
        the slot, records TTFT, delivers the first token, and offers the
        request for KV migration. Returns first tokens emitted (0/1)."""
        with self._lock:
            if not self._prefilling:
                return 0
            slot, req = min(self._prefilling.items(),
                            key=lambda kv: kv[1].id)
        if req.finished:
            # cancelled mid-chunking: cancel() deferred the slot release
            # to this (the driving) thread — no in-flight chunk to race
            with self._lock:
                self._prefilling.pop(slot, None)
            self.engine.release(slot)
            return 0
        st = self.engine.chunk_state(slot)
        if st is None:   # engine restarted under us: nothing staged left
            with self._lock:
                self._prefilling.pop(slot, None)
            return 0
        _, clen, bucket = st.chunks[st.next_idx]
        idx, total = st.next_idx, len(st.chunks)
        ctx = {"reqs": [req.id]}
        if req.trace.enabled:
            ctx["traces"] = [req.trace.trace_id]
        t0 = time.perf_counter()
        try:
            first = self.engine.prefill_chunk(slot, ctx=ctx)
        except Exception as e:  # noqa: BLE001 — degradation boundary
            if not self._engine_failure(e):
                raise
            return 0
        t1 = time.perf_counter()
        if self.costs is not None:
            # each chunk is one full prefill_batch x bucket device call
            # with a single occupied row — the empty rows and the
            # intra-row padding book as `padding`, same as a batch of 1
            self.costs.record_prefill(
                t1 - t0, bucket=bucket,
                batch_rows=self.engine.prefill_batch,
                members=[(req.id, req.tenant, clen)])
        req.trace.add_span("prefill_chunk", t0, t1, bucket=bucket,
                           chunk=idx, of=total, tokens=clen, slot=slot)
        if first is None:
            return 0
        with self._lock:
            self._prefilling.pop(slot, None)
            if req.state is RequestState.CANCELLED:
                self.engine.release(slot)
                return 0
            req.state = RequestState.DECODE
            self._by_slot[slot] = req
        now = time.perf_counter()
        self.metrics.record_first_token(
            req.t_submit, now, req_id=req.id,
            cached_frac=(st.start / len(st.prompt)
                         if len(st.prompt) else 0.0))
        self._deliver(req, first, now)
        if not req.finished:
            self._maybe_migrate(req, slot)
        return 1

    def _maybe_migrate(self, req: Request, slot: int) -> bool:
        """Offer a prefill-complete request to :attr:`migrate_cb` for
        handover to a decode-tier peer (see :meth:`_handover`)."""
        cb = self.migrate_cb
        if cb is None or not getattr(self.engine, "migration_supported",
                                     False):
            return False
        return self._handover(req, slot, cb, reason="migrated")

    def _handover(self, req: Request, slot: int, cb: Callable,
                  reason: str = "migrated") -> bool:
        """Hand an in-flight request's slot over to a peer through
        ``cb(req, payload) -> bool``. The slot's KV blocks are read out
        host-side first (read-only gather — the slot keeps decoding in
        place if anything below fails), then the callback places the
        request: on True the SAME Request object now belongs to the
        destination scheduler and the slot is released here; on False —
        or an export/callback raise — the request is re-bound to its slot
        unchanged. Never a lost request. Shared by the prefill-complete
        migration (``reason="migrated"``) and the mid-decode rebalance
        (``reason="rebalanced"``) — the payload format and the
        all-or-nothing import don't care why the blocks are moving."""
        t0 = time.perf_counter()
        try:
            payload = self.engine.export_slot_kv(
                slot, ctx={"reqs": [req.id]})
        except Exception:  # noqa: BLE001 — fall back to decoding in place
            return False
        t1 = time.perf_counter()
        n_tokens = len(req.tokens)
        # all request-side bookkeeping happens BEFORE the callback: on
        # True the destination owns the object immediately (possibly
        # already admitting it on its own thread)
        req.trace.add_span("migrate", t0, t1, blocks=payload["n_blocks"],
                           src_slot=slot)
        req._span_queue = req.trace.start_span("queue")
        req._t_enqueue = time.perf_counter()
        with self._lock:
            self._by_slot.pop(slot, None)
            req.state = RequestState.QUEUED
            req.slot = -1
        try:
            ok = bool(cb(req, payload))
        except Exception:  # noqa: BLE001 — handshake failure = stay local
            ok = False
        if not ok:
            # decode in place: re-bind the slot exactly as it was
            if req._span_queue is not None:
                req.trace.end_span(req._span_queue)
                req._span_queue = None
            with self._lock:
                req.state = RequestState.DECODE
                req.slot = slot
                self._by_slot[slot] = req
            return False
        if self.costs is not None:
            self.costs.record_migration(t1 - t0, req_id=req.id,
                                        tenant=req.tenant)
            self.costs.finalize(req.id)
        self.engine.release(slot)
        self._events.emit("slot_retire", req=req.id, slot=slot,
                          reason=reason, tokens=n_tokens,
                          **self._trace_label(req))
        return True

    # ------------------------------------------------------------------ #
    # fleet KV reuse (prefix sharing + mid-decode rebalancing)            #
    # ------------------------------------------------------------------ #

    def request_prefix_export(self, tokens, *,
                              min_blocks: int = 1) -> KvReuseTicket:
        """Ask the drive thread to export this engine's cached prefix of
        ``tokens`` (thread-safe; the fleet router's share handshake).
        The ticket resolves to the share payload, or ``None`` when the
        trie holds fewer than ``min_blocks`` — the caller's timeout on
        ``wait()`` is the whole backpressure story: a wedged holder just
        means the destination re-prefills."""
        ticket = KvReuseTicket("prefix_export", tokens=tokens,
                               min_blocks=int(min_blocks))
        with self._lock:
            self._pending_kv_reuse.append(ticket)
        return ticket

    def enqueue_prefix_import(self, payload: dict,
                              on_done: Optional[Callable] = None
                              ) -> KvReuseTicket:
        """Queue a shared prefix payload for adoption into this engine's
        trie (thread-safe). Served at the next step() BEFORE fresh
        admissions, so a request submitted after the returned ticket
        resolves admits against the already-populated trie — zero
        prefill of the shared blocks. The ticket resolves to the blocks
        adopted (0 = already cached here, or the import failed —
        decays to a plain prefill); ``on_done(adopted)`` additionally
        fires on the drive thread."""
        ticket = KvReuseTicket("prefix_import", payload=payload,
                               on_done=on_done)
        with self._lock:
            self._pending_kv_reuse.append(ticket)
        return ticket

    def request_rebalance(self, place_cb: Callable) -> KvReuseTicket:
        """Ask the drive thread to hand its cheapest decoding victim
        over through ``place_cb(req, payload) -> bool`` (thread-safe;
        the fleet controller's mid-decode rebalance). Resolves True when
        a victim moved; False/None keeps everything decoding in place."""
        ticket = KvReuseTicket("rebalance", place_cb=place_cb)
        with self._lock:
            self._pending_kv_reuse.append(ticket)
        return ticket

    def _serve_kv_reuse(self) -> None:
        """Drain the pending KV-reuse queue on the drive thread (step()
        start, before fresh admissions). Every operation is best-effort:
        an export that can't match resolves None, an import that can't
        land is dropped (the requester re-prefills), a rebalance that
        can't place leaves the victim decoding here. Only a store-
        consuming failure escalates (engine-failure boundary, same as
        migrated imports)."""
        eng = self.engine
        while True:
            with self._lock:
                if not self._pending_kv_reuse:
                    return
                ticket = self._pending_kv_reuse.popleft()
            if ticket.kind == "prefix_export":
                payload = None
                try:
                    payload = eng.export_prefix_kv(
                        ticket.kw["tokens"],
                        min_blocks=ticket.kw["min_blocks"])
                except Exception:  # noqa: BLE001 — share is best-effort
                    payload = None
                ticket.resolve(payload)
            elif ticket.kind == "prefix_import":
                adopted = 0
                payload = ticket.kw["payload"]
                try:
                    if eng.can_import_prefix(payload):
                        adopted = eng.import_prefix_kv(payload)
                except EngineStateError as e:
                    ticket.resolve(0)
                    if not self._engine_failure(e):
                        raise
                    return
                except Exception:  # noqa: BLE001 — decay to re-prefill
                    adopted = 0
                ticket.resolve(adopted)
                on_done = ticket.kw.get("on_done")
                if on_done is not None:
                    try:
                        on_done(adopted)
                    except Exception:  # noqa: BLE001 — observer only
                        pass
            elif ticket.kind == "rebalance":
                ok = False
                try:
                    ok = self._rebalance_once(ticket.kw["place_cb"])
                except Exception:  # noqa: BLE001 — decode in place
                    ok = False
                ticket.resolve(bool(ok))

    def _rebalance_once(self, place_cb: Callable) -> bool:
        """Pick this scheduler's cheapest decoding victim — batch class
        first, then fewest live KV blocks (least payload to move), then
        the PR-18 tenant-overshare/recency order — and hand it over
        mid-decode through :meth:`_handover`. PREFILLING slots are never
        victims (their staged chunk state is not transferable)."""
        with self._lock:
            cands = [(slot, req) for slot, req in self._by_slot.items()
                     if not req.finished]
        if not cands:
            return False

        def cheap_key(item):
            slot, req = item
            blocks = self.engine.slot_block_count(slot)
            return (req.priority == "batch", -blocks,
                    (self._fair.tenant_share(req.tenant)
                     if self._fair is not None else 0.0), req.id)

        slot, req = max(cands, key=cheap_key)
        return self._handover(req, slot, place_cb, reason="rebalanced")

    def enqueue_migrated(self, req: Request, payload: dict) -> Request:
        """Accept a prefill-complete request handed over from another
        scheduler (thread-safe). The SAME Request object continues here —
        its tokens/stream_cb/trace/``_done`` ride along, so the consumer
        never notices the move. It waits in the import queue until the
        engine can take the scatter (:meth:`_admit_imports` — FCFS among
        imports, ahead of fresh admissions)."""
        with self._lock:
            self._pending_imports.append((req, payload))
        return req

    def _admit_imports(self) -> None:
        """Land pending migrated-in requests (FCFS, head-of-line: a
        transient slot/block shortage waits rather than reordering). A
        structurally unplaceable payload fails its request loudly so a
        supervising layer replays it elsewhere; a scatter that consumed
        the donated store escalates through the engine-failure boundary.
        Either way: never silently stuck, never silently lost."""
        eng = self.engine
        while True:
            with self._lock:
                if not self._pending_imports:
                    return
                req, payload = self._pending_imports[0]
            if req.finished:
                with self._lock:
                    if (self._pending_imports
                            and self._pending_imports[0][0] is req):
                        self._pending_imports.popleft()
                continue
            remaining = max(1, req.max_new_tokens - len(req.tokens))
            if not eng.can_import(payload, max_new=remaining):
                if eng.can_import(payload, max_new=remaining,
                                  static_only=True):
                    return   # transient: slots/blocks free up later
                with self._lock:
                    if (self._pending_imports
                            and self._pending_imports[0][0] is req):
                        self._pending_imports.popleft()
                self._fail_group([req], RuntimeError(
                    "migrated payload can never land on this engine "
                    "(block layout / position / capacity mismatch)"))
                continue
            t0 = time.perf_counter()
            try:
                slot = eng.import_slot_kv(payload, prompt=req.prompt,
                                          max_new=remaining,
                                          ctx={"reqs": [req.id]})
            except EngineStateError as e:
                with self._lock:
                    if (self._pending_imports
                            and self._pending_imports[0][0] is req):
                        self._pending_imports.popleft()
                if not self._engine_failure(e, admitting=req):
                    raise
                return
            except Exception:  # noqa: BLE001 — engine intact: retry later
                return
            t1 = time.perf_counter()
            with self._lock:
                if (self._pending_imports
                        and self._pending_imports[0][0] is req):
                    self._pending_imports.popleft()
                if req.state is RequestState.CANCELLED:
                    eng.release(slot)
                    continue
                req.slot = slot
                req.state = RequestState.DECODE
                self._by_slot[slot] = req
                req.weight_version = getattr(eng, "weight_version", None)
            if req._span_queue is not None:
                req.trace.end_span(req._span_queue)
                req._span_queue = None
            req.trace.add_span("import", t0, t1, slot=slot,
                               blocks=payload["n_blocks"])
            if self.costs is not None:
                self.costs.record_queue_wait(
                    req.tenant, time.perf_counter() - req._t_enqueue)
            self._events.emit("slot_admit", req=req.id, slot=slot,
                              prompt_len=len(req.prompt), migrated=True,
                              queue_depth=self.queue_depth,
                              **self._trace_label(req))

    # ------------------------------------------------------------------ #
    # paged-KV block management (decode-side)                             #
    # ------------------------------------------------------------------ #

    def _ensure_decode_blocks(self) -> None:
        """Before a paged decode step: append a fresh block for every
        active slot whose next write crosses a block boundary. When the
        pool is dry (even after trie eviction), deterministically preempt
        the LOWEST-priority request — the most recently submitted
        (highest id) — requeueing it instead of failing anyone
        mid-decode; an injected ``serving.kv_append`` fault is contained
        the same way (only that slot's request preempts — no engine
        restart burned, every other slot keeps decoding)."""
        eng = self.engine
        # drive-thread read; concurrent release is caught by the .get
        # None check, same contract as the step() token loop
        for slot in sorted(self._by_slot):  # graftlint: unguarded-ok
            req = self._by_slot.get(slot)
            if req is None:
                continue
            while eng.slot_needs_block(slot):
                try:
                    appended = eng.append_block(slot)
                except Exception as e:  # noqa: BLE001 — containment
                    self._preempt(req, reason=f"kv_append_"
                                              f"{type(e).__name__}")
                    break
                if appended:
                    # re-check: a multi-token round (speculative window /
                    # decode_window) can span MORE than one new block
                    continue
                victim = max(self._by_slot.values(), key=self._preempt_key)
                self._preempt(victim, reason="kv_pool_dry")
                if victim is req:
                    break   # we were the lowest priority ourselves

    def _preempt_key(self, req: Request) -> tuple:
        """Victim ordering when blocks run dry (max = evicted first):
        ``batch`` before any ``interactive``, then the tenant with the
        largest measured device-second share (the noisy neighbor pays
        first), then recency (highest id) — (class, overshare, recency).
        Without fair admission the share term is 0 and this reduces to
        (class, recency); without classes it is exactly the old
        newest-first rule."""
        share = (self._fair.tenant_share(req.tenant)
                 if self._fair is not None else 0.0)
        return (req.priority == "batch", share, req.id)

    def _preempt(self, req: Request, reason: str) -> None:
        """Evict a decoding request back to QUEUED: its slot and blocks
        free immediately, its generated-so-far tokens are discarded, and
        it re-enters the queue in submission-id order (FCFS). On
        re-admission it replays the SAME prompt with the SAME rng, so the
        sampler split sequence — and therefore the token stream —
        reproduces exactly (greedy or sampled); a ``stream_cb`` consumer
        sees the replayed tokens again."""
        with self._lock:
            if req.finished:
                return
            if req.slot >= 0:
                self.engine.release(req.slot)
                self._by_slot.pop(req.slot, None)
            if self.costs is not None:
                # the work already booked as useful stays useful (the
                # counters are monotonic); the REPLAY of these discarded
                # tokens is what books as waste, forward, as it happens
                self.costs.note_preempt(req.id, req.tenant,
                                        len(req.tokens))
            req.slot = -1
            req.tokens = []
            req.state = RequestState.QUEUED
            # reinsert preserving id (arrival) order among QUEUED peers
            idx = 0
            for idx, queued in enumerate(self._queue):  # noqa: B007
                if queued.id > req.id:
                    break
            else:
                idx = len(self._queue)
            self._queue.insert(idx, req)
        self.metrics.record_preemption(priority=req.priority)
        req._t_enqueue = time.perf_counter()
        if req._span_admit is not None:
            req.trace.end_span(req._span_admit)
            req._span_admit = None
        req._span_queue = req.trace.start_span("queue")
        self._events.emit("kv_preempt", req=req.id, reason=reason,
                          priority=req.priority, tenant=req.tenant,
                          queue_depth=self.queue_depth,
                          **self._trace_label(req))

    # ------------------------------------------------------------------ #
    # degradation internals                                               #
    # ------------------------------------------------------------------ #

    def _shed_expired(self) -> None:
        """Fail requests past their deadline (terminal ERRORED with
        DeadlineExceededError stored) — work that can no longer meet its
        deadline must not consume a slot another request could use. Both
        sides are swept: QUEUED requests are dropped from the queue, and
        a DECODING request past its deadline is retired at this step
        boundary with its slot + blocks freed — before this fix it kept
        burning device time to finish an answer nobody would read. The
        retirement happens strictly BETWEEN engine steps, so surviving
        slots' token streams (and replay parity) are untouched."""
        now = time.perf_counter()
        expired: list[Request] = []
        decode_expired: list[Request] = []
        prefill_expired: list[Request] = []
        with self._lock:
            if (not self._queue and not self._by_slot
                    and not self._prefilling
                    and not self._pending_imports):
                return
            hint = self._retry_after_locked()
            if self._queue:
                keep: deque[Request] = deque()
                for req in self._queue:
                    if req.t_deadline is not None and now >= req.t_deadline:
                        req.error = DeadlineExceededError(
                            f"request {req.id} spent its {req.deadline_s}s "
                            "deadline in the admission queue",
                            retry_after_s=hint,
                        )
                        req.state = RequestState.ERRORED
                        self.metrics.record_shed()
                        expired.append(req)
                    else:
                        keep.append(req)
                self._queue = sanitizer.guarded(
                    keep, lock=self._lock, name="FCFSScheduler._queue")
            for slot in sorted(self._by_slot):
                req = self._by_slot[slot]
                if req.t_deadline is None or now < req.t_deadline:
                    continue
                self.engine.release(slot)
                self._by_slot.pop(slot, None)
                req.error = DeadlineExceededError(
                    f"request {req.id} passed its {req.deadline_s}s "
                    f"deadline after {len(req.tokens)} decoded token(s)",
                    retry_after_s=hint,
                )
                req.state = RequestState.ERRORED
                self.metrics.record_shed()
                decode_expired.append(req)
            # chunked prefills past deadline: this sweep runs on the
            # driving thread between steps, so no chunk is in flight and
            # the slot release cannot race a commit
            for slot in sorted(self._prefilling):
                req = self._prefilling[slot]
                if req.t_deadline is None or now < req.t_deadline:
                    continue
                self.engine.release(slot)
                self._prefilling.pop(slot, None)
                req.error = DeadlineExceededError(
                    f"request {req.id} passed its {req.deadline_s}s "
                    "deadline mid chunked prefill",
                    retry_after_s=hint,
                )
                req.state = RequestState.ERRORED
                self.metrics.record_shed()
                prefill_expired.append(req)
            if self._pending_imports:
                keep_imp: deque = deque()
                for item in self._pending_imports:
                    req = item[0]
                    if (req.t_deadline is not None
                            and now >= req.t_deadline):
                        req.error = DeadlineExceededError(
                            f"request {req.id} passed its "
                            f"{req.deadline_s}s deadline awaiting its "
                            "KV migration import",
                            retry_after_s=hint,
                        )
                        req.state = RequestState.ERRORED
                        self.metrics.record_shed()
                        expired.append(req)
                    else:
                        keep_imp.append(item)
                self._pending_imports = sanitizer.guarded(
                    keep_imp, lock=self._lock,
                    name="FCFSScheduler._pending_imports")
        for req in expired + decode_expired + prefill_expired:
            if self.costs is not None:
                self.costs.finalize(req.id)
            # deadline-missed traces are retained regardless of sampling
            # (always-sample-on-deadline-miss): exactly the requests an
            # SLO breach will want to name
            req.trace.mark_deadline_miss()
            req.trace.finish(reason="shed")
            self._events.emit("shed", req=req.id,
                              where=("decode" if req in decode_expired
                                     else "prefill"
                                     if req in prefill_expired
                                     else "queue"),
                              waited_s=round(now - req.t_submit, 6),
                              **self._trace_label(req))
            req._done.set()

    def _retry_after_locked(self) -> float:
        """The structured backpressure hint attached to rejections and
        sheds: scales with queue depth so a deeper backlog pushes
        retries further out (the fleet edge's retry budget and breaker
        honor it end to end)."""
        return round(0.05 + 0.01 * len(self._queue), 3)

    def _policy_tick(self) -> None:
        """Once per step, before admissions: feed the fair-admission
        policy the ledger's measured per-tenant device-seconds (the
        noisy-neighbor weight shrink), let a self-driving brownout
        policy observe queue pressure, and execute the L4 shed when the
        ladder is that deep."""
        if self._fair is not None and self.costs is not None:
            self._fair.set_shares(self.costs.tenant_device_seconds())
        bo = self._brownout
        if bo is None:
            return
        # pressure = INTERACTIVE depth only: a paused batch backlog must
        # not hold the ladder up (L1 pauses batch — counting it would
        # make the level self-sustaining and the queue never drain)
        with self._lock:
            depth = sum(1 for r in self._queue if r.priority != "batch")
        bo.auto_observe(depth)
        if bo.shed_lowest:
            self._brownout_shed()

    def _brownout_shed(self) -> None:
        """Brownout L4: shed the lowest-effective-weight tenant's QUEUED
        work with a Retry-After hint (terminal QueueFullError — the
        client-visible contract is identical to an admission-queue
        rejection, plus the hint). In-flight work is never touched: the
        shed frees queue pressure, not slots."""
        with self._lock:
            tenants = sorted({r.tenant for r in self._queue})
        if not tenants:
            return
        if self._fair is not None:
            victim_tenant = self._fair.lowest_weight_tenant(tenants)
        else:
            victim_tenant = tenants[0]
        dropped: list[Request] = []
        with self._lock:
            hint = max(self._retry_after_locked(),
                       float(self._brownout.down_after_s))
            keep: deque[Request] = deque()
            for req in self._queue:
                if req.tenant == victim_tenant:
                    req.error = QueueFullError(
                        f"request {req.id} shed by brownout L4 "
                        f"(tenant {victim_tenant})",
                        retry_after_s=round(hint, 3),
                    )
                    req.state = RequestState.ERRORED
                    self.metrics.record_shed()
                    dropped.append(req)
                else:
                    keep.append(req)
            self._queue = sanitizer.guarded(
                keep, lock=self._lock, name="FCFSScheduler._queue")
        for req in dropped:
            if self.costs is not None:
                self.costs.finalize(req.id)
            self.metrics.record_tenant_shed(req.tenant)
            req.trace.finish(reason="shed")
            self._events.emit("shed", req=req.id, where="brownout",
                              tenant=req.tenant,
                              retry_after_s=req.error.retry_after_s,
                              **self._trace_label(req))
            req._done.set()

    def _engine_failure(self, e: BaseException,
                        admitting=None) -> bool:
        """The engine raised mid-round: fail every in-flight request
        loudly (their cache/slot state is unknown), dump the flight
        recorder once, and — within the restart budget — warm-restart the
        engine (fresh caches, slot mirrors, AND prefix store/trie — one
        consistent rebuild) so the queue keeps being served. Returns True
        when the engine was restarted; False tells the caller to
        re-raise. ``admitting`` is the request or group mid-admission."""
        if admitting is None:
            admitting = []
        elif isinstance(admitting, Request):
            admitting = [admitting]
        with self._lock:
            victims = list(self._by_slot.values())
            self._by_slot.clear()
            # half-prefilled chunked requests die with the store too;
            # pending KV imports are KEPT — their payloads are host-side
            # copies, importable onto the restarted engine as-is
            victims.extend(self._prefilling.values())
            self._prefilling.clear()
            victims.extend(admitting)
            for req in victims:
                if req.finished:
                    continue
                if req.error is None:
                    failure = EngineFailed(
                        f"engine failed while request {req.id} was in "
                        f"flight: {type(e).__name__}: {e}")
                    failure.__cause__ = e
                    req.error = failure
                req.state = RequestState.ERRORED
                self.metrics.record_errored()
        self._events.emit("engine_error", error=type(e).__name__,
                          detail=str(e)[:200], in_flight=len(victims),
                          traces=[r.trace.trace_id for r in victims
                                  if r.trace.enabled])
        get_event_log().dump(file=sys.stderr, last=32, once="failure")
        for req in victims:
            if self.costs is not None:
                self.costs.finalize(req.id)
            req.trace.mark_error(type(e).__name__)
            req.trace.finish(reason="engine_error")
            req._done.set()
        if not self._restart_on_error or self._restarts >= self._max_restarts:
            return False
        self.engine.restart()
        self._restarts += 1
        self.metrics.record_restart()
        self._events.emit("engine_restart", restarts=self._restarts)
        get_event_log().reset_dump_guard()  # recovered: next failure dumps
        return True

    # ------------------------------------------------------------------ #
    # internals                                                           #
    # ------------------------------------------------------------------ #

    def _deliver(self, req: Request, tok: int, now: float) -> None:
        req.tokens.append(int(tok))
        req.t_last_token = now
        if req.stream_cb is not None:
            try:
                req.stream_cb(int(tok))
            except Exception:
                pass  # a consumer's callback must not kill the engine loop
        hit_eos = self.eos_id is not None and int(tok) == self.eos_id
        # brownout L3: the effective max_new ceiling tightens for
        # in-flight and future requests alike — early retirement yields
        # a PREFIX of the request's full token stream (determinism kept)
        limit = req.max_new_tokens
        if self._brownout is not None:
            cap = self._brownout.effective_max_new_cap
            if cap is not None:
                limit = min(limit, cap)
        if hit_eos or len(req.tokens) >= limit:
            self._retire(req, "eos" if hit_eos else "length")

    def _retire(self, req: Request, reason: str) -> None:
        paged = getattr(self.engine, "paged", False)
        with self._lock:
            if req.finished:   # a concurrent cancel() won the race
                return
            if paged:
                # sampled BEFORE release drops the table: how many store
                # blocks this request's whole life actually took
                self.metrics.record_request_blocks(
                    self.engine.slot_block_count(req.slot))
            self.engine.release(req.slot)
            self._by_slot.pop(req.slot, None)
            req.state = RequestState.DONE
            self.metrics.record_done()
        if self.costs is not None:
            self.costs.finalize(req.id)
        self._events.emit("slot_retire", req=req.id, slot=req.slot,
                          reason=reason, tokens=len(req.tokens),
                          **self._trace_label(req))
        req.trace.finish(reason=reason, tokens=len(req.tokens))
        if req.trace.enabled:
            # per-trace critical path into the metrics surface: where the
            # slowest request actually spent its time
            self.metrics.record_trace(req.id, req.trace.breakdown())
        req._done.set()


__all__ = [
    "DeadlineExceededError",
    "EngineFailed",
    "FCFSScheduler",
    "QueueFullError",
    "Request",
    "RequestState",
    "SwapTicket",
]
