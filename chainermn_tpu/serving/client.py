"""In-process serving client: a background thread drives the scheduler;
callers get blocking and streaming APIs.

This is the process-local front of the serving stack (engine = mechanism,
scheduler = policy, client = thread + API). A network front would sit
where this class sits — the scheduler surface is already
submission-threaded — but in-process is the tier-1-testable core and what
``bench.py --mode serving`` and ``examples/lm/serve_lm.py`` drive.

Usage::

    engine = ServingEngine(model, params, n_slots=4, prefill_len=16)
    with ServingClient(engine, eos_id=0) as client:
        out = client.generate(prompt, max_new_tokens=32)      # blocking
        req = client.submit(prompt, 32, stream_cb=print)       # streaming
        req.wait()

The engine thread wakes on submission and sleeps when idle (event-driven,
no spin); an engine-side exception fails every in-flight request loudly
(the ``global_except_hook`` stance: die informatively, never hang a
caller on a dead engine).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import numpy as np

from chainermn_tpu.serving.scheduler import FCFSScheduler, Request


class ServingClient:
    """Background-threaded continuous-batching server, in process.

    Parameters mirror :class:`FCFSScheduler` (``eos_id``); the engine is
    built by the caller so model/sharding/sampler configuration stays in
    one place.
    """

    def __init__(self, engine, *, eos_id: Optional[int] = None,
                 idle_wait_s: float = 0.05,
                 max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 retry=None, restart_on_error: bool = True,
                 max_restarts: int = 8, fair=None, tenant_weights=None,
                 brownout=None,
                 chunk_tokens_per_step: Optional[int] = None) -> None:
        self.engine = engine
        self.scheduler = FCFSScheduler(
            engine, eos_id=eos_id, max_queue=max_queue,
            default_deadline_s=default_deadline_s, retry=retry,
            restart_on_error=restart_on_error, max_restarts=max_restarts,
            fair=fair, tenant_weights=tenant_weights, brownout=brownout,
            chunk_tokens_per_step=chunk_tokens_per_step)
        self.metrics = self.scheduler.metrics
        self._work = threading.Event()
        self._stop = threading.Event()
        self._failure: Optional[BaseException] = None
        self._idle_wait_s = idle_wait_s
        self._thread = threading.Thread(
            target=self._loop, name="chainermn-tpu-serving", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------ #
    # public API                                                          #
    # ------------------------------------------------------------------ #

    def submit(self, prompt, max_new_tokens: int, *, rng=None,
               stream_cb: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default",
               priority: str = "interactive") -> Request:
        """Enqueue a request; returns immediately. ``stream_cb`` (if set)
        is invoked from the engine thread once per generated token.
        ``tenant`` labels the request for the cost ledger's per-tenant
        attribution (and, with fair admission on, keys its DRR budget);
        ``priority`` picks the admission class (``"interactive"`` /
        ``"batch"``). Raises ``QueueFullError`` in the calling thread
        when the bounded admission queue (``max_queue``) is at capacity
        — backpressure is the submitter's signal, not a queued request's
        problem; its ``retry_after_s`` is the structured wait hint."""
        if self._failure is not None:
            raise RuntimeError("serving engine failed") from self._failure
        if self._stop.is_set():
            raise RuntimeError("client is closed")
        req = self.scheduler.submit(prompt, max_new_tokens, rng=rng,
                                    stream_cb=stream_cb,
                                    deadline_s=deadline_s,
                                    tenant=tenant, priority=priority)
        self._work.set()
        return req

    def generate(self, prompt, max_new_tokens: int, *, rng=None,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 tenant: str = "default",
                 priority: str = "interactive") -> np.ndarray:
        """Blocking single-request decode: ``prompt + generated`` tokens,
        the :func:`chainermn_tpu.models.generate`-shaped result. A shed
        or engine-failed (ERRORED) request re-raises its stored exception
        here, in the caller's thread — degradation is loud, never a
        silent hang (a shed's ``retry_after_s`` rides the exception)."""
        req = self.submit(prompt, max_new_tokens, rng=rng,
                          deadline_s=deadline_s, tenant=tenant,
                          priority=priority)
        if not req.wait(timeout):
            self.cancel(req)
            raise TimeoutError(
                f"request {req.id} did not finish within {timeout}s")
        return req.output

    def cancel(self, req: Request) -> bool:
        return self.scheduler.cancel(req)

    def close(self, timeout: float = 10.0) -> None:
        """Stop the engine thread (in-flight work is abandoned; pending
        requests are cancelled so no waiter hangs)."""
        self._stop.set()
        self._work.set()
        self._thread.join(timeout)
        # fail any stragglers loudly rather than leaving waiters blocked
        with self.scheduler._lock:
            pending = list(self.scheduler._queue) + list(
                self.scheduler._by_slot.values())
        for req in pending:
            self.scheduler.cancel(req)

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # engine thread                                                       #
    # ------------------------------------------------------------------ #

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self.scheduler.has_work:
                    self.scheduler.step()
                else:
                    # sleep until a submission (or periodic re-check);
                    # clear first so a submit during step() re-wakes us
                    self._work.clear()
                    if self.scheduler.has_work:
                        continue
                    self._work.wait(self._idle_wait_s)
        except BaseException as e:  # noqa: BLE001 — fail every waiter loudly
            self._failure = e
            with self.scheduler._lock:
                pending = list(self.scheduler._queue) + list(
                    self.scheduler._by_slot.values())
            for req in pending:
                req.error = e
                req._done.set()


__all__ = ["ServingClient"]
