"""Dataset scattering across processes.

Re-design of ``[U] chainermn/datasets/__init__.py`` (``scatter_dataset``,
``scatter_index``) and ``[U] chainermn/datasets/empty_dataset.py``
(SURVEY.md S2.13 — unverified cites). The reference's root rank permutes the
index space, slices it into ``size`` near-equal ``SubDataset`` shards, and
ships each shard to its rank over pickled MPI messages.

TPU re-design: shards live in *process* space (each host process feeds its
local devices; per-device distribution happens at ``device_put`` time via the
batch sharding, not at dataset level). Only the *permutation* travels over the
wire — every process holds the same underlying dataset object in the common
launch pattern (shared filesystem / storage bucket), so shipping indices is
enough; set ``force_transport=True`` for the reference behaviour of moving
the actual records when only root can see the data.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class SubDataset:
    """An index-remapped view of a dataset (reference: chainer's SubDataset
    as used by scatter_dataset). Supports len/getitem/iteration."""

    def __init__(self, dataset, indices: Sequence[int]) -> None:
        self._dataset = dataset
        self._indices = np.asarray(indices, dtype=np.int64)

    def __len__(self) -> int:
        return len(self._indices)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self._indices[i]]
        return self._dataset[int(self._indices[i])]

    def __iter__(self):
        for j in self._indices:
            yield self._dataset[int(j)]

    @property
    def indices(self) -> np.ndarray:
        return self._indices


def _n_processes(comm) -> int:
    """Data distribution shards over *processes* (each feeds its devices).
    ``process_size`` differs from ``inter_size`` only on declared
    multi-process-per-host launches; the getattr keeps duck-typed comms
    (host-only shims) working."""
    return max(1, getattr(comm, "process_size", None) or comm.inter_size)


def scatter_index(
    n_total: int, comm: CommunicatorBase, root: int = 0,
    *, n_shards: Optional[int] = None, shard_id: Optional[int] = None,
) -> tuple[int, int]:
    """Partition ``range(n_total)`` into near-equal contiguous shards; return
    this shard's ``(begin, end)``. Reference ``scatter_index``. The first
    ``n_total % n_shards`` shards get one extra element."""
    del root  # pure arithmetic: no transport needed for an index split
    n = n_shards if n_shards is not None else _n_processes(comm)
    i = shard_id if shard_id is not None else comm.rank
    if not 0 <= i < n:
        raise ValueError(f"shard_id {i} out of range [0, {n})")
    base, extra = divmod(n_total, n)
    begin = i * base + min(i, extra)
    end = begin + base + (1 if i < extra else 0)
    return begin, end


def scatter_dataset(
    dataset,
    comm: CommunicatorBase,
    shuffle: bool = False,
    root: int = 0,
    seed: Optional[int] = None,
    *,
    n_shards: Optional[int] = None,
    shard_id: Optional[int] = None,
    force_transport: bool = False,
):
    """Shard ``dataset`` across processes (reference ``scatter_dataset``).

    Root draws the (optionally shuffled) permutation and broadcasts it so all
    shards are disjoint and exhaustive. By default each process keeps a
    ``SubDataset`` view over its local ``dataset`` object; with
    ``force_transport=True`` root ships the actual records (for sources only
    root can read — the reference always does this, paying the transport).

    ``n_shards``/``shard_id`` override the process-space geometry (used by
    tests to emulate N ranks in one process, and by hybrid-parallel setups
    that shard over a sub-axis).
    """
    n = n_shards if n_shards is not None else _n_processes(comm)
    i = shard_id if shard_id is not None else comm.rank
    if comm.rank == root:
        n_total = len(dataset)
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(n_total)
        else:
            order = np.arange(n_total)
    else:
        order = None
    order = comm.bcast_obj(order, root=root)
    n_total = len(order)

    shards = []
    for s in range(n):
        b, e = scatter_index(n_total, comm, n_shards=n, shard_id=s)
        shards.append(order[b:e])

    if force_transport:
        if comm.rank == root:
            payloads = [[dataset[int(j)] for j in idx] for idx in shards]
        else:
            payloads = None
        n_proc = _n_processes(comm)
        if n == n_proc and shard_id is None:
            # aligned with process geometry: true scatter (each process
            # receives only its shard, the reference's transport pattern)
            local = comm.scatter_obj(payloads, root=root)
        else:
            # overridden geometry: ship all shards, pick locally (transport
            # is already the expensive part; correctness over cleverness)
            payloads = comm.bcast_obj(payloads, root=root)
            local = payloads[i]
        return SubDataset(local, np.arange(len(local)))
    return SubDataset(dataset, shards[i])


def create_empty_dataset(dataset):
    """Zero-length placeholder with the dataset interface (reference
    ``create_empty_dataset``): lets non-root ranks build pipelines that
    expect a dataset object when only root holds data."""
    return SubDataset(dataset, np.empty((0,), np.int64))


def get_n_iterations_for_one_epoch(dataset, local_batch_size: int) -> int:
    """ceil(len/batch) — reference helper of the same name (med confidence)."""
    return -(-len(dataset) // local_batch_size)


__all__ = [
    "SubDataset",
    "scatter_dataset",
    "scatter_index",
    "create_empty_dataset",
    "get_n_iterations_for_one_epoch",
]
