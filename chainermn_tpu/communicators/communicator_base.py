"""Abstract communicator contract.

TPU-native re-design of ``[U] chainermn/communicators/communicator_base.py``
(SURVEY.md S2.2 — unverified upstream-layout cite). The reference contract is
kept name-for-name (``rank``/``size``/``intra_rank``/``inter_rank``, array and
object collectives, ``bcast_data``, ``allreduce_grad`` /
``multi_node_mean_grad``, ``split``) so reference-shaped training scripts carry
over, but the execution model is inverted (DESIGN.md): a communicator owns a
``jax.sharding.Mesh`` and its collectives are XLA ops, not byte-movers.

Two calling contexts for every array collective:

- **traced**: argument is a tracer inside ``shard_map``/``pjit`` over this
  communicator's mesh -> lowers to the bare ``lax`` collective. Hot path.
- **eager**: argument is a concrete array in **rank-major** layout — a global
  array whose leading axis has length ``size``, slice ``i`` being "rank i's
  array". The communicator runs a cached ``jit(shard_map(...))``. This mirrors
  the reference's per-rank test semantics without per-rank processes.

Object communication (``*_obj``) lives in *process* space (host side, DCN on a
multi-host pod), exactly like the reference's pickle-over-MPI path
(``[U] chainermn/communicators/mpi_communicator_base.py`` — ``_MessageType``
header + chunked raw sends). Here it rides the jax.distributed KV store or the
native objstore sidecar; in a single-process run it degenerates to identity.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Sequence

ReduceOp = str  # 'sum' | 'mean' | 'max' | 'min' | 'prod'


class CommunicatorBase(abc.ABC):
    """The contract every communicator implements.

    Reference parity: every public method/property of the reference's
    ``CommunicatorBase`` has a counterpart here; additions are marked *TPU
    extension* in their docstrings.
    """

    # ------------------------------------------------------------------ #
    # Topology                                                            #
    # ------------------------------------------------------------------ #

    @property
    @abc.abstractmethod
    def size(self) -> int:
        """Number of participants (devices along the communicator axis)."""

    @property
    @abc.abstractmethod
    def rank(self) -> int:
        """This *process*'s rank. In single-controller SPMD the per-device
        rank only exists inside traced code — use :meth:`axis_index` there.
        Host-side, ``rank`` identifies the process (0 in single-process runs),
        which is what the reference uses it for (root checks, data loading)."""

    @property
    @abc.abstractmethod
    def intra_rank(self) -> int:
        """Rank within the node (reference: GPU index on the host)."""

    @property
    @abc.abstractmethod
    def inter_rank(self) -> int:
        """Node index (reference: host index)."""

    @property
    @abc.abstractmethod
    def intra_size(self) -> int:
        """Participants per node (ICI-local devices per process)."""

    @property
    @abc.abstractmethod
    def inter_size(self) -> int:
        """Number of nodes (hosts)."""

    @property
    def process_size(self) -> int:
        """Number of processes. Equals :attr:`inter_size` except on declared
        multi-process-per-host launches (``CHAINERMN_TPU_PROCS_PER_HOST``).
        Host-side data distribution — dataset scattering, per-rank
        checkpoints, obj-comm worlds — shards over processes, not hosts."""
        return self.inter_size

    @abc.abstractmethod
    def axis_index(self):
        """Traced device rank: ``lax.axis_index`` over the communicator axis.
        Only valid inside ``shard_map``/``pjit`` over this mesh. *TPU
        extension* — the SPMD replacement for per-process ``comm.rank``."""

    # ------------------------------------------------------------------ #
    # Array collectives (dual traced/eager — see module docstring)        #
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def allreduce(self, x, op: ReduceOp = "sum"):
        """Reference ``allreduce``. Traced: ``lax.psum``/``pmax``/... Eager:
        rank-major in, rank-major out (every slice holds the reduction)."""

    @abc.abstractmethod
    def bcast(self, x, root: int = 0):
        """Reference ``bcast``: root's array to all ranks."""

    @abc.abstractmethod
    def gather(self, x, root: int = 0):
        """Reference ``gather``: stacked ``[size, ...]`` result (global —
        in SPMD "only root has it" is a sharding, not a location)."""

    @abc.abstractmethod
    def allgather(self, x):
        """Reference ``allgather``: every rank receives all ranks' arrays."""

    @abc.abstractmethod
    def scatter(self, x, root: int = 0):
        """Reference ``scatter``: slice ``i`` of root's ``[size, ...]`` array
        to rank ``i``."""

    @abc.abstractmethod
    def alltoall(self, x):
        """Reference ``alltoall``: rank i's slice j goes to rank j's slice i."""

    @abc.abstractmethod
    def send(self, x, dest: int, tag: int = 0) -> None:
        """Host-side point-to-point send (reference MPI ``send``). For
        *traced* p2p inside a step function use
        :mod:`chainermn_tpu.functions` (``ppermute``-based, differentiable)."""

    @abc.abstractmethod
    def recv(self, source: int, tag: int = 0):
        """Host-side point-to-point receive paired with :meth:`send`."""

    # ------------------------------------------------------------------ #
    # Object communication (process space, host side)                     #
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None: ...

    @abc.abstractmethod
    def recv_obj(self, source: int, tag: int = 0) -> Any: ...

    @abc.abstractmethod
    def bcast_obj(self, obj: Any, root: int = 0) -> Any: ...

    @abc.abstractmethod
    def gather_obj(self, obj: Any, root: int = 0) -> list[Any] | None: ...

    @abc.abstractmethod
    def allgather_obj(self, obj: Any) -> list[Any]: ...

    @abc.abstractmethod
    def allreduce_obj(self, obj: Any, reduce_func: Callable | None = None) -> Any: ...

    @abc.abstractmethod
    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any: ...

    # ------------------------------------------------------------------ #
    # Model helpers — the data-parallel integration surface               #
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def bcast_data(self, params):
        """Reference ``bcast_data(model)``: replicate a parameter pytree so
        every participant starts identical. Here: device_put with a replicated
        ``NamedSharding`` (+ process-0 broadcast on multi-host)."""

    @abc.abstractmethod
    def multi_node_mean_grad(self, grads, zero_fill: bool = False):
        """Reference ``allreduce_grad`` / ``multi_node_mean_grad``: average a
        gradient pytree across participants. Traced (the hot path — fuses into
        the jitted train step) or eager rank-major. Strategy subclasses differ
        ONLY in how this moves bytes, mirroring SURVEY.md S2.3-2.8."""

    def allreduce_grad(self, grads, zero_fill: bool = False):
        """Backward-compat alias (older reference name)."""
        return self.multi_node_mean_grad(grads, zero_fill)

    # ------------------------------------------------------------------ #
    # Topology surgery                                                    #
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def split(self, color, key=None) -> "CommunicatorBase":
        """Reference ``split(color, key)`` -> sub-communicator.

        SPMD re-design: ``color`` is a sequence of length ``size`` assigning
        every *device rank* a color (the reference's per-process color arg,
        gathered). Returns a communicator whose collectives are scoped to the
        caller-colored groups via ``axis_index_groups`` — no new bootstrap.
        """

    @abc.abstractmethod
    def finalize(self) -> None:
        """Release cached executables (reference: free MPI/NCCL comms)."""
