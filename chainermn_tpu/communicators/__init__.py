"""Communicator factory.

Parity with ``[U] chainermn/communicators/__init__.py``'s
``create_communicator`` (SURVEY.md S2.1 — unverified cite). The reference's
seven strategy names are all accepted; GPU-era names map to their TPU
equivalents (the mapping is the DESIGN.md strategy table):

==================  =============================================
reference name      resolves to
==================  =============================================
``naive``           :class:`NaiveCommunicator` (per-param psum)
``flat``            :class:`FlatCommunicator` (packed single psum)
``tpu``             :class:`TpuCommunicator` — the flagship
``pure_ici``        alias of ``tpu``
``pure_nccl``       alias of ``tpu`` (GPU name, kept for parity)
``hierarchical``    :class:`HierarchicalCommunicator` (ICI+DCN 2-level)
``two_dimensional`` :class:`TwoDimensionalCommunicator` (RS/AR/AG)
``single_node``     :class:`SingleNodeCommunicator`
``non_cuda_aware``  alias of ``hierarchical`` (host-staging is meaningless
                    on TPU; name kept so reference scripts run)
==================  =============================================
"""

from __future__ import annotations

import warnings

from chainermn_tpu.communicators.communicator_base import CommunicatorBase
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator
from chainermn_tpu.communicators.naive_communicator import NaiveCommunicator
from chainermn_tpu.communicators.flat_communicator import FlatCommunicator
from chainermn_tpu.communicators.tpu_communicator import TpuCommunicator
from chainermn_tpu.communicators.hierarchical_communicator import (
    HierarchicalCommunicator,
    SingleNodeCommunicator,
    TwoDimensionalCommunicator,
)

__all__ = [
    "CommunicatorBase",
    "MeshCommunicator",
    "NaiveCommunicator",
    "FlatCommunicator",
    "TpuCommunicator",
    "HierarchicalCommunicator",
    "TwoDimensionalCommunicator",
    "SingleNodeCommunicator",
    "create_communicator",
]

_GPU_ALIASES = {"pure_nccl": "tpu", "non_cuda_aware": "hierarchical"}


def create_communicator(
    communicator_name: str = "tpu",
    mesh=None,
    devices=None,
    allreduce_grad_dtype=None,
    **kwargs,
) -> CommunicatorBase:
    """Create a communicator by strategy name.

    Args:
      communicator_name: strategy (see module docstring). Default ``'tpu'``
        (the reference defaults to ``'hierarchical'``, a GPU-cluster-shaped
        choice; on TPU the flat ICI ring is the right default).
      mesh: optional existing ``jax.sharding.Mesh`` to wrap.
      devices: optional explicit device list (default: all devices).
      allreduce_grad_dtype: wire dtype for gradient averaging, e.g.
        ``'bfloat16'`` — reference ``allreduce_grad_dtype=np.float16`` on the
        pure_nccl strategy. Only the ``tpu``/``pure_ici`` strategy honors it,
        matching the reference's pure_nccl-only support.
    """
    name = communicator_name.lower()
    if name in _GPU_ALIASES:
        warnings.warn(
            f"communicator {communicator_name!r} is a GPU-era strategy; "
            f"using the TPU equivalent {_GPU_ALIASES[name]!r}",
            stacklevel=2,
        )
        name = _GPU_ALIASES[name]

    if name in ("tpu", "pure_ici"):
        return TpuCommunicator(
            mesh=mesh, devices=devices,
            allreduce_grad_dtype=allreduce_grad_dtype, **kwargs,
        )
    if allreduce_grad_dtype is not None:
        raise ValueError(
            "allreduce_grad_dtype is supported only by the 'tpu' strategy "
            "(reference: pure_nccl-only)"
        )
    if name == "naive":
        return NaiveCommunicator(mesh=mesh, devices=devices, **kwargs)
    if name == "flat":
        return FlatCommunicator(mesh=mesh, devices=devices, **kwargs)
    if name == "hierarchical":
        return HierarchicalCommunicator(mesh=mesh, devices=devices, **kwargs)
    if name == "two_dimensional":
        return TwoDimensionalCommunicator(mesh=mesh, devices=devices, **kwargs)
    if name == "single_node":
        return SingleNodeCommunicator(mesh=mesh, devices=devices, **kwargs)
    raise ValueError(f"unknown communicator: {communicator_name!r}")
