"""Per-parameter allreduce strategy.

Parity with ``[U] chainermn/communicators/naive_communicator.py`` (SURVEY.md
S2.3 — unverified cite): the reference issues one ``MPI_Allreduce`` per
parameter on whatever memory MPI can see; it is the CPU-only baseline and the
backend every distributed test can run. Here the analog is one ``lax.pmean``
per gradient leaf — the simplest correct strategy, and the one the CPU test
mesh exercises. (Under jit XLA may still fuse neighbouring collectives; the
*strategy* is "no packing", not "no fusion".)
"""

from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator


class NaiveCommunicator(MeshCommunicator):
    pass  # base class behaviour IS the naive strategy
