"""Mesh-backed communicator: the concrete core of the framework.

Re-designs the reference's ``MpiCommunicatorBase``
(``[U] chainermn/communicators/mpi_communicator_base.py``, SURVEY.md S2.2 —
unverified cite) for single-controller SPMD: instead of issuing MPI/NCCL calls
per collective, this class owns a ``jax.sharding.Mesh`` and lowers each
collective to the corresponding XLA op — directly when called on tracers
inside ``shard_map``/``pjit`` (the hot path, fused into the step program), or
through a cached ``jit(shard_map(...))`` harness when called eagerly on
rank-major arrays (the test/bootstrap path). See DESIGN.md.

The reference's chunked-transfer machinery (32-bit MPI count limits), typed
``_MessageType`` headers, and pinned-buffer staging have no equivalent here *by
design*: XLA owns buffering and transport on ICI, and arbitrary-object traffic
rides the process-space object comm (``_object_comm.py``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators import _object_comm
from chainermn_tpu.communicators.communicator_base import CommunicatorBase, ReduceOp
from chainermn_tpu.monitor import annotate
from chainermn_tpu.parallel import mesh as mesh_lib
from chainermn_tpu.resilience.cutpoints import COMM_ALLGATHER_OBJ, comm_point
from chainermn_tpu.resilience.faults import inject


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across JAX generations.

    New JAX exposes ``jax.shard_map(check_vma=...)``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map(check_rep=...)`` (same knob,
    pre-rename: static replication tracking). Every shard_map in the
    framework funnels through here (or through :meth:`MeshCommunicator.
    shard_map`), so the emulated-CPU-mesh harness — and the serving
    engine's tensor-parallel decode — run on both generations.
    """
    if hasattr(jax, "shard_map"):  # the deprecation stub raises -> False
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _legacy

    # check_rep is ALWAYS off on the legacy API: its psum2/pbroadcast
    # rewrite auto-psums backward gradients of replicated inputs — the
    # exact behavior the framework's pcast-to-varying pattern suppresses
    # on new JAX (training.py: grads must stay per-rank so the
    # communicator strategy owns the one reduction). With the rewrite
    # disabled, legacy gradients are per-rank local by default and the
    # explicit collectives carry the same semantics on both generations.
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def _is_traced(x) -> bool:
    return any(
        isinstance(leaf, jax.core.Tracer) for leaf in jax.tree_util.tree_leaves(x)
    )


def _leaf_vma(leaf):
    """The mesh axes a traced value varies over (its varying manner), or
    ``None`` when unavailable/untracked (e.g. ``check_vma=False`` tracing) —
    callers must then assume fully varying, the conservative default for
    gradient leaves."""
    try:
        vma = jax.typeof(leaf).vma
        return vma if isinstance(vma, frozenset) else frozenset(vma)
    except Exception:
        return None


class _MessageType(NamedTuple):
    """Typed p2p header: structure + per-leaf metadata, sent before the raw
    buffers — the descendant of the reference's ``_MessageType`` (shape/
    dtype/tuple-structure of ndarray trees, ``[U] .../mpi_communicator_base
    .py`` SURVEY.md S2.2). Dtypes are carried as ``np.dtype`` objects so
    extended dtypes (bfloat16 via ml_dtypes) round-trip exactly."""

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[np.dtype, ...]


class MeshCommunicator(CommunicatorBase):
    """Communicator over one flat mesh axis (or a tuple of axes treated as
    one flattened rank space — the hierarchical subclasses use that)."""

    # Whether steps traced over this communicator can keep shard_map's static
    # replication (VMA) check on. Strategies whose lowering contains an
    # all_gather that is provably-but-not-statically replicated (currently
    # TwoDimensionalCommunicator) set this False; comm.shard_map and the
    # training-step builders read it.
    check_vma = True

    def __init__(
        self,
        mesh: Mesh | None = None,
        axis_name: str | tuple[str, ...] | None = None,
        devices: Sequence[jax.Device] | None = None,
        _groups: list[list[int]] | None = None,
    ) -> None:
        if mesh is None:
            mesh = mesh_lib.make_mesh(devices)
        self._mesh = mesh
        if axis_name is None:
            axes: tuple[str, ...] = tuple(mesh.axis_names)
        elif isinstance(axis_name, str):
            axes = (axis_name,)
        else:
            axes = tuple(axis_name)
        for a in axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
        self._axes = axes
        self._geom = mesh_lib.RankGeometry.from_mesh(mesh)
        self._groups = _groups  # set on split() sub-communicators
        if _groups is not None:
            gsize = len(_groups[0])
            if any(len(g) != gsize for g in _groups):
                raise ValueError(
                    "split() groups must be equal-sized (XLA collective "
                    "requirement; the reference's MPI split has no such "
                    "constraint — pad colors if you need ragged groups)"
                )
            table = np.full(self._global_size, -1, np.int32)
            for g in _groups:
                for local, glob in enumerate(g):
                    table[glob] = local
            if (table < 0).any():
                raise ValueError("split() groups must cover every rank")
            self._local_rank_table = table
        self._cache: dict[Any, Callable] = {}
        self._mailbox: dict[tuple[int, int], list[Any]] = {}
        self._obj = _object_comm.create_object_comm()

    # ------------------------------------------------------------------ #
    # Topology                                                            #
    # ------------------------------------------------------------------ #

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def axis_name(self):
        """The communicator axis (str, or tuple for hierarchical meshes)."""
        return self._axes if len(self._axes) > 1 else self._axes[0]

    @property
    def _global_size(self) -> int:
        return int(np.prod([self._mesh.shape[a] for a in self._axes]))

    @property
    def size(self) -> int:
        return len(self._groups[0]) if self._groups else self._global_size

    @property
    def rank(self) -> int:
        return self._geom.rank

    @property
    def intra_rank(self) -> int:
        return self._geom.intra_rank

    @property
    def inter_rank(self) -> int:
        return self._geom.inter_rank

    @property
    def intra_size(self) -> int:
        return self._geom.intra_size

    @property
    def inter_size(self) -> int:
        return self._geom.inter_size

    @property
    def process_size(self) -> int:
        return self._geom.process_size

    def axis_index(self):
        """Traced rank (group-local on split communicators)."""
        idx = lax.axis_index(self._axes)
        if self._groups is not None:
            idx = jnp.asarray(self._local_rank_table)[idx]
        return idx

    # ------------------------------------------------------------------ #
    # Sharding conveniences (TPU extensions)                              #
    # ------------------------------------------------------------------ #

    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self._mesh, P(*spec))

    @property
    def data_spec(self) -> P:
        """PartitionSpec sharding a leading batch axis over the comm axis."""
        return P(self._axes if len(self._axes) > 1 else self._axes[0])

    def shard_map(self, f, in_specs, out_specs, check_vma: bool | None = None):
        """``jax.shard_map`` bound to this communicator's mesh. ``check_vma``
        defaults to the communicator's own :attr:`check_vma` (strategies with
        statically-unprovable replication turn the check off)."""
        if check_vma is None:
            check_vma = self.check_vma
        return _shard_map(
            f, mesh=self._mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

    # ------------------------------------------------------------------ #
    # Traced collective bodies (group-aware)                              #
    # ------------------------------------------------------------------ #

    # Every traced collective body is wrapped in monitor.annotate: the XLA
    # ops carry a ``chainermn.<op>`` scope in their HLO metadata, so an
    # XProf/Perfetto capture shows WHICH framework collective a device-time
    # span belongs to. (Scope names avoid hyphenated opcode spellings so
    # parse_hlo_collectives' text scan can never match them.)

    def _gathered(self, x):
        """all_gather giving every rank the full [size, ...] stack; the
        building block for ops XLA lacks a grouped/native primitive for."""
        with annotate("chainermn.allgather"):
            return lax.all_gather(
                x, self._axes, axis_index_groups=self._groups, tiled=False
            )

    def _grouped_sum(self, x):
        """Group-scoped sum with ring-allreduce wire cost (~2x payload).

        ``lax.psum(axis_index_groups=...)`` is NotImplemented under shard_map
        in current JAX, but ``psum_scatter`` and ``all_gather`` both take
        groups — so decompose the allreduce the way the ring algorithm does:
        reduce-scatter a 1/n shard to each group member, then all-gather the
        shards back. (The previous fallback all-gathered the full payload:
        group_size x the bytes.)"""
        n = self.size

        def leaf(a):
            flat = jnp.ravel(a)
            pad = (-flat.size) % n
            if pad:
                flat = jnp.pad(flat, (0, pad))
            shard = lax.psum_scatter(
                flat.reshape(n, -1), self._axes, scatter_dimension=0,
                tiled=False, axis_index_groups=self._groups,
            )
            full = lax.all_gather(
                shard, self._axes, axis_index_groups=self._groups, tiled=False
            ).reshape(-1)
            if pad:
                full = full[: flat.size - pad]
            return full.reshape(a.shape)

        return jax.tree_util.tree_map(leaf, x)

    # Below this many bytes per leaf, prod uses one all_gather + local
    # reduce (one collective, size x bytes — fine for the typical tiny
    # operands); above it, the ring decomposition (2x payload wire,
    # O(payload) memory, n-1 latency steps).
    _PROD_RING_THRESHOLD = 1 << 16

    def _prod(self, x):
        """Allreduce-prod. XLA has no prod collective and psum_scatter can't
        carry the op, so this is either gather+reduce (small leaves) or a
        ring reduce-scatter in multiply (large leaves) — the same
        decomposition `_grouped_sum` uses, with ppermute because the
        reduction op must be ours."""
        ring_ok = self.size > 1

        def leaf(a):
            if not ring_ok or a.size * a.dtype.itemsize <= self._PROD_RING_THRESHOLD:
                return jnp.prod(self._gathered(a), axis=0)
            return self._ring_prod_leaf(a)

        return jax.tree_util.tree_map(leaf, x)

    def _ring_prod_leaf(self, a):
        """Ring allreduce with multiply: after s hops the carry that will end
        at group slot q has visited slots q-s..q-1, each multiplying in its
        local block for index q; an all_gather of the finished blocks
        rebuilds the full product. Works grouped (ring within each group),
        ungrouped, and on multi-axis meshes (ppermute linearizes tuple axes
        exactly as axis_index does)."""
        axis = self._axes
        n = self.size
        pos = self.axis_index()
        flat = jnp.ravel(a)
        pad = (-flat.size) % n
        if pad:  # pad value never survives the final slice; ones for tidiness
            flat = jnp.concatenate([flat, jnp.ones((pad,), flat.dtype)])
        blocks = flat.reshape(n, -1)
        if self._groups is None:
            perm = [(i, (i + 1) % n) for i in range(n)]
        else:
            perm = [(g[i], g[(i + 1) % len(g)])
                    for g in self._groups for i in range(len(g))]

        def block_for(s):
            return jnp.take(blocks, jnp.mod(pos - s - 1, n), axis=0)

        carry = block_for(0)
        for s in range(1, n):
            carry = lax.ppermute(carry, axis, perm)
            carry = carry * block_for(s)
        full = lax.all_gather(
            carry, axis, axis_index_groups=self._groups, tiled=False
        ).reshape(-1)
        if pad:
            full = full[: flat.size - pad]
        return full.reshape(a.shape)

    def _t_allreduce(self, x, op: ReduceOp):
        with annotate(f"chainermn.allreduce_{op}"):
            return self._t_allreduce_body(x, op)

    def _t_allreduce_body(self, x, op: ReduceOp):
        if op == "prod":
            return self._prod(x)
        if self._groups is None:
            if op == "sum":
                return lax.psum(x, self._axes)
            if op == "mean":
                return lax.pmean(x, self._axes)
            if op == "max":
                return lax.pmax(x, self._axes)
            if op == "min":
                return lax.pmin(x, self._axes)
            raise ValueError(f"unknown reduce op {op!r}")
        if op == "max":
            return lax.pmax(x, self._axes, axis_index_groups=self._groups)
        if op == "min":
            return lax.pmin(x, self._axes, axis_index_groups=self._groups)
        if op == "sum":
            return self._grouped_sum(x)
        if op == "mean":
            return jax.tree_util.tree_map(
                lambda s: s / self.size, self._grouped_sum(x)
            )
        raise ValueError(f"unknown reduce op {op!r}")

    def _t_bcast(self, x, root: int):
        # Masked sum: only root contributes, everyone ends with root's value.
        # Ungrouped this is one psum (~2x-of-optimal ring traffic, payload-
        # sized HLO output — independent of mesh size); grouped it rides the
        # reduce-scatter/all-gather decomposition. (A true collective-
        # broadcast would halve wire bytes, but JAX exposes neither
        # collective-broadcast nor multi-destination ppermute.)
        with annotate("chainermn.bcast"):
            mask = self.axis_index() == root
            masked = jax.tree_util.tree_map(
                lambda a: jnp.where(mask, a, jnp.zeros_like(a)), x
            )
            if self._groups is None:
                return lax.psum(masked, self._axes)
            return self._grouped_sum(masked)

    def _t_gather(self, x, root: int):
        del root  # SPMD: the stack is global; "root-ness" is a sharding choice
        return self._gathered(x)

    def _t_allgather(self, x):
        return self._gathered(x)

    def _t_scatter(self, x, root: int):
        # Masked reduce-scatter: root's [size, ...] array is the only nonzero
        # contribution, so the summed shard each rank receives IS its slice.
        # O(payload) on the wire vs the previous bcast-the-whole-array+slice
        # (which shipped size x the useful bytes); works grouped too.
        if x.shape[0] != self.size:
            raise ValueError(
                f"scatter input leading axis {x.shape[0]} != comm size {self.size}"
            )
        with annotate("chainermn.scatter"):
            mask = self.axis_index() == root
            masked = jnp.where(mask, x, jnp.zeros_like(x))
            return lax.psum_scatter(
                masked, self._axes, scatter_dimension=0, tiled=False,
                axis_index_groups=self._groups,
            )

    def _t_alltoall(self, x):
        if x.shape[0] != self.size:
            raise ValueError(
                f"alltoall input leading axis {x.shape[0]} != comm size {self.size}"
            )
        with annotate("chainermn.alltoall"):
            return lax.all_to_all(
                x, self._axes, split_axis=0, concat_axis=0, tiled=True,
                axis_index_groups=self._groups,
            )

    def _t_ppermute(self, x, perm: Sequence[tuple[int, int]]):
        """Group-local perm pairs -> global pairs when split."""
        if self._groups is not None:
            perm = [(g[s], g[d]) for g in self._groups for (s, d) in perm]
        with annotate("chainermn.ppermute"):
            return lax.ppermute(x, self._axes, perm=list(perm))

    # ------------------------------------------------------------------ #
    # Eager harness: rank-major arrays through cached jit(shard_map)      #
    # ------------------------------------------------------------------ #

    def _eager(self, opname: str, body: Callable, args, extra_key=()):
        """Run ``body`` (written against per-rank local arrays) over
        rank-major global inputs. ``args`` is a tuple; each element is a
        pytree whose every leaf has leading axis == global size."""
        # fault cut-point: the host boundary of every eager collective
        # (traced collectives fuse into compiled programs and cannot host-
        # inject — a device-program failure is the engine/step boundary's
        # scenario, exercised at serving.*/trainer.step instead)
        inject(comm_point(opname))
        leaves, treedef = jax.tree_util.tree_flatten(args)
        gsize = self._global_size
        multiproc = jax.process_count() > 1
        if multiproc:
            # Multi-controller: every process passes the same rank-major host
            # array; ONE device_put with the global sharding moves just this
            # process's addressable shards. (A jnp.asarray commit first would
            # pay a full-array transfer before resharding.) Outputs are
            # global jax.Arrays — read your shard via .addressable_data(0).
            sharding = NamedSharding(self._mesh, self.data_spec)
            leaves = [
                jax.device_put(np.asarray(l), sharding) for l in leaves
            ]
        else:
            leaves = [jnp.asarray(l) for l in leaves]
        for l in leaves:
            if l.ndim < 1 or l.shape[0] != gsize:
                raise ValueError(
                    f"{opname}: eager collectives take rank-major arrays "
                    f"(leading axis == {gsize}); got shape {l.shape}. "
                    "Inside shard_map/pjit, pass tracers instead."
                )
        key = (
            opname,
            treedef,
            tuple((l.shape, str(l.dtype)) for l in leaves),
            extra_key,
        )
        fn = self._cache.get(key)
        if fn is None:
            spec = self.data_spec

            def wrapper(*flat_local):
                local = jax.tree_util.tree_unflatten(
                    treedef, [l[0] for l in flat_local]
                )
                out = body(*local)  # args is always a tuple of pytrees
                return jax.tree_util.tree_map(lambda o: o[None, ...], out)

            fn = jax.jit(
                _shard_map(
                    wrapper, mesh=self._mesh, in_specs=spec, out_specs=spec
                )
            )
            self._cache[key] = fn
        return fn(*leaves)

    # ------------------------------------------------------------------ #
    # Public array collectives (dual dispatch)                            #
    # ------------------------------------------------------------------ #

    def allreduce(self, x, op: ReduceOp = "sum"):
        if _is_traced(x):
            return self._t_allreduce(x, op)
        return self._eager("allreduce", lambda a: self._t_allreduce(a, op), (x,), op)

    def bcast(self, x, root: int = 0):
        if _is_traced(x):
            return self._t_bcast(x, root)
        return self._eager("bcast", lambda a: self._t_bcast(a, root), (x,), root)

    def gather(self, x, root: int = 0):
        if _is_traced(x):
            return self._t_gather(x, root)
        out = self._eager("gather", lambda a: self._t_gather(a, root), (x,), root)
        return out[0] if self._groups is None else out

    def allgather(self, x):
        if _is_traced(x):
            return self._t_allgather(x)
        return self._eager("allgather", self._t_allgather, (x,))

    def scatter(self, x, root: int = 0):
        if _is_traced(x):
            return self._t_scatter(x, root)
        return self._eager("scatter", lambda a: self._t_scatter(a, root), (x,), root)

    def alltoall(self, x):
        if _is_traced(x):
            return self._t_alltoall(x)
        return self._eager("alltoall", self._t_alltoall, (x,))

    def ppermute(self, x, perm: Sequence[tuple[int, int]]):
        """Rotate arrays along an explicit (source, dest) permutation —
        the primitive under functions.send/recv. *TPU extension*."""
        if _is_traced(x):
            return self._t_ppermute(x, perm)
        return self._eager(
            "ppermute", lambda a: self._t_ppermute(a, perm), (x,), tuple(perm)
        )

    # ------------------------------------------------------------------ #
    # Host-side p2p (process space)                                       #
    # ------------------------------------------------------------------ #

    def _check_process_rank(self, who: str, r: int) -> None:
        n = max(1, jax.process_count())
        if not 0 <= r < n:
            raise ValueError(
                f"{who}={r} out of range: host-side send/recv are *process*-"
                f"space (0..{n - 1}), mirroring the reference's per-process "
                "MPI p2p. For device-rank p2p inside a step, use "
                "chainermn_tpu.functions.send/recv (differentiable, "
                "ppermute-based)."
            )

    def send(self, x, dest: int, tag: int = 0) -> None:
        """Typed p2p send of an **array pytree** (single arrays included):
        a ``_MessageType`` header (treedef, shapes, dtypes) goes first, then
        one raw buffer per leaf — the reference's ndarray-tree ``send``
        protocol, re-hosted on the object transport. ``recv`` reconstructs
        the exact structure and dtypes."""
        if _is_traced(x):
            raise RuntimeError(
                "comm.send inside traced code: use chainermn_tpu.functions."
                "send (differentiable, ppermute-based) for in-step p2p."
            )
        self._check_process_rank("dest", dest)
        leaves, treedef = jax.tree_util.tree_flatten(x)
        arrays = [np.asarray(l) for l in leaves]
        header = _MessageType(
            treedef,
            tuple(a.shape for a in arrays),
            tuple(a.dtype for a in arrays),
        )
        if dest == self.rank:
            # copy: the remote path hands the receiver fresh buffers, so the
            # self-send path must too (no sender/receiver aliasing)
            self._mailbox.setdefault(tag, []).append(
                (header, [np.array(a) for a in arrays])
            )
        else:
            self._obj.send_obj(header, dest, tag)
            for a in arrays:
                self._obj.send_obj(np.ascontiguousarray(a).tobytes(), dest, tag)

    def recv(self, source: int, tag: int = 0):
        """Receive an array pytree sent by :meth:`send`: header first, then
        the leaf buffers, reassembled to the sent structure (a bare array in
        comes back as a bare array). Leaves come back as **numpy** arrays
        with the exact sent dtypes (f64 included — ``jnp.asarray`` would
        silently downcast without x64 mode); pass them straight into jitted
        code or ``device_put`` as needed."""
        self._check_process_rank("source", source)
        if source == self.rank:
            q = self._mailbox.get(tag)
            if not q:
                raise RuntimeError(f"recv(source={source}, tag={tag}): nothing sent")
            header, arrays = q.pop(0)
        else:
            header = self._obj.recv_obj(source, tag)
            if not isinstance(header, _MessageType):
                raise RuntimeError(
                    f"recv(source={source}, tag={tag}): expected a "
                    f"_MessageType header, got {type(header).__name__} — "
                    "pair comm.recv with comm.send (use recv_obj for "
                    "send_obj traffic)"
                )
            arrays = [
                np.frombuffer(
                    self._obj.recv_obj(source, tag), dtype=dt
                ).reshape(shape)
                for shape, dt in zip(header.shapes, header.dtypes)
            ]
        return jax.tree_util.tree_unflatten(header.treedef, list(arrays))

    # ------------------------------------------------------------------ #
    # Object communication (delegates to process-space transport)         #
    # ------------------------------------------------------------------ #

    def send_obj(self, obj, dest: int, tag: int = 0) -> None:
        self._obj.send_obj(obj, dest, tag)

    def recv_obj(self, source: int, tag: int = 0):
        return self._obj.recv_obj(source, tag)

    def bcast_obj(self, obj, root: int = 0):
        return self._obj.bcast_obj(obj, root)

    def gather_obj(self, obj, root: int = 0):
        return self._obj.gather_obj(obj, root)

    def allgather_obj(self, obj):
        # cut-point: the host object channel the checkpoint agreement and
        # registry aggregation ride (a raise here = a lost DCN peer)
        inject(COMM_ALLGATHER_OBJ)
        return self._obj.allgather_obj(obj)

    def allreduce_obj(self, obj, reduce_func: Callable | None = None):
        return self._obj.allreduce_obj(obj, reduce_func)

    def scatter_obj(self, objs, root: int = 0):
        return self._obj.scatter_obj(objs, root)

    def barrier(self) -> None:
        """Host-side barrier across processes (TPU extension; the reference
        leans on MPI's implicit collective synchronization)."""
        self._obj.barrier()

    # ------------------------------------------------------------------ #
    # Model helpers                                                       #
    # ------------------------------------------------------------------ #

    def bcast_data(self, params):
        """Replicate a parameter pytree across the mesh (reference
        ``bcast_data(model)`` — rank 0's weights to everyone). On multi-host,
        process 0's values win via a host broadcast first."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            params = multihost_utils.broadcast_one_to_all(params)
        sharding = NamedSharding(self._mesh, P())
        return jax.device_put(params, sharding)

    def _mean_leaves_traced(self, leaves: list):
        """Strategy hook: how a list of gradient leaves becomes a list of
        cross-rank means. Base = per-parameter collectives, the reference's
        ``NaiveCommunicator`` strategy (one MPI_Allreduce per param,
        ``[U] .../naive_communicator.py``)."""
        return [self._t_allreduce(g, "mean") for g in leaves]

    def multi_node_mean_grad(self, grads, zero_fill: bool = False):
        del zero_fill  # jax.grad never yields missing leaves; kept for parity
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if not leaves:
            return grads
        if _is_traced(grads):
            # The contract is "mean of the per-rank gradients". Leaves that
            # shard_map's replication tracking marks INVARIANT along a comm
            # axis are already equal across that axis — their mean over it is
            # the value itself, so that axis needs NO collective (running the
            # strategy psum anyway would both waste wire bytes and, worse,
            # SUM the equal copies into size x the mean). This matters
            # because differentiating wrt replicated params with a
            # cross-rank-reduced loss auto-psums the backward: the arriving
            # grads are the correct global gradient, already invariant (see
            # test_hand_written_step... in tests/test_training_step.py; our
            # own step builders instead pcast params to varying so the
            # strategy owns the collective). With check_vma=False, tracking
            # is off and every value reports an empty vma — probe a
            # known-varying value so untracked local grads still take the
            # strategy path.
            tracking = bool(_leaf_vma(lax.axis_index(self._axes)))
            if self._groups is None and tracking:
                axes = set(self._axes)
                vmas = [_leaf_vma(l) for l in leaves]
                pending = [
                    i for i, v in enumerate(vmas)
                    if v is not None and not axes.issubset(v)
                ]
                if pending:
                    out = list(leaves)
                    for i in pending:
                        # pmean over the still-varying comm axes only;
                        # fully-invariant leaves pass through untouched
                        rest = tuple(a for a in self._axes if a in vmas[i])
                        out[i] = lax.pmean(leaves[i], rest) if rest else leaves[i]
                    varying = [i for i in range(len(leaves)) if i not in pending]
                    if varying:
                        meaned = self._mean_leaves_traced(
                            [leaves[i] for i in varying]
                        )
                        for i, m in zip(varying, meaned):
                            out[i] = m
                    return jax.tree_util.tree_unflatten(treedef, out)
            return jax.tree_util.tree_unflatten(
                treedef, self._mean_leaves_traced(leaves)
            )

        def body(tree):
            ls, td = jax.tree_util.tree_flatten(tree)
            return jax.tree_util.tree_unflatten(td, self._mean_leaves_traced(ls))

        return self._eager("mean_grad", body, (grads,))

    # ------------------------------------------------------------------ #
    # Split & lifecycle                                                   #
    # ------------------------------------------------------------------ #

    def split(self, color, key=None) -> "MeshCommunicator":
        del key  # rank order within a color group follows device-rank order
        colors = list(color)
        if len(colors) != self._global_size:
            raise ValueError(
                f"split(): need one color per device rank ({self._global_size}); "
                f"got {len(colors)}. (The reference's per-process color arg is "
                "passed gathered in the SPMD re-design — see DESIGN.md.)"
            )
        groups: dict[Any, list[int]] = {}
        for r, c in enumerate(colors):
            groups.setdefault(c, []).append(r)
        return self._make_split([groups[c] for c in sorted(groups)])

    def _make_split(self, groups: list[list[int]]) -> "MeshCommunicator":
        """Same class, same mesh, group-scoped collectives. Strategy
        subclasses keep their identity (and copy extra state via
        :meth:`_copy_strategy_state`); their ``_mean_leaves_traced`` overrides
        see ``_groups`` and fall back where the strategy needs full-axis
        structure (the hierarchical pair)."""
        sub = object.__new__(type(self))
        MeshCommunicator.__init__(
            sub, mesh=self._mesh, axis_name=self._axes, _groups=groups
        )
        self._copy_strategy_state(sub)
        return sub

    def _copy_strategy_state(self, sub: "MeshCommunicator") -> None:
        """Hook: copy subclass-held config onto a split() child (overridden
        e.g. by TpuCommunicator for ``allreduce_grad_dtype``)."""

    def finalize(self) -> None:
        self._cache.clear()

    def __repr__(self) -> str:
        g = f", groups={self._groups}" if self._groups else ""
        return (
            f"<{type(self).__name__} size={self.size} axes={self._axes} "
            f"mesh={dict(self._mesh.shape)}{g}>"
        )
