"""Two-level (intra-node / inter-node) allreduce strategies.

Parity with ``[U] chainermn/communicators/hierarchical_communicator.py`` and
``[U] .../two_dimensional_communicator.py`` (SURVEY.md S2.3 — unverified
cites). The reference splits MPI_COMM_WORLD into intra-node and inter-node
sub-communicators and composes the allreduce from NCCL (fast, local) and MPI
(slow, cross-node) legs:

- hierarchical: NCCL reduce -> leader MPI allreduce -> NCCL bcast
- two_dimensional: NCCL reduce-scatter -> MPI allreduce -> NCCL allgather

The TPU mapping keeps the *decomposition* but swaps the legs for mesh axes:
``intra`` = ICI-local devices of one process, ``inter`` = across processes
(DCN on a multi-host pod). Two chained collectives over the factored axes let
XLA schedule the fast-leg/slow-leg split explicitly — the same reason the
reference does it by hand. On a single-slice pod (all-ICI) the flat
``TpuCommunicator`` is usually faster; these exist for multi-slice/DCN pods
and for strategy parity.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import _memory_utility
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator
from chainermn_tpu.parallel import mesh as mesh_lib


class HierarchicalCommunicator(MeshCommunicator):
    """reduce(intra) -> allreduce(inter) -> bcast(intra), expressed as two
    chained psums (a psum over ``intra`` IS reduce+bcast fused, which is how
    XLA would lower the reference's reduce/bcast pair anyway)."""

    def __init__(self, devices: Sequence[jax.Device] | None = None, mesh=None,
                 **kwargs):
        if mesh is None:
            mesh = mesh_lib.make_hierarchical_mesh(devices)
        super().__init__(
            mesh=mesh, axis_name=(mesh_lib.INTER_AXIS, mesh_lib.INTRA_AXIS),
            **kwargs,
        )

    def _mean_leaves_traced(self, leaves):
        if self._groups is not None:  # split() comms lose the 2-level structure
            return super()._mean_leaves_traced(leaves)
        inter, intra = self._axes
        scale = 1.0 / self.size
        out = []
        for g in leaves:
            g = lax.psum(g, intra)   # fast leg: ICI
            g = lax.psum(g, inter)   # slow leg: DCN
            out.append(g * scale)
        return out


class TwoDimensionalCommunicator(HierarchicalCommunicator):
    """reduce_scatter(intra) -> allreduce(inter) -> all_gather(intra) on the
    packed flat buffer: each intra-rank shepherds 1/intra_size of the bytes
    through the slow leg — the bandwidth-optimal decomposition the reference's
    two-dimensional strategy approximates."""

    # The gather leg is a true all_gather whose output JAX's static
    # replication (VMA) tracking cannot prove replicated over the intra axis
    # (all_gather output is conservatively 'varying'), so steps built on this
    # strategy must run with the replication check off — same trade ZeRO-1
    # made for its update gather (optimizers.ZeroOptimizer.check_vma). The
    # library's own step builders and comm.shard_map read this attribute;
    # semantics are unchanged, only the static check is disabled. The win
    # over the previous one-hot-psum formulation: an all_gather of B bytes
    # moves ~B on the wire where a ring all-reduce of the B-sized slab moved
    # ~2B — the gather leg's traffic halves.
    check_vma = False

    def _mean_leaves_traced(self, leaves):
        if self._groups is not None:
            return MeshCommunicator._mean_leaves_traced(self, leaves)
        inter, intra = self._axes
        n_intra = self._mesh.shape[intra]
        scale = 1.0 / self.size
        buffers, metas = _memory_utility.pack_leaves(leaves)
        out = []
        for buf in buffers:
            n = buf.shape[0]
            pad = (-n) % n_intra
            if pad:
                buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
            shard = lax.psum_scatter(buf, intra, scatter_dimension=0, tiled=True)
            shard = lax.psum(shard, inter)
            full = lax.all_gather(shard, intra, tiled=True)
            out.append(full[:n] * scale)
        return _memory_utility.unpack_leaves(out, metas)


class SingleNodeCommunicator(MeshCommunicator):
    """Parity with ``[U] .../single_node_communicator.py``: asserts the job is
    one node (one process here) and uses the pure ICI path."""

    def __init__(self, *args, **kwargs):
        if jax.process_count() != 1:
            raise RuntimeError(
                "SingleNodeCommunicator requires a single-process launch "
                f"(got {jax.process_count()} processes); use 'tpu' or "
                "'hierarchical' for multi-host pods."
            )
        super().__init__(*args, **kwargs)
