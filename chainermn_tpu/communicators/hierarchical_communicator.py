"""Two-level (intra-node / inter-node) allreduce strategies.

Parity with ``[U] chainermn/communicators/hierarchical_communicator.py`` and
``[U] .../two_dimensional_communicator.py`` (SURVEY.md S2.3 — unverified
cites). The reference splits MPI_COMM_WORLD into intra-node and inter-node
sub-communicators and composes the allreduce from NCCL (fast, local) and MPI
(slow, cross-node) legs:

- hierarchical: NCCL reduce -> leader MPI allreduce -> NCCL bcast
- two_dimensional: NCCL reduce-scatter -> MPI allreduce -> NCCL allgather

The TPU mapping keeps the *decomposition* but swaps the legs for mesh axes:
``intra`` = ICI-local devices of one process, ``inter`` = across processes
(DCN on a multi-host pod). Two chained collectives over the factored axes let
XLA schedule the fast-leg/slow-leg split explicitly — the same reason the
reference does it by hand. On a single-slice pod (all-ICI) the flat
``TpuCommunicator`` is usually faster; these exist for multi-slice/DCN pods
and for strategy parity.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import _memory_utility
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator
from chainermn_tpu.parallel import mesh as mesh_lib


class HierarchicalCommunicator(MeshCommunicator):
    """reduce(intra) -> allreduce(inter) -> bcast(intra), expressed as two
    chained psums (a psum over ``intra`` IS reduce+bcast fused, which is how
    XLA would lower the reference's reduce/bcast pair anyway)."""

    def __init__(self, devices: Sequence[jax.Device] | None = None, mesh=None,
                 **kwargs):
        if mesh is None:
            mesh = mesh_lib.make_hierarchical_mesh(devices)
        super().__init__(
            mesh=mesh, axis_name=(mesh_lib.INTER_AXIS, mesh_lib.INTRA_AXIS),
            **kwargs,
        )

    def _mean_leaves_traced(self, leaves):
        if self._groups is not None:  # split() comms lose the 2-level structure
            return super()._mean_leaves_traced(leaves)
        inter, intra = self._axes
        scale = 1.0 / self.size
        out = []
        for g in leaves:
            g = lax.psum(g, intra)   # fast leg: ICI
            g = lax.psum(g, inter)   # slow leg: DCN
            out.append(g * scale)
        return out


class TwoDimensionalCommunicator(HierarchicalCommunicator):
    """reduce_scatter(intra) -> allreduce(inter) -> all_gather(intra) on the
    packed flat buffer: each intra-rank shepherds 1/intra_size of the bytes
    through the slow leg — the bandwidth-optimal decomposition the reference's
    two-dimensional strategy approximates."""

    def _mean_leaves_traced(self, leaves):
        if self._groups is not None:
            return MeshCommunicator._mean_leaves_traced(self, leaves)
        inter, intra = self._axes
        n_intra = self._mesh.shape[intra]
        scale = 1.0 / self.size
        buffers, metas = _memory_utility.pack_leaves(leaves)
        out = []
        for buf in buffers:
            n = buf.shape[0]
            pad = (-n) % n_intra
            if pad:
                buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
            shard = lax.psum_scatter(buf, intra, scatter_dimension=0, tiled=True)
            shard = lax.psum(shard, inter)
            # Final all-gather leg, expressed as a one-hot psum. Why not
            # lax.all_gather: JAX's VMA analysis does not mark all_gather
            # output replicated over the gathered axis, which would force
            # check_vma=False (or 'reduced'-annotated out_specs) onto every
            # user's shard_map. The trade: the slab is a full-buffer-sized
            # temporary (mostly zeros) and a ring psum over it moves ~2x the
            # bytes of the all_gather it replaces — acceptable for a parity
            # strategy whose slow leg is DCN anyway; switch to
            # all_gather(..., to='reduced') once reduced out_specs are
            # plumbed through the public API.
            idx = lax.axis_index(intra)
            slab = jnp.zeros((n_intra, shard.shape[0]), shard.dtype)
            slab = lax.dynamic_update_index_in_dim(slab, shard, idx, 0)
            full = lax.psum(slab, intra).reshape(-1)
            out.append(full[:n] * scale)
        return _memory_utility.unpack_leaves(out, metas)


class SingleNodeCommunicator(MeshCommunicator):
    """Parity with ``[U] .../single_node_communicator.py``: asserts the job is
    one node (one process here) and uses the pure ICI path."""

    def __init__(self, *args, **kwargs):
        if jax.process_count() != 1:
            raise RuntimeError(
                "SingleNodeCommunicator requires a single-process launch "
                f"(got {jax.process_count()} processes); use 'tpu' or "
                "'hierarchical' for multi-host pods."
            )
        super().__init__(*args, **kwargs)
