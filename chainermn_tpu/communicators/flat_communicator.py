"""Flat-buffer allreduce strategy.

Parity with ``[U] chainermn/communicators/flat_communicator.py`` (SURVEY.md
S2.3 — unverified cite): pack every gradient into ONE flat buffer, run a
single collective, unpack and divide by size. One large ICI collective per
dtype group amortizes launch/ring latency the way the reference's single
``MPI_Allreduce`` amortizes NIC latency.
"""

from jax import lax

from chainermn_tpu.communicators import _memory_utility
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator


class FlatCommunicator(MeshCommunicator):
    def _mean_leaves_traced(self, leaves):
        buffers, metas = _memory_utility.pack_leaves(leaves)
        reduced = [self._t_allreduce(b, "mean") for b in buffers]
        return _memory_utility.unpack_leaves(reduced, metas)
