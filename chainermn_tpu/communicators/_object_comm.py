"""Host-side (process-space) object communication.

TPU-native replacement for the reference's pickle-over-MPI object transport
(``[U] chainermn/communicators/mpi_communicator_base.py`` — ``send_obj`` /
``bcast_obj`` / ``gather_obj`` etc., built on a ``_MessageType`` header plus
chunked raw buffer sends; SURVEY.md S2.2, unverified cite).

Design: object comm is *bootstrap/side-channel* traffic (dataset scattering,
metric dicts, checkpoint agreement) — low rate, host side, DCN on multi-host
pods. Three transports, picked automatically:

1. **Single process** (includes every single-host TPU VM and the CPU test
   mesh): all "ranks" share one interpreter -> identity semantics. Zero copy.
2. **Multi-process with jax.distributed**: the coordination-service KV store
   carries pickled chunks (the same store XLA uses to bootstrap — the analog
   of the reference bootstrapping NCCL ids over MPI), with
   ``multihost_utils`` array broadcast for the large-payload bcast path.
3. **Native sidecar** (``chainermn_tpu.native.objstore``): optional C++ TCP
   object store for high-rate obj traffic; drops in as the same interface.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Sequence

import jax
import numpy as np

_CHUNK = 1 << 20  # KV-store values are strings; keep chunks modest.


class SingleProcessObjectComm:
    """Process-space object comm when there is exactly one process.

    All collectives degenerate: every "process rank" is us. ``send_obj`` /
    ``recv_obj`` still work (mailbox) so rank-agnostic library code runs
    unchanged.
    """

    def __init__(self) -> None:
        self.rank = 0
        self.size = 1
        self._mailbox: dict[tuple[int, int, int], list[Any]] = {}

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        if dest != 0:
            raise ValueError(f"dest {dest} out of range for 1-process run")
        self._mailbox.setdefault((0, dest, tag), []).append(obj)

    def recv_obj(self, source: int, tag: int = 0) -> Any:
        q = self._mailbox.get((source, 0, tag))
        if not q:
            raise RuntimeError(
                f"recv_obj(source={source}, tag={tag}): nothing sent. "
                "Host p2p in a single process requires a prior send_obj."
            )
        return q.pop(0)

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        return obj

    def gather_obj(self, obj: Any, root: int = 0) -> list[Any]:
        return [obj]

    def allgather_obj(self, obj: Any) -> list[Any]:
        return [obj]

    def allreduce_obj(self, obj: Any, reduce_func: Callable | None = None) -> Any:
        return obj

    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any:
        if objs is None:
            raise ValueError("root must supply the sequence to scatter")
        if len(objs) != 1:
            raise ValueError(f"scatter_obj needs len == size (1), got {len(objs)}")
        return objs[0]

    def barrier(self) -> None:
        pass


class KVStoreObjectComm:
    """Process-space object comm over jax.distributed's coordination KV store.

    Chunked pickled payloads with a tiny length header — the same
    header-then-chunks shape as the reference's ``_MessageType`` protocol,
    re-hosted on the KV store instead of MPI messages.

    Key freshness: collective ops use a per-instance, per-op counter that every
    process advances identically (SPMD host code calls collectives in the same
    order everywhere — the same assumption MPI collectives make). Point-to-point
    ops use a per-(src, dst, tag) sequence advanced by both endpoints of the
    pair, so uninvolved processes never desynchronize. Instances are numbered
    by construction order (again identical across SPMD processes), so two
    communicators never share a key namespace.

    GC without races: a round's keys may only be deleted once every reader has
    consumed them. Single-reader rounds (p2p recv; gather at root) are deleted
    by that reader immediately after consumption. Multi-reader rounds (bcast /
    allgather / scatter) get an ``ack/<rank>`` key from each reader; the
    round's GC owner checks acks *lazily* on its next use of the same op and
    deletes only fully-acked rounds — unacked rounds are kept (a bounded leak
    beats a 600s blocking-get failure on a slow process). If the store lacks
    directory listing, GC degrades to never-delete, which is still correct.
    """

    _instance_counter = 0

    def __init__(self) -> None:
        from jax._src import distributed  # KV store client (no public alias yet)

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "Multi-process object communication requires "
                "jax.distributed.initialize() (the reference requires "
                "mpiexec for the same reason)."
            )
        self._client = client
        self._init_protocol_state(jax.process_index(), jax.process_count())

    def _init_protocol_state(self, rank: int, size: int) -> None:
        """Transport-independent sequencing/GC state. Subclasses that swap the
        transport (``NativeObjectComm``) call this instead of ``__init__`` so
        new protocol fields can never be silently missing there."""
        self.rank = rank
        self.size = size
        self._uid = KVStoreObjectComm._instance_counter
        KVStoreObjectComm._instance_counter += 1
        self._op_seq: dict[str, int] = {}
        self._p2p_seq: dict[tuple[int, int, int], int] = {}
        # rounds this process wrote, awaiting reader acks: op -> [(key, n_acks)]
        self._pending: dict[str, list[tuple[str, int]]] = {}

    # -- chunked byte transport over the KV store ----------------------- #

    def _put(self, key: str, payload: bytes) -> None:
        import base64

        n = max(1, (len(payload) + _CHUNK - 1) // _CHUNK)
        self._client.key_value_set(f"{key}/hdr", f"{len(payload)}:{n}")
        for i in range(n):
            chunk = payload[i * _CHUNK : (i + 1) * _CHUNK]
            self._client.key_value_set(
                f"{key}/{i}", base64.b64encode(chunk).decode("ascii")
            )

    def _get(self, key: str, timeout_ms: int = 600_000) -> bytes:
        import base64

        hdr = self._client.blocking_key_value_get(f"{key}/hdr", timeout_ms)
        total, n = (int(v) for v in hdr.split(":"))
        payload = b"".join(
            base64.b64decode(self._client.blocking_key_value_get(f"{key}/{i}", timeout_ms))
            for i in range(n)
        )
        assert len(payload) == total
        return payload

    def _delete_dir(self, key_prefix: str) -> None:
        try:  # best-effort GC; the store tolerates missing keys
            self._client.key_value_delete(key_prefix + "/")
        except Exception:
            pass

    def _op_key(self, op: str) -> str:
        """Advance the collective counter for ``op`` (no GC here — see
        class docstring for the ack-based scheme)."""
        seq = self._op_seq.get(op, 0)
        self._op_seq[op] = seq + 1
        return f"chainermn_tpu/obj/{self._uid}/{op}/{seq}"

    def _p2p_key(self, src: int, dst: int, tag: int) -> str:
        pair = (src, dst, tag)
        seq = self._p2p_seq.get(pair, 0)
        self._p2p_seq[pair] = seq + 1
        return f"chainermn_tpu/obj/{self._uid}/p2p/{src}/{dst}/{tag}/{seq}"

    # -- ack-based lazy GC ---------------------------------------------- #

    def _ack(self, round_key: str) -> None:
        self._client.key_value_set(f"{round_key}/ack/{self.rank}", "1")

    def _count_acks(self, prefix: str) -> int:
        """Transport hook (the native sidecar overrides it): how many ack
        keys exist under ``prefix``."""
        return len(self._client.key_value_dir_get(prefix))

    def _gc_pending(self, op: str) -> None:
        """Delete previously-written rounds of ``op`` whose readers have all
        acked. Every process calls this on every use of ``op`` (its pending
        list only contains rounds *it* wrote, so ownership follows the writer
        even when roots rotate). Failures mean 'keep' — leak, never race."""
        pend = self._pending.setdefault(op, [])
        keep = []
        for rk, expected_acks in pend:
            done = False
            try:
                done = self._count_acks(f"{rk}/ack/") >= expected_acks
            except Exception:
                done = False
            if done:
                self._delete_dir(rk)
            else:
                keep.append((rk, expected_acks))
        self._pending[op] = keep

    # -- collectives ----------------------------------------------------- #

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        self._put(self._p2p_key(self.rank, dest, tag), pickle.dumps(obj))

    def recv_obj(self, source: int, tag: int = 0) -> Any:
        key = self._p2p_key(source, self.rank, tag)
        out = pickle.loads(self._get(key))
        self._delete_dir(key)  # sole reader: immediate GC is race-free
        return out

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        key = self._op_key("bcast")
        self._gc_pending("bcast")
        if self.rank == root:
            self._put(f"{key}/payload", pickle.dumps(obj))
            self._pending["bcast"].append((key, self.size - 1))
            return obj
        out = pickle.loads(self._get(f"{key}/payload"))
        self._ack(key)
        return out

    def gather_obj(self, obj: Any, root: int = 0) -> list[Any] | None:
        key = self._op_key("gather")
        self._put(f"{key}/val/{self.rank}", pickle.dumps(obj))
        if self.rank != root:
            return None
        out = [pickle.loads(self._get(f"{key}/val/{r}")) for r in range(self.size)]
        self._delete_dir(key)  # root is the only reader and has read all
        return out

    def allgather_obj(self, obj: Any) -> list[Any]:
        key = self._op_key("allgather")
        self._gc_pending("allgather")
        self._put(f"{key}/val/{self.rank}", pickle.dumps(obj))
        out = [pickle.loads(self._get(f"{key}/val/{r}")) for r in range(self.size)]
        self._ack(key)
        if self.rank == 0:  # one designated janitor per round is enough
            self._pending["allgather"].append((key, self.size))
        return out

    def allreduce_obj(self, obj: Any, reduce_func: Callable | None = None) -> Any:
        import functools

        gathered = self.allgather_obj(obj)
        if reduce_func is None:
            reduce_func = lambda a, b: a + b  # noqa: E731 — reference default: sum
        return functools.reduce(reduce_func, gathered)

    def scatter_obj(self, objs: Sequence[Any] | None, root: int = 0) -> Any:
        key = self._op_key("scatter")
        self._gc_pending("scatter")
        if self.rank == root:
            if objs is None or len(objs) != self.size:
                raise ValueError("root must supply a sequence of length size")
            for r, o in enumerate(objs):
                if r != root:
                    self._put(f"{key}/val/{r}", pickle.dumps(o))
            self._pending["scatter"].append((key, self.size - 1))
            return objs[root]
        out = pickle.loads(self._get(f"{key}/val/{self.rank}"))
        self._ack(key)
        return out

    def barrier(self) -> None:
        self.allgather_obj(None)


def create_object_comm():
    """Pick the transport for this launch (native sidecar > KV store > local)."""
    if jax.process_count() == 1:
        return SingleProcessObjectComm()
    try:
        from chainermn_tpu.native import objstore  # optional C++ sidecar

        if objstore.available():
            return objstore.NativeObjectComm()
    except Exception:
        pass
    return KVStoreObjectComm()
