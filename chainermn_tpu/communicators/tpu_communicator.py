"""The flagship strategy: fused flat allreduce with optional compressed dtype.

TPU analog of ``[U] chainermn/communicators/pure_nccl_communicator.py``
(SURVEY.md S2.3/S2.8 — unverified cite). The reference's pure-NCCL strategy is
(a) one NCCL ring over ALL ranks, (b) fused pack+cast kernels so the wire
dtype can be fp16 (``allreduce_grad_dtype``), (c) a dedicated CUDA stream.
The TPU mapping:

- (a) one collective over the whole mesh axis -> XLA's ICI allreduce;
- (b) ``allreduce_grad_dtype='bfloat16'`` casts the packed buffer before the
  ``psum`` and back after (divide folded in) — bf16 keeps fp32's exponent
  range, so unlike the reference's fp16 path there is no overflow hazard;
  XLA fuses the casts into the collective's neighbourhood, which is exactly
  what the reference's hand-written pack+cast kernel buys;
- (c) stream overlap -> XLA's async collectives + the double-buffering
  optimizer option (``optimizers.py``) for explicit one-step-stale overlap.
"""

from __future__ import annotations

import numpy as np

from chainermn_tpu.communicators import _memory_utility
from chainermn_tpu.communicators.mesh_communicator import MeshCommunicator


class TpuCommunicator(MeshCommunicator):
    def __init__(self, *args, allreduce_grad_dtype=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.allreduce_grad_dtype = (
            np.dtype(allreduce_grad_dtype) if allreduce_grad_dtype else None
        )

    def _copy_strategy_state(self, sub):
        sub.allreduce_grad_dtype = self.allreduce_grad_dtype

    def _mean_leaves_traced(self, leaves):
        buffers, metas = _memory_utility.pack_leaves(leaves)
        # The wire dtype compresses bytes crossing ICI. With one rank on the
        # axis there IS no wire: the psum is identity (XLA deletes it) but a
        # bf16 round-trip is lossy, so the compiler must keep both casts —
        # measured at +2.5ms/step on the round-5 v5e ResNet-50 headline for
        # zero traffic saved, and it quantizes the gradients. Skip it.
        wire = self.allreduce_grad_dtype if self.size > 1 else None
        out = []
        for buf in buffers:
            orig = buf.dtype
            if wire is not None and orig != wire:
                buf = buf.astype(wire)
            buf = self._t_allreduce(buf, "sum")
            out.append(buf.astype(orig) * (1.0 / self.size))
        return _memory_utility.unpack_leaves(out, metas)
