"""Gradient flat-packing helpers.

TPU re-design of ``[U] chainermn/communicators/_memory_utility.py``
(SURVEY.md S2.9 — unverified cite): the reference JIT-compiles CUDA kernels to
gather many parameter gradients into one flat pinned/device buffer, cast
fp32<->fp16, and divide by comm size. On TPU none of that needs hand-written
kernels — XLA fuses concatenate/cast/scale into the surrounding program — so
this module is pure tracing-level plumbing: flatten a pytree of gradient
leaves into one buffer **per dtype** (the reference assumes homogeneous fp32;
modern mixed bf16/f32 trees get one buffer each) and restore it.

Why flat at all, when XLA could fuse per-leaf collectives? One large collective
per dtype amortizes ICI latency exactly the way the reference's single
``MPI_Allreduce``/``ncclAllReduce`` on the packed buffer amortizes NIC/ring
latency, and gives the compiler one contiguous buffer to schedule around.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class _PackMeta:
    dtype: np.dtype
    indices: tuple[int, ...]      # positions in the original leaf list
    shapes: tuple[tuple[int, ...], ...]
    sizes: tuple[int, ...]


def pack_leaves(leaves: list[jax.Array]) -> tuple[list[jax.Array], list[_PackMeta]]:
    """Group leaves by dtype and concatenate each group into one flat buffer.

    Returns (buffers, metas); ``unpack_leaves`` inverts. Order inside a buffer
    follows original leaf order, so pack/unpack round-trips exactly.
    """
    by_dtype: dict[np.dtype, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)
    buffers, metas = [], []
    for dtype, idxs in by_dtype.items():
        group = [jnp.ravel(leaves[i]) for i in idxs]
        buffers.append(jnp.concatenate(group) if len(group) > 1 else group[0])
        metas.append(
            _PackMeta(
                dtype=dtype,
                indices=tuple(idxs),
                shapes=tuple(tuple(leaves[i].shape) for i in idxs),
                sizes=tuple(int(np.prod(leaves[i].shape or (1,))) for i in idxs),
            )
        )
    return buffers, metas


def unpack_leaves(buffers: list[jax.Array], metas: list[_PackMeta]) -> list[jax.Array]:
    n = sum(len(m.indices) for m in metas)
    out: list = [None] * n
    for buf, meta in zip(buffers, metas):
        offset = 0
        for idx, shape, size in zip(meta.indices, meta.shapes, meta.sizes):
            out[idx] = jax.lax.dynamic_slice_in_dim(buf, offset, size).reshape(shape)
            offset += size
    return out
