"""Differentiable collective communication.

Re-design of ``[U] chainermn/functions/collective_communication.py``
(SURVEY.md S2.10 — unverified cite). The reference implements each collective
as a FunctionNode whose backward is the hand-written transposed collective
(allgather <-> reduce-scatter-sum, alltoall <-> alltoall, bcast <-> gather+sum
at root, scatter <-> gather). Here every forward lowers to a ``lax``
collective, and JAX's transpose rules derive exactly those backwards — the
tests assert the transposition property numerically.

All functions are dual-context like the communicator methods: traced inside
``shard_map`` (per-rank local values) or eager on rank-major arrays.
"""

from __future__ import annotations

__all__ = ["allreduce", "allgather", "alltoall", "bcast", "gather", "scatter"]


def allreduce(x, communicator, op: str = "sum"):
    """Differentiable allreduce. Reference note: chainermn's differentiable
    ``allreduce`` divides by size in backward (mean-like semantics for
    loss-parallel training); we keep forward-op symmetry instead — the
    backward of sum-allreduce is sum-allreduce of the cotangents, which is
    what psum's transpose provides."""
    return communicator.allreduce(x, op)


def allgather(x, communicator):
    """Differentiable allgather; backward reduces each rank's cotangent slice
    back to its owner (reduce-scatter-sum) via all_gather's transpose."""
    return communicator.allgather(x)


def alltoall(x, communicator):
    """Differentiable alltoall; backward is the transposed alltoall."""
    return communicator.alltoall(x)


def bcast(x, communicator, root: int = 0):
    """Differentiable broadcast; backward sums cotangents onto root (the
    transpose of the masked-psum forward)."""
    return communicator.bcast(x, root)


def gather(x, communicator, root: int = 0):
    """Differentiable gather; backward scatters root's cotangent slices back."""
    return communicator.gather(x, root)


def scatter(x, communicator, root: int = 0):
    """Differentiable scatter; backward gathers cotangents onto root."""
    return communicator.scatter(x, root)
