"""Differentiable communication ops (``[U] chainermn/functions/`` parity)."""

from chainermn_tpu.functions.point_to_point import (
    DelegateVariable,
    current_rank,
    pseudo_connect,
    rank_context,
    recv,
    send,
)
from chainermn_tpu.functions.collective_communication import (
    allgather,
    allreduce,
    alltoall,
    bcast,
    gather,
    scatter,
)

__all__ = [
    "DelegateVariable",
    "rank_context",
    "current_rank",
    "send",
    "recv",
    "pseudo_connect",
    "allreduce",
    "allgather",
    "alltoall",
    "bcast",
    "gather",
    "scatter",
]
