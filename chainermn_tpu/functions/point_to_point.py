"""Differentiable point-to-point communication.

Re-design of ``[U] chainermn/functions/point_to_point_communication.py``
(SURVEY.md S2.10 — unverified cite). The reference's ``send`` returns a
zero-sized *delegate variable* that keeps the autograd edge alive across the
process boundary, ``recv`` materializes the tensor on the peer, and their
backwards run the *transposed* communication (send.backward receives the
gradient, recv.backward sends it); ``pseudo_connect`` grafts the delegate onto
another variable so disjoint per-process subgraphs backprop in a deadlock-free
order.

The SPMD inversion (DESIGN.md): both endpoints of a p2p transfer live in ONE
traced program, so the primitive is a single ``ppermute`` whose transpose rule
*is* the reference's hand-written transposed backward — JAX's autodiff derives
it. What remains of the reference machinery:

- ``send``/``recv`` keep their per-rank calling convention via a *rank
  context*: code that plays logical rank r (a ``MultiNodeChainList`` branch,
  or a user's ``with rank_context(r):`` block) calls ``send(x, comm, rank=d)``
  and the (r, d) pair builds the static permutation.
- The delegate variable survives as the carrier of the in-flight payload
  between the ``send`` call site and the ``recv`` call site (in SPMD the
  payload must travel through the program; zeros off the destination rank).
  Its secondary reference role — ordering disconnected subgraphs — is
  preserved by ``pseudo_connect`` via ``lax.optimization_barrier``.
- Deadlock-freedom is structural: one program, one collective schedule, no
  per-process blocking calls to mis-order. The reference's subtlest failure
  mode (S3.3: mis-ordered send/recv pairs hanging in MPI) cannot be
  expressed.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

_RANK_CONTEXT: list[int] = []


@contextlib.contextmanager
def rank_context(rank: int):
    """Declare that the enclosed code plays logical rank ``rank``.

    The SPMD replacement for "this code runs on process r": inside, ``send``/
    ``recv`` infer their local endpoint. Nestable; ``MultiNodeChainList``
    manages it per component.
    """
    _RANK_CONTEXT.append(int(rank))
    try:
        yield
    finally:
        _RANK_CONTEXT.pop()


def current_rank() -> int:
    if not _RANK_CONTEXT:
        raise RuntimeError(
            "send/recv need a logical rank: wrap the call in "
            "`with chainermn_tpu.functions.rank_context(r):` (or use "
            "MultiNodeChainList, which does this for you)."
        )
    return _RANK_CONTEXT[-1]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DelegateVariable:
    """In-flight p2p payload + autograd edge carrier.

    Parity with the reference's zero-sized delegate: holds the edge that makes
    backward communication happen in transposed order. In SPMD it additionally
    carries the payload itself (valid on the destination rank, zeros
    elsewhere — a ``ppermute`` with a partial permutation yields zeros on
    non-destinations, which is exactly the "empty variable" the reference
    returns on the source side).
    """

    data: Any
    src: int = dataclasses.field(metadata={"static": True})
    dst: int = dataclasses.field(metadata={"static": True})

    def tree_flatten(self):
        return (self.data,), (self.src, self.dst)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)


def send(x, communicator, rank: int, tag: int = 0) -> DelegateVariable:
    """Send ``x`` from the current logical rank to ``rank``.

    Returns a delegate variable; pass it to the matching ``recv`` (directly,
    or positionally through your program the way the reference threads
    delegates). Differentiable: the cotangent arriving at the destination is
    routed back to ``x`` by the ppermute transpose.
    """
    del tag  # payloads are positional in SPMD; kept for signature parity
    src = current_rank()
    if not 0 <= rank < communicator.size:
        raise ValueError(f"send: peer rank {rank} out of range [0, {communicator.size})")
    if rank == src:
        raise ValueError("send: source and destination rank are both "
                         f"{src}; self-sends are the identity — drop the send")
    moved = jax.tree_util.tree_map(
        lambda leaf: communicator.ppermute(leaf, [(src, rank)]), x
    )
    return DelegateVariable(moved, src=src, dst=rank)


def recv(communicator, rank: int, delegate_variable: DelegateVariable | None = None,
         tag: int = 0, force_tuple: bool = False):
    """Receive the payload sent from ``rank`` to the current logical rank.

    ``delegate_variable`` is the value returned by the matching ``send``. The
    reference's recv(comm, rank) can omit it only because MPI delivers by
    (peer, tag) out-of-band; in one SPMD program the payload must arrive
    through the program, so the delegate is required here — a structural
    difference, documented, not hidden.
    """
    del tag
    dst = current_rank()
    if delegate_variable is None:
        raise ValueError(
            "recv requires the delegate_variable returned by the matching "
            "send: in a single SPMD program the payload travels through the "
            "traced graph, not out-of-band (see functions/point_to_point.py "
            "docstring)."
        )
    if delegate_variable.src != rank or delegate_variable.dst != dst:
        raise ValueError(
            f"recv endpoint mismatch: delegate carries {delegate_variable.src}"
            f"->{delegate_variable.dst}, recv expects {rank}->{dst}"
        )
    data = delegate_variable.data
    if force_tuple and not isinstance(data, tuple):
        return (data,)
    return data


def pseudo_connect(delegate_variable: DelegateVariable | None, *actual_variables):
    """Graft a delegate's dependency onto ``actual_variables``.

    Parity with the reference's ``pseudo_connect``: ensures the communication
    captured by ``delegate_variable`` is ordered with (and its backward
    reached from) the returned value. Implemented with
    ``lax.optimization_barrier`` so XLA cannot reorder or DCE the transfer,
    and the delegate's autograd edge joins the returned value's graph.
    """
    if delegate_variable is None:
        return actual_variables if len(actual_variables) > 1 else actual_variables[0]
    dleaves = jax.tree_util.tree_leaves(delegate_variable.data)
    tied = []
    for v in actual_variables:
        leaves, treedef = jax.tree_util.tree_flatten(v)
        out = lax.optimization_barrier(tuple(leaves) + tuple(dleaves))
        tied.append(jax.tree_util.tree_unflatten(treedef, out[: len(leaves)]))
    return tuple(tied) if len(tied) > 1 else tied[0]
