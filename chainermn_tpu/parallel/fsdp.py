"""FSDP (ZeRO-3): parameters, gradients, and optimizer state sharded at rest.

TPU-idiomatic extension BEYOND the reference (SURVEY.md S2.16 marks every
form of sharded-state data parallelism absent upstream — params and moments
are fully replicated there, and ZeRO-1 is this repo's `create_zero_optimizer`).

On TPU, FSDP is not a wrapper object that moves bytes on a side channel the
way GPU implementations shuttle flat buffers around NCCL process groups — it
is a *layout*. Parameters live scattered over the data-parallel mesh axis;
the training step is ONE global jitted program whose batch axis is sharded
over the same mesh axis; and XLA's SPMD partitioner materializes each weight
where it is used (all_gather on use, forward and backward — the "unshard on
demand" half of ZeRO-3) and scatters the gradients back (reduce_scatter — the
"shard the reduction" half), scheduling both behind adjacent compute. The
optimizer update then runs entirely on 1/n-sized shards, so per-device bytes
for params + grads + moments are ``full/n`` plus one transiently-gathered
layer — the ZeRO-3 memory profile, with the collective schedule chosen by the
compiler instead of hand-written bucketing code.

Sharding rule: each leaf is split along its LARGEST axis divisible by the
mesh size (ties -> the earlier axis); leaves with no divisible axis stay
replicated (biases, scalars, odd shapes — a few KB). The rule is a pure
function of the leaf's *shape*, so the same rule applied to the optimizer
state automatically co-shards every moment with its parameter (``mu``/``nu``
have the parameter's shape) and replicates step counters.

Usage::

    comm = chainermn_tpu.create_communicator("tpu")
    variables = fsdp_shard(model.init(key, x), comm)       # scatter at rest
    opt_state = fsdp_shard(jax.jit(opt.init)(variables["params"]), comm)
    step = jit_fsdp_train_step(model, opt, comm)
    variables, opt_state, loss = step(variables, opt_state, images, labels)

Note the plain optax optimizer: there is NO multi-node wrapper here. The loss
is the global-batch mean of one global program, so the cross-rank gradient
mean is not an explicit collective we insert — it falls out of
differentiating a global mean wrt scattered parameters (XLA emits the
reduce_scatter). BatchNorm under this step likewise computes *global* batch
statistics — sync-BN semantics with no MNBN machinery. That also means BN
models are NOT numerically identical across layouts: the shard_map DP step
normalizes each rank's local batch, this one the global batch. BN-free
models (the parity test's subject) match exactly.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


def _shard_axis(comm: CommunicatorBase, axis: Optional[str]) -> str:
    """Resolve the mesh axis the weights scatter over.

    Flat communicator: its one axis (``axis`` may be omitted). Hierarchical
    communicator: ``axis`` picks which level shards — passing the *intra*
    (ICI) axis gives HSDP: weights scattered within each fast domain and
    replicated across the slow (inter/DCN) one, so the per-use all_gathers
    ride ICI while cross-host traffic stays one gradient all-reduce.
    """
    if getattr(comm, "_groups", None) is not None:
        raise ValueError("FSDP does not support split() sub-communicators")
    axes = comm.axis_name
    if isinstance(axes, str):
        if axis is not None and axis != axes:
            raise ValueError(f"axis {axis!r} is not the communicator's "
                             f"axis {axes!r}")
        return axes
    if axis is None:
        raise ValueError(
            f"hierarchical communicator has axes {axes!r}: pass axis=... to "
            "choose the level the weights scatter over (the intra/ICI axis "
            "for HSDP)"
        )
    if axis not in axes:
        raise ValueError(f"axis {axis!r} not in communicator axes {axes!r}")
    return axis


def spec_for_shape(shape, n: int, axis: str) -> P:
    """The FSDP PartitionSpec for one leaf shape: shard the largest
    ``n``-divisible axis, earlier axis on ties; replicate if none."""
    best = None
    for i, d in enumerate(shape):
        if d % n == 0 and d > 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    return P(*(axis if i == best else None for i in range(len(shape))))


def fsdp_spec(tree, comm: CommunicatorBase, axis: Optional[str] = None):
    """Per-leaf PartitionSpecs scattering ``tree`` over ``axis`` (see
    :func:`_shard_axis`; omit on a flat communicator)."""
    ax = _shard_axis(comm, axis)
    n = comm.mesh.shape[ax]
    return jax.tree_util.tree_map(
        lambda l: spec_for_shape(jax.numpy.shape(l), n, ax), tree
    )


def fsdp_shard(tree, comm: CommunicatorBase, axis: Optional[str] = None):
    """Place ``tree`` scattered over the mesh per :func:`fsdp_spec`."""
    mesh = comm.mesh
    return jax.tree_util.tree_map(
        lambda l, s: jax.device_put(l, NamedSharding(mesh, s)),
        tree,
        fsdp_spec(tree, comm, axis),
    )


def _constrain(tree, comm: CommunicatorBase, axis: Optional[str] = None):
    """with_sharding_constraint to the FSDP layout (traced-side: shapes are
    static, so the same shape rule applies)."""
    mesh = comm.mesh
    return jax.tree_util.tree_map(
        lambda l, s: jax.lax.with_sharding_constraint(l, NamedSharding(mesh, s)),
        tree,
        fsdp_spec(tree, comm, axis),
    )


def jit_fsdp_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    donate: bool = True,
    train_kwargs: Optional[dict] = None,
    label_smoothing: float = 0.0,
    axis: Optional[str] = None,
) -> Callable:
    """The FSDP classification train step (same call shape as
    ``jit_train_step``): ``step(variables, opt_state, images, labels)``.

    ``variables``/``opt_state`` must be placed with :func:`fsdp_shard` (same
    ``axis``); the batch is global (leading axis = global batch) and is
    constrained onto the mesh inside the program, so callers may pass
    ordinary host arrays. On a hierarchical communicator, ``axis`` picks the
    scatter level (HSDP — see :func:`_shard_axis`): the batch still shards
    over ALL mesh axes, so the partitioner emits intra-domain all_gathers
    for the weights and a cross-domain gradient all-reduce.

    Unlike ``jit_train_step`` this is NOT a ``shard_map`` program: there is no
    per-rank body and no explicit gradient collective — one global program,
    and the partitioner owns the byte movement (module docstring). For the
    same reason the communicator's gradient-strategy knobs do NOT apply here:
    ``allreduce_grad_dtype`` (the compressed-wire setting) and double
    buffering configure the explicit collective in the shard_map step, and
    this step has no such collective to configure — a warning is emitted if
    the communicator carries a wire dtype so the setting never goes silently
    unused.
    """
    _shard_axis(comm, axis)
    if getattr(comm, "allreduce_grad_dtype", None) is not None:
        import warnings

        warnings.warn(
            "jit_fsdp_train_step ignores the communicator's "
            f"allreduce_grad_dtype={comm.allreduce_grad_dtype!r}: the FSDP "
            "step's gradient reduce_scatter is inserted by the XLA "
            "partitioner in the gradient's own dtype, not by the "
            "communicator strategy",
            stacklevel=2,
        )
    train_kwargs = dict(train_kwargs or {})

    def step(variables, opt_state, images, labels):
        images = jax.lax.with_sharding_constraint(
            images, NamedSharding(comm.mesh, comm.data_spec)
        )
        labels = jax.lax.with_sharding_constraint(
            labels, NamedSharding(comm.mesh, comm.data_spec)
        )
        params = variables["params"]
        rest = {k: v for k, v in variables.items() if k != "params"}
        mutable = list(rest.keys())
        from chainermn_tpu.training import classification_loss_fn

        loss_fn = classification_loss_fn(
            model, rest, mutable, images, labels, train_kwargs, label_smoothing
        )
        (loss, updated), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # keep the gradients scattered (this is what makes the backward's
        # cross-device reduction a reduce_scatter rather than an all-reduce)
        grads = _constrain(grads, comm, axis)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # pin the updated state back to the at-rest layout so donation reuses
        # the input buffers and nothing silently re-replicates
        params = _constrain(params, comm, axis)
        opt_state = _constrain(opt_state, comm, axis)
        new_variables = {"params": params, **_constrain(updated, comm, axis)}
        return new_variables, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
