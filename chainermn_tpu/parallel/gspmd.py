"""GSPMD tensor parallelism: Megatron sharding as a *layout*, weights at rest.

Round 3's explicit TP (:mod:`chainermn_tpu.parallel.tensor`) buys compute
and activation sharding but stores every parameter replicated — at real LM
sizes the replicated matrices OOM a chip the sharded layout would fit. This
module closes that gap the most TPU-idiomatic way there is: keep the DENSE
``TransformerLM`` code, annotate each parameter with its Megatron
partition (heads and FFN columns over the tensor axis, vocab over the head),
run the train step under **plain jit**, and let XLA's SPMD partitioner
insert the collectives the explicit implementation hand-writes. Per-device
parameter AND optimizer-state bytes drop to ~1/n at rest (proven by
``sharding.shard_shape`` in tests) — no gather-on-use for the per-block
matmuls: each consumes exactly its local shard, costing Megatron's two
psums per block. The vocab-sharded embedding and head DO add collectives
(a cross-shard lookup gather, and the logits re-materialize across the
axis for the replicated cross entropy) — the price of storing the two
largest tables at 1/n.

Two entry points:

- :func:`megatron_param_specs` / :func:`megatron_shard` — the per-leaf
  ``PartitionSpec`` tree for a dense ``TransformerLM`` param tree (path
  rules below), and placement onto the communicator's mesh.
- :func:`gspmd_lm_train_step` — the plain-jit LM train step over those
  layouts (optional ``dp_axis`` shards the batch for dp x tp on a 2-axis
  mesh).

Sharding rules (leaves not matched stay replicated — layernorms, biases of
row-parallel outputs):

====================  =======================  ===========================
leaf                  shape                    spec
====================  =======================  ===========================
``qkv/kernel``        ``[d, 3, H, dh]``        ``P(None, None, tp, None)``
``qkv/bias``          ``[3, H, dh]``           ``P(None, tp, None)``
``proj/kernel``       ``[H, dh, d]``           ``P(tp, None, None)``
``Dense_0/kernel``    ``[d, ff]``              ``P(None, tp)``
``Dense_0/bias``      ``[ff]``                 ``P(tp)``
``Dense_1/kernel``    ``[ff, d]``              ``P(tp, None)``
``lm_head/kernel``    ``[d, V]``               ``P(None, tp)``
``lm_head/bias``      ``[V]``                  ``P(tp)``
``embed/embedding``   ``[V, d]``               ``P(tp, None)``
``moe/w1|w2|b1|b2``   ``[E, ...]``             ``P(tp, ...)`` (expert dim)
====================  =======================  ===========================

MoE under plain jit uses :class:`GShardMoE` (``TransformerLM(...,
moe_impl='gshard')``): the einsum-dispatch formulation — no explicit
``all_to_all``; with the expert stack sharded over the axis the partitioner
derives the exchange. The shard_map ``ExpertParallelMLP`` remains the
explicit-collective twin (``moe_impl='ep'``, the default).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


def _ends(parts, *names) -> bool:
    """Whole-component suffix match: ``('pos_embed', 'embedding')`` does NOT
    match ``('embed', 'embedding')`` — ``str.endswith`` would, silently
    handing the position table the vocab-embedding spec and a cross-shard
    gather per lookup."""
    return tuple(parts[-len(names):]) == names


# Leaves the Megatron layout stores replicated ON PURPOSE: norm vectors,
# row-parallel output biases, the position table, the router. Sharding any
# of these buys ~nothing (tiny) or costs a gather per use (pos_embed).
def _known_replicated(parts) -> bool:
    if any(p.startswith("LayerNorm") for p in parts):
        return True
    tail = tuple(parts[-2:])
    return tail in {
        ("pos_embed", "embedding"),
        ("proj", "bias"),
        ("Dense_1", "bias"),
        ("gate", "kernel"),
        ("gate", "bias"),
    }


def _leaf_rule(parts, shape, tp: str, n: int):
    """``(spec, status)`` for one dense-TransformerLM leaf (rules in the
    module docstring). Status distinguishes the three ways a leaf ends up
    replicated: ``undividable`` (rule hit, dim % n != 0),
    ``known_replicated`` (intentional), ``unmatched`` (NO rule knows this
    leaf — the silent-layout-loss case :func:`megatron_param_specs` makes
    loud)."""

    def pick(spec, dim_idx):
        if shape[dim_idx] % n == 0:
            return spec, "sharded"
        return P(), "undividable"

    if _ends(parts, "qkv", "kernel") and len(shape) == 4:
        return pick(P(None, None, tp, None), 2)
    if _ends(parts, "qkv", "bias") and len(shape) == 3:
        return pick(P(None, tp, None), 1)
    if _ends(parts, "proj", "kernel") and len(shape) == 3:
        return pick(P(tp, None, None), 0)
    if _ends(parts, "Dense_0", "kernel") and len(shape) == 2:
        return pick(P(None, tp), 1)
    if _ends(parts, "Dense_0", "bias") and len(shape) == 1:
        return pick(P(tp), 0)
    if _ends(parts, "Dense_1", "kernel") and len(shape) == 2:
        return pick(P(tp, None), 0)
    if _ends(parts, "lm_head", "kernel") and len(shape) == 2:
        return pick(P(None, tp), 1)
    if _ends(parts, "lm_head", "bias") and len(shape) == 1:
        return pick(P(tp), 0)
    if _ends(parts, "embed", "embedding") and len(shape) == 2:
        return pick(P(tp, None), 0)
    # GShard MoE expert stacks: shard the expert dim
    for name in ("w1", "b1", "w2", "b2"):
        if _ends(parts, "moe", name) and shape:
            return pick(P(tp, *(None,) * (len(shape) - 1)), 0)
    if _known_replicated(parts):
        return P(), "known_replicated"
    return P(), "unmatched"


def _path_parts(path):
    return tuple(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def _leaf_bytes(leaf) -> int:
    shape = jnp.shape(leaf)
    size = 1
    for d in shape:
        size *= d
    return size * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize


# Unmatched-replicated bytes above this trip the warning (strict mode raises
# on ANY unmatched leaf). Norm-sized vectors stay under it at any real d.
_UNMATCHED_WARN_BYTES = 1 << 20


def megatron_param_specs(params, tp_axis: str, n_tp: int, *,
                         strict: bool = False, report: bool = False):
    """Per-leaf ``PartitionSpec`` tree for a dense ``TransformerLM`` param
    tree (or any tree using the same layer names).

    Rule matching is by path NAME, so a renamed module would silently fall
    back to replicated — the exact layout loss this module exists to
    prevent. Defense: leaves matching no rule and not on the
    known-replicated list are reported — ``strict=True`` raises on any;
    otherwise a warning fires when they exceed ~1 MiB total.
    ``report=True`` returns ``(specs, report_dict)`` with per-status paths
    and byte totals.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves, statuses = [], []
    for p, l in flat:
        spec, status = _leaf_rule(_path_parts(p), jnp.shape(l), tp_axis, n_tp)
        leaves.append(spec)
        statuses.append(status)
    rep = {s: [] for s in
           ("sharded", "undividable", "known_replicated", "unmatched")}
    bytes_by = dict.fromkeys(rep, 0)
    for (p, l), status in zip(flat, statuses):
        path = "/".join(_path_parts(p))
        rep[status].append(path)
        bytes_by[status] += _leaf_bytes(l)
    if rep["unmatched"]:
        msg = (
            f"megatron_param_specs: {len(rep['unmatched'])} leaves "
            f"({bytes_by['unmatched']} bytes) matched no sharding rule and "
            "are not known-replicated — they will be stored REPLICATED on "
            f"every device: {rep['unmatched'][:8]}"
        )
        if strict:
            raise ValueError(msg)
        if bytes_by["unmatched"] > _UNMATCHED_WARN_BYTES:
            import warnings

            warnings.warn(msg, stacklevel=2)
    specs = jax.tree_util.tree_unflatten(treedef, leaves)
    if report:
        return specs, {"paths": rep, "bytes": bytes_by}
    return specs


def _resolve_tp_axis(comm: CommunicatorBase, tp_axis: Optional[str]) -> str:
    axes = comm.axis_name
    if isinstance(axes, str):
        if tp_axis is not None and tp_axis != axes:
            raise ValueError(
                f"tp_axis {tp_axis!r} is not the communicator's axis {axes!r}")
        return axes
    if tp_axis is None or tp_axis not in axes:
        raise ValueError(
            f"multi-axis mesh {axes!r}: pass tp_axis= naming the tensor axis")
    return tp_axis


def megatron_shard(params, comm: CommunicatorBase,
                   tp_axis: Optional[str] = None):
    """Place a dense-LM param tree (or its optimizer state via
    :func:`megatron_opt_shard`) in the Megatron at-rest layout."""
    ax = _resolve_tp_axis(comm, tp_axis)
    n = comm.mesh.shape[ax]
    specs = megatron_param_specs(params, ax, n)
    return jax.tree_util.tree_map(
        lambda l, s: jax.device_put(l, NamedSharding(comm.mesh, s)),
        params, specs,
    )


def _opt_specs(optimizer, opt_state, param_specs):
    """Spec tree for an optimizer state: every params-shaped leaf (adam
    mu/nu, momentum, ...) gets its parameter's spec; anything else (step
    counts) is replicated. Single-sourced so placement
    (:func:`megatron_opt_shard`) and the step's per-iteration constraint
    can never diverge."""
    return optax.tree_map_params(
        optimizer, lambda _, s: s, opt_state, param_specs,
        transform_non_params=lambda _: P(),
    )


def megatron_opt_shard(optimizer, opt_state, params,
                       comm: CommunicatorBase,
                       tp_axis: Optional[str] = None):
    """Co-shard optimizer state with its parameters (see
    :func:`_opt_specs`)."""
    ax = _resolve_tp_axis(comm, tp_axis)
    n = comm.mesh.shape[ax]
    specs = megatron_param_specs(params, ax, n)
    return jax.tree_util.tree_map(
        lambda l, s: jax.device_put(l, NamedSharding(comm.mesh, s)),
        opt_state, _opt_specs(optimizer, opt_state, specs),
        is_leaf=lambda x: isinstance(x, P),
    )


def gspmd_lm_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    tp_axis: Optional[str] = None,
    dp_axis: Optional[str] = None,
    donate: bool = True,
    moe_aux_weight: float = 0.01,
) -> Callable:
    """Plain-jit Megatron-TP LM train step: ``step(params, opt_state,
    tokens, targets) -> (params, opt_state, loss, stats)`` — the same
    uniform arity as :func:`chainermn_tpu.training.jit_lm_train_step`:
    ``stats`` is ``{}`` for dense models and ``{'moe_drop_frac': ...}``
    for gshard-MoE models (the capacity-drop telemetry is visible at
    GSPMD scale too, not only under the shard_map step).

    ``params``/``opt_state`` should be placed with :func:`megatron_shard` /
    :func:`megatron_opt_shard` (the step re-constrains them each iteration,
    so donation keeps the layout without re-sharding traffic). ``model`` is
    the DENSE ``TransformerLM`` — no ``tensor_axis``; with
    ``moe_impl='gshard'`` the expert stacks shard over the same axis.
    ``dp_axis`` (on a 2-axis mesh) shards the batch for dp x tp; otherwise
    the batch is replicated (pure TP).
    """
    if getattr(model, "tensor_axis", None) is not None or (
            getattr(model, "sequence_axis", None) is not None):
        raise ValueError(
            "gspmd_lm_train_step takes the DENSE model: the partitioner "
            "derives the TP collectives from the param layout — rebuild "
            "without tensor_axis/sequence_axis"
        )
    if getattr(model, "moe_experts", 0) and (
            getattr(model, "moe_impl", "ep") != "gshard"):
        raise ValueError(
            "MoE under the gspmd step needs moe_impl='gshard' (the "
            "shard_map ExpertParallelMLP's collectives need an axis "
            "context plain jit does not bind)"
        )
    if getattr(comm, "allreduce_grad_dtype", None) is not None:
        import warnings

        warnings.warn(
            "gspmd_lm_train_step ignores the communicator's "
            f"allreduce_grad_dtype={comm.allreduce_grad_dtype!r}: the "
            "partitioner inserts this step's collectives in the tensors' "
            "own dtypes; the compressed-wire knob configures the explicit "
            "shard_map collective only",
            stacklevel=2,
        )
    ax = _resolve_tp_axis(comm, tp_axis)
    n = comm.mesh.shape[ax]
    mesh = comm.mesh
    moe = bool(getattr(model, "moe_experts", 0))
    data_spec = P(dp_axis, None) if dp_axis else P()

    def constrain(tree, specs):
        return jax.tree_util.tree_map(
            lambda l, s: jax.lax.with_sharding_constraint(
                l, NamedSharding(mesh, s)),
            tree, specs, is_leaf=lambda x: isinstance(x, P),
        )

    def step(params, opt_state, tokens, targets):
        specs = megatron_param_specs(params, ax, n)
        params = constrain(params, specs)
        tokens = jax.lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, data_spec))
        targets = jax.lax.with_sharding_constraint(
            targets, NamedSharding(mesh, data_spec))

        def loss_fn(p):
            if moe:
                (logits, aux), sown = model.apply(
                    p, tokens, 0, return_aux=True, mutable=["moe_stats"])
            else:
                logits, aux, sown = model.apply(p, tokens, 0), 0.0, {}
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()
            return ce + moe_aux_weight * aux, sown

        (loss, sown), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = constrain(grads, specs)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = constrain(optax.apply_updates(params, updates), specs)
        opt_state = constrain(opt_state,
                              _opt_specs(optimizer, opt_state, specs))
        if moe:
            from chainermn_tpu.parallel.moe import drop_frac_from_sown

            return params, opt_state, loss, {
                "moe_drop_frac": drop_frac_from_sown(sown)}
        return params, opt_state, loss, {}

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


__all__ = [
    "megatron_param_specs",
    "megatron_shard",
    "megatron_opt_shard",
    "gspmd_lm_train_step",
]
