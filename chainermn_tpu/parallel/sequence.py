"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has **no** long-context machinery (SURVEY.md S2.16/S5: it
predates attention; its closest shape is the alltoall channel-parallel
convolution). These are the TPU-first extensions the rebuild owes
first-class support for long sequences:

- **Ring attention** (:func:`ring_attention`): the sequence axis is sharded
  over a mesh axis; K/V blocks rotate around the ring via ``lax.ppermute``
  while each device's Q stays put, merging partial results with the
  flash-attention online-softmax recurrence. Comm volume per step is one
  K/V block over ICI neighbor links — the collective pattern overlaps with
  the blockwise matmuls (XLA pipelines the ppermute with the einsums).
- **Ulysses attention** (:func:`ulysses_attention`): ``lax.all_to_all``
  re-shards from sequence-sharded to head-sharded, runs exact local
  attention per head group, and all-to-alls back — the same collective
  shape as the reference's channel-parallel conv example, applied to heads.

Both are *traced* functions: call them inside ``shard_map``/``pjit`` over
the communicator's mesh (e.g. via ``comm.shard_map``). Both are exact —
they compute the same result as full attention on the gathered sequence
(tested against the single-device reference), and both differentiate
(``ppermute``/``all_to_all`` have transposed-communication VJPs, the same
property the reference's differentiable collectives hand-implement).

Layouts follow the TPU-friendly convention ``[batch, seq, heads, head_dim]``
with contractions in f32 (``preferred_element_type``) so bf16 inputs hit the
MXU without accumulating in bf16.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.utils import axis_size as _axis_size

_NEG_BIG = -1e30  # finite "minus infinity": avoids inf-inf NaNs in masked rows


def chunk_spans(start: int, total: int, chunk_len: int
                ) -> list[tuple[int, int]]:
    """Partition token range ``[start, total)`` into consecutive
    ``(offset, length)`` spans of at most ``chunk_len`` tokens.

    The one sequence-partitioning arithmetic shared by both consumers of
    "process a long sequence in bounded pieces": sequence-parallel
    sharding plans (where each span is a shard's local window) and the
    serving engine's chunked prefill (where each span is one scheduler
    step's device call). Pure host math — every span is non-empty, spans
    tile the range exactly, and only the last may be short."""
    start, total, chunk_len = int(start), int(total), int(chunk_len)
    if chunk_len < 1:
        raise ValueError(f"chunk_len must be >= 1, got {chunk_len}")
    spans = []
    frontier = start
    while frontier < total:
        clen = min(chunk_len, total - frontier)
        spans.append((frontier, clen))
        frontier += clen
    return spans


def _typeof_vma(x):
    """Varying-manner set of a traced value; empty on legacy JAX (no
    ``jax.typeof``/vma — replication tracking is off there, see
    ``_vary_to``)."""
    return jax.typeof(x).vma if hasattr(jax, "typeof") else frozenset()


def _vary_to(x, vma):
    """pcast ``x`` to varying over exactly the axes in ``vma`` it does not
    already vary on. A plain ``pcast(..., to='varying')`` on a value that
    already carries some of the axes raises ("Unsupported pcast
    from=varying, to='varying'") — hit once the flash kernels started
    propagating input vma to their outputs (round 5). Legacy JAX (no
    ``jax.typeof``/vma) runs shard_map with replication tracking off
    (``mesh_communicator._shard_map``), where everything is already
    varying — identity."""
    if not hasattr(jax, "typeof"):
        return x
    need = tuple(a for a in vma if a not in _typeof_vma(x))
    return lax.pcast(x, need, to="varying") if need else x



def _block_attend(q, k, v, *, scale, mask, m, l, o):
    """One flash-style block update.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    (m, l, o): running max [B, H, Tq], denominator [B, H, Tq], unnormalized
    accumulator [B, Tq, H, D]. Returns updated (m, l, o).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, _NEG_BIG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])  # [B, H, Tq, Tk]
    l = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l, o


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    skip_masked_blocks: bool = True,
):
    """Exact attention over a sequence sharded along ``axis_name``.

    Args (all per-device shards, inside ``shard_map``):
      q, k, v: ``[B, T_local, H, D]`` — the local sequence block.
      causal: apply a causal mask over *global* positions (block offsets are
        derived from ``lax.axis_index``; shard i holds positions
        ``[i*T_local, (i+1)*T_local)``).

    Returns ``[B, T_local, H, D]`` in ``q.dtype``.
    """
    if not isinstance(axis_name, str):
        raise ValueError(
            f"ring_attention needs a single named mesh axis, got {axis_name!r} "
            "— use a flat communicator (e.g. 'tpu') for sequence parallelism"
        )
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    q32 = q.astype(jnp.float32)
    # mark the accumulators as per-device state; without it the fori_loop
    # carry's replicated-ness changes across steps. Vary over the RING axis
    # plus every axis the inputs already vary on (under TP composition the
    # q/k/v carry the tensor axis's vma too; a ring-axis-only pcast would
    # make the carry types diverge after one iteration). With check_vma off
    # the vma sets are empty and this degenerates to the ring axis alone.
    vma = (frozenset({axis_name}) | _typeof_vma(q)
           | _typeof_vma(k) | _typeof_vma(v))
    _vary = lambda x: _vary_to(x, vma)
    m0 = _vary(jnp.full((b, h, t), _NEG_BIG, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, t), jnp.float32))
    o0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = my * t + jnp.arange(t)

    def body(step, carry):
        m, l, o, kb, vb = carry
        src = (my - step) % n  # origin rank of the block we currently hold
        if causal:
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]
            # Blocks from the future (src > my) are fully masked — skip the
            # einsums entirely instead of computing and discarding them.
            # NOTE this halves per-rank FLOPs but NOT wall-clock: the ring
            # barriers every step, so lockstep time is set by the busiest
            # rank (rank n-1 computes every step). zigzag_ring_attention
            # fixes the imbalance itself; this cond still saves energy and
            # helps when ranks aren't lockstep (e.g. CPU testing).
            # skip_masked_blocks=False keeps the round-3 compute-everything
            # behavior (benchmark baseline).
            if skip_masked_blocks:
                m, l, o = lax.cond(
                    src <= my,
                    lambda mlo: _block_attend(
                        q32, kb, vb, scale=scale, mask=mask,
                        m=mlo[0], l=mlo[1], o=mlo[2]
                    ),
                    lambda mlo: mlo,
                    (m, l, o),
                )
            else:
                m, l, o = _block_attend(
                    q32, kb, vb, scale=scale, mask=mask, m=m, l=l, o=o
                )
        else:
            m, l, o = _block_attend(
                q32, kb, vb, scale=scale, mask=None, m=m, l=l, o=o
            )
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, o, kb, vb

    # k/v stay in their input dtype through the ring: the ppermute per step
    # ships half the bytes for bf16 inputs, and _block_attend accumulates in
    # f32 regardless (preferred_element_type + local cast)
    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    # rows with no visible keys (never happens for causal with aligned
    # blocks, but keep the division safe)
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# --------------------------------------------------------------------------- #
# Ring attention with Pallas flash blocks                                     #
# --------------------------------------------------------------------------- #
# The XLA ring above materializes each [B, H, Tq, Tk] score tile via jnp
# einsums; on TPU the per-block computation should be the flash kernel
# (ops/flash_attention.py) so the two O(T)-memory paths compose: ring
# memory ACROSS devices, flash tiling WITHIN each block. AD cannot trace
# through pallas_call, so the ring owns a custom VJP:
#
# - forward: one primal flash call per incoming block (the kernel's causal
#   trip-count clamp skips fully-masked blocks for free); partials merge by
#   the lse-weighted rule o <- o*exp(lse-lse') + o_b*exp(lse_b-lse').
# - backward: the flash backward decomposes over K/V blocks once the FINAL
#   lse and delta = rowsum(do*out) are fixed, so a second rotation pass
#   computes per-block (dq, dk_b, dv_b) with the block kernels; dk/dv
#   accumulators ride the ring WITH their k/v block and arrive back at the
#   owner after n steps holding every rank's contribution.

def _zz_merge(o, lse, ob, lse_b):
    """lse-weighted merge of a partial block result into the running
    (o [B,T,H,D] f32, lse [B,H,T] f32) — the single home of the merge
    recurrence shared by the ring-flash and zigzag-flash forwards."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w1 = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(lse_b - lse_new).transpose(0, 2, 1)[..., None]
    return o * w1 + ob * w2, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, scale):
    out, _ = _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale)
    return out


def _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale):
    from chainermn_tpu.ops.flash_attention import flash_fwd_with_lse

    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    vma = (frozenset({axis_name}) | _typeof_vma(q)
           | _typeof_vma(k) | _typeof_vma(v))
    _vary = lambda x: _vary_to(x, vma)
    o0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    lse0 = _vary(jnp.full((b, h, t), _NEG_BIG, jnp.float32))

    def body(step, carry):
        o, lse, kb, vb = carry
        src = (my - step) % n
        ob, lse_b = flash_fwd_with_lse(
            q, kb, vb, causal=causal, scale=scale,
            q_offset=my * t, k_offset=src * t, out_dtype=jnp.float32,
        )
        o, lse = _zz_merge(o, lse, ob, lse_b)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, lse, kb, vb

    o, lse, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, scale):
    out, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis_name, causal, scale, res, do):
    from chainermn_tpu.ops.flash_attention import flash_block_grads

    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    # delta rows must pair with lse rows: [B, T, H] -> [B, H, T]
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)
    vma = (_typeof_vma(q) | _typeof_vma(do)
           | frozenset({axis_name}))
    _vary = lambda x: _vary_to(x, vma)
    dq0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    dk0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    dv0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))

    def body(step, carry):
        dq, dka, dva, kb, vb = carry
        src = (my - step) % n
        dqb, dkb, dvb = flash_block_grads(
            q, kb, vb, do, lse, delta, causal=causal, scale=scale,
            q_offset=my * t, k_offset=src * t,
        )
        dq = dq + dqb
        dka = dka + dkb
        dva = dva + dvb
        # accumulators travel WITH their block; after n rotations both are
        # back at the block's owner carrying all ranks' contributions
        kb, vb, dka, dva = (lax.ppermute(x, axis_name, perm)
                            for x in (kb, vb, dka, dva))
        return dq, dka, dva, kb, vb

    dq, dka, dva, _, _ = lax.fori_loop(0, n, body, (dq0, dk0, dv0, k, v))
    return dq.astype(q.dtype), dka.astype(k.dtype), dva.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_flash_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """:func:`ring_attention` with Pallas flash kernels as the per-block
    computation — same semantics and layout, O(T) memory at BOTH levels
    (ring across devices, flash tiles within a block), fully-masked blocks
    skipped inside the kernel. Differentiable via a ring-level custom VJP
    (flash backward kernels in a second rotation pass). Off TPU the kernels
    run interpreted — use ``check_vma=False`` on the enclosing shard_map
    there, like plain ``'flash'``."""
    if not isinstance(axis_name, str):
        raise ValueError(
            f"ring_flash_attention needs a single named mesh axis, got "
            f"{axis_name!r}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_flash(q, k, v, axis_name, bool(causal), float(scale))


# --------------------------------------------------------------------------- #
# Zigzag ring with Pallas flash blocks                                        #
# --------------------------------------------------------------------------- #
# The balanced layout AND the kernel blocks — the long-context flagship
# composition. Every zigzag interaction decomposes into offset-causal or
# fully-visible chunk pairs, which is exactly what the flash kernel
# supports: the diagonal step is (qe vs ke causal) + (ql vs kl causal) +
# (ql vs ke full), and each off-diagonal step is one unmasked [t, c] or
# [c, t] call — equal FLOPs in both cond branches, so the balance property
# is preserved. Ring-level custom VJP like _ring_flash, with the same
# rotating dk/dv accumulators.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _zigzag_flash(q, k, v, axis_name, scale):
    out, _ = _zigzag_flash_fwd_pass(q, k, v, axis_name, scale)
    return out


def _zigzag_flash_fwd_pass(q, k, v, axis_name, scale):
    from chainermn_tpu.ops.flash_attention import flash_fwd_with_lse

    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    if t % 2:
        raise ValueError(f"local sequence length {t} must be even")
    c = t // 2
    perm = [(i, (i + 1) % n) for i in range(n)]
    off_e = my * c                 # global offset of the early chunk
    off_l = (2 * n - 1 - my) * c   # ... and the late chunk

    def block(qc, kc, vc, *, causal, q_off=0, k_off=0):
        return flash_fwd_with_lse(
            qc, kc, vc, causal=causal, scale=scale, q_offset=q_off,
            k_offset=k_off, out_dtype=jnp.float32,
        )

    # diagonal: qe/ke causal + ql/kl causal + ql/ke full
    oe, lse_e = block(q[:, :c], k[:, :c], v[:, :c], causal=True,
                      q_off=off_e, k_off=off_e)
    ol1, lse_l1 = block(q[:, c:], k[:, c:], v[:, c:], causal=True,
                        q_off=off_l, k_off=off_l)
    ol2, lse_l2 = block(q[:, c:], k[:, :c], v[:, :c], causal=False)
    ol, lse_l = _zz_merge(ol1, lse_l1, ol2, lse_l2)
    o = jnp.concatenate([oe, ol], axis=1)
    lse = jnp.concatenate([lse_e, lse_l], axis=2)

    kb = lax.ppermute(k, axis_name, perm)
    vb = lax.ppermute(v, axis_name, perm)

    # BRANCH-FREE ring steps (round 5): the round-5 AOT schedule analysis
    # showed XLA will not hoist collective starts across a lax.cond, so a
    # cond-shaped body serializes the ring's permutes against the kernels
    # (PERF.md "Ring overlap"). Both former branches decompose into the
    # SAME two fully-visible (c x c) kernel calls with selected operands —
    # earlier-rank block: (q_e x k_e) + (q_l x k_e); later-rank block:
    # (q_l x k_e) + (q_l x k_l) — equal FLOPs (the balance property), no
    # control flow, so the scheduler overlaps the permutes like the plain
    # ring's. Only the cheap elementwise merges are select-routed.
    def body(step, carry):
        o, lse, kb, vb = carry
        earlier = my >= step  # the held block came from an earlier rank
        ke, ve, kl, vl = kb[:, :c], vb[:, :c], kb[:, c:], vb[:, c:]
        q_e, q_l = q[:, :c], q[:, c:]

        ob1, lse_b1 = block(jnp.where(earlier, q_e, q_l), ke, ve,
                            causal=False)
        ob2, lse_b2 = block(q_l, jnp.where(earlier, ke, kl),
                            jnp.where(earlier, ve, vl), causal=False)

        o_e, lse_e = o[:, :c], lse[:, :, :c]
        o_l, lse_l = o[:, c:], lse[:, :, c:]
        # call 1 merges into the half its q rows came from
        oe_m, lsee_m = _zz_merge(o_e, lse_e, ob1, lse_b1)
        ol_m, lsel_m = _zz_merge(o_l, lse_l, ob1, lse_b1)
        o_e = jnp.where(earlier, oe_m, o_e)
        lse_e = jnp.where(earlier, lsee_m, lse_e)
        o_l = jnp.where(earlier, o_l, ol_m)
        lse_l = jnp.where(earlier, lse_l, lsel_m)
        # call 2's q rows are always the late half
        o_l, lse_l = _zz_merge(o_l, lse_l, ob2, lse_b2)

        o = jnp.concatenate([o_e, o_l], axis=1)
        lse = jnp.concatenate([lse_e, lse_l], axis=2)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, lse, kb, vb

    o, lse, _, _ = lax.fori_loop(1, n, body, (o, lse, kb, vb))
    return o.astype(q.dtype), lse


def _zigzag_flash_fwd_rule(q, k, v, axis_name, scale):
    out, lse = _zigzag_flash_fwd_pass(q, k, v, axis_name, scale)
    return out, (q, k, v, out, lse)


def _zigzag_flash_bwd_rule(axis_name, scale, res, do):
    from chainermn_tpu.ops.flash_attention import flash_block_grads

    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    c = t // 2
    perm = [(i, (i + 1) % n) for i in range(n)]
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)
    vma = _typeof_vma(q) | _typeof_vma(do) | frozenset({axis_name})
    _vary = lambda x: _vary_to(x, vma)
    off_e, off_l = my * c, (2 * n - 1 - my) * c

    def grads(qs, ks, vs, dos, lses, deltas, *, causal, q_off=0, k_off=0):
        return flash_block_grads(
            qs, ks, vs, dos, lses, deltas, causal=causal, scale=scale,
            q_offset=q_off, k_offset=k_off,
        )

    # diagonal contributions (same three pairs as forward)
    dqe, dke, dve = grads(q[:, :c], k[:, :c], v[:, :c], do[:, :c],
                          lse[:, :, :c], delta[:, :, :c], causal=True,
                          q_off=off_e, k_off=off_e)
    dql1, dkl, dvl = grads(q[:, c:], k[:, c:], v[:, c:], do[:, c:],
                           lse[:, :, c:], delta[:, :, c:], causal=True,
                           q_off=off_l, k_off=off_l)
    dql2, dke2, dve2 = grads(q[:, c:], k[:, :c], v[:, :c], do[:, c:],
                             lse[:, :, c:], delta[:, :, c:], causal=False)
    dq = _vary(jnp.concatenate([dqe, dql1 + dql2], axis=1))
    dka = _vary(jnp.concatenate([dke + dke2, dkl], axis=1))
    dva = _vary(jnp.concatenate([dve + dve2, dvl], axis=1))

    kb = lax.ppermute(k, axis_name, perm)
    vb = lax.ppermute(v, axis_name, perm)
    dka = lax.ppermute(dka, axis_name, perm)
    dva = lax.ppermute(dva, axis_name, perm)

    # Branch-free like the forward (see _zigzag_flash_fwd_pass): a lax.cond
    # body would serialize all four permutes against the kernels (XLA will
    # not hoist collective starts across control flow — round-5 AOT
    # schedule analysis, PERF.md "Ring overlap"). The two former branches
    # are the same two (c x c) kernel calls with selected operands:
    #   earlier: (q_e x k_e) + (q_l x k_e)   later: (q_l x k_e) + (q_l x k_l)
    # Only the cheap gradient scatter-adds are select-routed.
    def body(step, carry):
        dq, dka, dva, kb, vb = carry
        earlier = my >= step
        ke, ve, kl, vl = kb[:, :c], vb[:, :c], kb[:, c:], vb[:, c:]
        q_e, q_l = q[:, :c], q[:, c:]
        do_e, do_l = do[:, :c], do[:, c:]
        lse_e, lse_l = lse[:, :, :c], lse[:, :, c:]
        de, dl = delta[:, :, :c], delta[:, :, c:]

        dq1, dk1, dv1 = grads(jnp.where(earlier, q_e, q_l), ke, ve,
                              jnp.where(earlier, do_e, do_l),
                              jnp.where(earlier, lse_e, lse_l),
                              jnp.where(earlier, de, dl), causal=False)
        dq2, dk2, dv2 = grads(q_l, jnp.where(earlier, ke, kl),
                              jnp.where(earlier, ve, vl),
                              do_l, lse_l, dl, causal=False)

        zc = jnp.zeros((b, c, h, d), jnp.float32)
        # dq: call 1's rows are q_e (earlier) or q_l (later); call 2's
        # rows are always q_l
        dq = dq + jnp.concatenate(
            [jnp.where(earlier, dq1, zc),
             jnp.where(earlier, dq2, dq1 + dq2)], axis=1)
        # dk/dv: call 1 always hits the early K half; call 2 hits the
        # early half (earlier) or the late half (later)
        dka = dka + jnp.concatenate(
            [dk1 + jnp.where(earlier, dk2, zc),
             jnp.where(earlier, zc, dk2)], axis=1)
        dva = dva + jnp.concatenate(
            [dv1 + jnp.where(earlier, dv2, zc),
             jnp.where(earlier, zc, dv2)], axis=1)
        kb, vb, dka, dva = (lax.ppermute(x, axis_name, perm)
                            for x in (kb, vb, dka, dva))
        return dq, dka, dva, kb, vb

    dq, dka, dva, _, _ = lax.fori_loop(1, n, body, (dq, dka, dva, kb, vb))
    return dq.astype(q.dtype), dka.astype(k.dtype), dva.astype(v.dtype)


_zigzag_flash.defvjp(_zigzag_flash_fwd_rule, _zigzag_flash_bwd_rule)


def zigzag_flash_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """:func:`zigzag_ring_attention` with Pallas flash kernels as the block
    computation — balanced causal work AND O(T)-memory MXU tiles. Data must
    be zigzag-permuted (:func:`zigzag_permutation`). Off TPU the kernels
    run interpreted; use ``check_vma=False`` on the enclosing shard_map."""
    if not causal:
        return ring_flash_attention(q, k, v, axis_name, causal=False,
                                    scale=scale)
    if not isinstance(axis_name, str):
        raise ValueError(
            f"zigzag_flash_attention needs a single named mesh axis, got "
            f"{axis_name!r}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _zigzag_flash(q, k, v, axis_name, float(scale))


def zigzag_permutation(t_global: int, n_shards: int):
    """Sequence permutation for the zigzag (striped-block) layout.

    The global sequence is split into ``2n`` chunks; shard ``i`` holds chunks
    ``(i, 2n-1-i)`` — one early and one late chunk — so each rank's causal
    workload is equal (the contiguous layout gives rank 0 one visible block
    and rank n-1 all n: the classic ring-attention imbalance).

    Returns an index array ``perm`` of length ``t_global`` such that
    ``x[:, perm]`` laid out contiguously over ``n_shards`` gives every shard
    its zigzag chunk pair. Apply the SAME permutation to tokens and targets
    (next-token pairing is preserved; a mean loss over tokens is
    permutation-invariant, so training needs no unpermute). Invert for
    outputs with ``jnp.argsort(perm)``.
    """
    if t_global % (2 * n_shards):
        raise ValueError(
            f"sequence length {t_global} must divide into 2*{n_shards} chunks"
        )
    c = t_global // (2 * n_shards)
    idx = []
    for i in range(n_shards):
        idx.append(jnp.arange(i * c, (i + 1) * c))
        j = 2 * n_shards - 1 - i
        idx.append(jnp.arange(j * c, (j + 1) * c))
    return jnp.concatenate(idx)


def zigzag_positions(rank, n_shards: int, t_local: int):
    """Global positions of shard ``rank``'s tokens under the zigzag layout
    (``rank`` may be traced, e.g. ``lax.axis_index``). Shape ``[t_local]`` —
    feed to position embeddings in place of the contiguous
    ``offset + arange`` base."""
    c = t_local // 2
    early = rank * c + jnp.arange(c)
    late = (2 * n_shards - 1 - rank) * c + jnp.arange(c)
    return jnp.concatenate([early, late])


def zigzag_ring_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Causal ring attention over a **zigzag-sharded** sequence — the
    load-balanced form of :func:`ring_attention`.

    Each shard holds the chunk pair ``(i, 2n-1-i)`` of a ``2n``-chunk global
    sequence (lay data out with :func:`zigzag_permutation`). Per ring step
    every rank then does the SAME useful work — exactly half the chunk-pair
    interactions are visible, and they are computed without masks:

    - block from an earlier rank (``src < my``): all local queries attend the
      block's early chunk only (its late chunk is entirely in the future);
    - block from a later rank (``src > my``): only the local late chunk
      attends, but it sees the whole block;
    - the local (diagonal) block needs the one genuinely masked update.

    Total FLOPs are ~half of contiguous causal ring (which computes every
    masked block) and per-rank work is equal, so the per-step ppermute
    barrier no longer waits on a straggler. Exact: matches full attention on
    the unpermuted sequence (tested). Differentiable.
    """
    if not causal:
        # zigzag exists solely to balance the causal mask; unmasked ring
        # attention is layout-independent
        return ring_attention(q, k, v, axis_name, causal=False, scale=scale)
    if not isinstance(axis_name, str):
        raise ValueError(
            f"zigzag_ring_attention needs a single named mesh axis, got "
            f"{axis_name!r}"
        )
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    if t % 2:
        raise ValueError(f"local sequence length {t} must be even (chunk pair)")
    c = t // 2
    if scale is None:
        scale = d ** -0.5

    q32 = q.astype(jnp.float32)
    vma = (frozenset({axis_name}) | _typeof_vma(q)
           | _typeof_vma(k) | _typeof_vma(v))
    _vary = lambda x: _vary_to(x, vma)
    m = _vary(jnp.full((b, h, t), _NEG_BIG, jnp.float32))
    l = _vary(jnp.zeros((b, h, t), jnp.float32))
    o = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Step 0 — the diagonal block: the one masked update (positions are the
    # zigzag pair's, not contiguous).
    pos = zigzag_positions(my, n, t)
    mask0 = pos[:, None] >= pos[None, :]
    m, l, o = _block_attend(q32, k, v, scale=scale, mask=mask0, m=m, l=l, o=o)
    kb = lax.ppermute(k, axis_name, perm)
    vb = lax.ppermute(v, axis_name, perm)

    # Branch-free ring steps, like _zigzag_flash_fwd_pass: a lax.cond body
    # serializes the permutes against the block compute on TPU schedules
    # (XLA will not hoist collective starts across control flow — PERF.md
    # "Ring overlap"). Both former branches are the SAME two unmasked
    # (c x c) block updates with selected operands; the online-softmax
    # update is exact in any order, so chaining two updates equals the old
    # single wider update.
    def body(step, carry):
        m, l, o, kb, vb = carry
        # src = (my - step) % n; for step in [1, n) src < my <=> my >= step
        earlier = my >= step
        ke, ve, kl, vl = kb[:, :c], vb[:, :c], kb[:, c:], vb[:, c:]
        m_e, m_l = m[:, :, :c], m[:, :, c:]
        l_e, l_l = l[:, :, :c], l[:, :, c:]
        o_e, o_l = o[:, :c], o[:, c:]
        q_e, q_l = q32[:, :c], q32[:, c:]

        # call 1: (q_e x k_e) on the early state (earlier-rank block) or
        # (q_l x k_e) on the late state (later-rank block)
        m1, l1, o1 = _block_attend(
            jnp.where(earlier, q_e, q_l), ke, ve, scale=scale, mask=None,
            m=jnp.where(earlier, m_e, m_l),
            l=jnp.where(earlier, l_e, l_l),
            o=jnp.where(earlier, o_e, o_l),
        )
        # call 2 always updates the late state: from the ORIGINAL late
        # state when call 1 touched the early half, or chained on call 1's
        # output when both calls are late-row updates
        m2, l2, o2 = _block_attend(
            q_l, jnp.where(earlier, ke, kl), jnp.where(earlier, ve, vl),
            scale=scale, mask=None,
            m=jnp.where(earlier, m_l, m1),
            l=jnp.where(earlier, l_l, l1),
            o=jnp.where(earlier, o_l, o1),
        )
        m = jnp.concatenate([jnp.where(earlier, m1, m_e), m2], axis=2)
        l = jnp.concatenate([jnp.where(earlier, l1, l_e), l2], axis=2)
        o = jnp.concatenate([jnp.where(earlier, o1, o_e), o2], axis=1)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, o, kb, vb

    m, l, o, _, _ = lax.fori_loop(1, n, body, (m, l, o, kb, vb))
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_impl: str = "xla",
    head_chunks: int = 1,
):
    """Exact attention via all-to-all head re-sharding (DeepSpeed-Ulysses
    collective shape, done with one XLA ``all_to_all`` each way).

    Per-device shards ``[B, T_local, H, D]`` with ``H`` divisible by the
    axis size; internally each device holds the FULL sequence for ``H/n``
    heads, so memory per device is ``T_global * H/n`` — choose ring
    attention instead when the full sequence per device is too large.

    ``block_impl='flash'`` runs the local per-head attention through the
    Pallas kernel (O(T) memory for the scores instead of the XLA path's
    materialized ``[B, H/n, T, T]`` tile — at long T that tile, not the
    K/V, is what OOMs first); the collectives are unchanged and
    differentiation works through the kernel's custom VJP + the
    ``all_to_all`` transpose. Off TPU the kernel runs interpreted (use
    ``check_vma=False`` on the enclosing shard_map, like 'flash').

    ``head_chunks > 1`` splits the local heads into that many groups and
    runs the exchange+attend+exchange pipeline per group, UNROLLED: group
    g+1's all_to_alls have no data dependency on group g's attention —
    the plain form's all_to_alls are provably un-hideable (exchange ->
    attend -> exchange are sequentially dependent). Exact for any
    chunking (heads are independent); per-group working memory drops by
    the same factor. NOTE the overlap is structural readiness, not a
    measured win on this toolchain: the current XLA TPU build lowers
    all_to_all synchronously (no -start/-done pair to schedule around;
    AOT-verified, PERF.md "Ring overlap"), so today the chunking buys
    memory granularity and future async toolchains the opportunity.
    """
    if not isinstance(axis_name, str):
        raise ValueError(
            f"ulysses_attention needs a single named mesh axis, got {axis_name!r} "
            "— use a flat communicator (e.g. 'tpu') for sequence parallelism"
        )
    if block_impl not in ("xla", "flash"):
        # a silent fallback to the XLA path would materialize the exact
        # O(T^2) score tile the flag exists to avoid
        raise ValueError(
            f"block_impl must be 'xla' or 'flash', got {block_impl!r}")
    n = _axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"heads ({h}) must be divisible by axis size ({n})")
    if head_chunks < 1 or h % head_chunks or (h // head_chunks) % n:
        raise ValueError(
            f"head_chunks={head_chunks} must partition the {h} heads into "
            f"groups divisible by the axis size ({n})"
        )

    def to_heads(x):  # [B, T, Hg, D] -> [B, n*T, Hg/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):  # [B, n*T, Hg/n, D] -> [B, T, Hg, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def attend(qg, kg, vg):
        if block_impl == "flash":
            from chainermn_tpu.ops import flash_attention

            return flash_attention(qg, kg, vg, causal=causal, scale=scale)
        return full_attention(qg, kg, vg, causal=causal, scale=scale)

    hg = h // head_chunks
    outs = []
    for g in range(head_chunks):  # unrolled: groups are independent
        sl = slice(g * hg, (g + 1) * hg)
        outs.append(to_seq(attend(
            to_heads(q[:, :, sl]), to_heads(k[:, :, sl]),
            to_heads(v[:, :, sl]))))
    return outs[0] if head_chunks == 1 else jnp.concatenate(outs, axis=2)


def ulysses_flash_attention(q, k, v, axis_name: str, *, causal: bool = False,
                            scale: Optional[float] = None):
    """:func:`ulysses_attention` with the Pallas flash kernel as the local
    attention (``block_impl='flash'``)."""
    return ulysses_attention(q, k, v, axis_name, causal=causal, scale=scale,
                             block_impl="flash")


def cached_attention(q, kbuf, vbuf, pos_offset, *, scale: Optional[float] = None):
    """Decode-time attention: ``S`` new queries against a static KV buffer.

    ``q [B, S, H, D]`` holds queries for global positions ``pos_offset ..
    pos_offset+S-1``; ``kbuf/vbuf [B, Tc, H, D]`` are the cache buffers
    whose first ``pos_offset+S`` rows are valid (later rows are masked by
    position, so their contents — typically zeros — never contribute).
    Static shapes throughout: the compiled program is one [S, Tc] score
    tile per head, O(Tc*D) per decoded token instead of the O(Tc^2)
    re-forward of cacheless decoding. Shared by the dense and
    tensor-parallel decode paths (``pos_offset`` may be traced).

    ``pos_offset`` may also be a ``[B]`` vector of per-sequence bases: each
    batch row then decodes at its OWN position — the continuous-batching
    shape, where one call advances every cache slot one token regardless of
    how far along each slot's sequence is."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kbuf,
                   preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(kbuf.shape[1])
    if jnp.ndim(pos_offset) == 0:
        q_pos = pos_offset + jnp.arange(q.shape[1])          # [S]
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]  # [1,1,S,Tc]
    else:
        q_pos = pos_offset[:, None] + jnp.arange(q.shape[1])[None]  # [B, S]
        mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]  # [B,1,S,Tc]
    s = jnp.where(mask, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vbuf.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _dequant_cached_attention(q, k8, k_sc, v8, v_sc, pos_offset, *,
                              scale: Optional[float] = None):
    """:func:`cached_attention` over an int8 K/V view with the dequant
    scales FOLDED into the contractions instead of materialized: the QK
    product runs on the raw int8 rows and its f32 scores are multiplied
    by ``k_sc`` per key column; the probabilities are multiplied by
    ``v_sc`` per key row before the PV product. Same math by linearity
    (the scales are per-row constants along the contracted dims), but
    the ``[B, T, H, D]`` dequantized f32 view never exists — the read
    path moves int8 rows plus the f32 scale vectors, preserving the
    ``kv_quant='int8'`` bandwidth win at read time (PERF.md "Paged-decode
    kernel"). ``k8``/``v8`` are ``[B, T, H, D]`` int8, ``k_sc``/``v_sc``
    their ``[B, T, H]`` f32 scales; masking is identical to
    :func:`cached_attention`."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k8.astype(q.dtype),
                   preferred_element_type=jnp.float32) * scale
    s = s * jnp.moveaxis(k_sc, 2, 1)[:, :, None, :]           # [B,H,1,T]
    k_pos = jnp.arange(k8.shape[1])
    if jnp.ndim(pos_offset) == 0:
        q_pos = pos_offset + jnp.arange(q.shape[1])
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
    else:
        q_pos = pos_offset[:, None] + jnp.arange(q.shape[1])[None]
        mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]
    s = jnp.where(mask, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    p = p * jnp.moveaxis(v_sc, 2, 1)[:, :, None, :]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v8.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def paged_update_cache_and_attend(kv_cache, q, k, v, pos_offset, *,
                                  scale: Optional[float] = None):
    """The paged twin of :func:`update_cache_and_attend`: K/V live in a
    shared **block store** instead of dense per-sequence regions, and each
    batch row reaches its own sequence through a **block table**.

    ``kv_cache`` is a dict with:

    - ``'k'``/``'v'``: the store, ``[n_blocks, block_size, H, D]`` — one
      pool of fixed-size token blocks shared by every sequence (and, in
      the serving engine, by the prefix cache: a cached prefix is just a
      table entry, not a copy);
    - ``'table'``: ``[B, max_blocks]`` int32 — row ``b``'s ``j``-th entry
      is the store block holding positions ``[j*bs, (j+1)*bs)`` of
      sequence ``b``. Entries for not-yet-written spans may be junk (by
      convention a reserved scratch block): the position mask hides every
      row at positions beyond the query, exactly like the dense path's
      stale-rows argument;
    - optional ``'k_scale'``/``'v_scale'``: ``[n_blocks, block_size, H]``
      f32 — present iff the store is int8-quantized. Each resident row
      carries one symmetric scale per head (``x ≈ x_q * scale``); writes
      quantize, the attention gather dequantizes in-program.
    - optional ``'valid'``: ``[B]`` int32 — per-row count of *leading*
      query positions whose K/V rows should actually land in the store.
      Rows ``j >= valid[b]`` are redirected into the scratch block
      (block 0) instead: the speculative verify window feeds ``k+1``
      rows per slot but slots near their cache limit may only have
      headroom for fewer, and without the redirect the clamped
      ``pos // bs`` table lookup would silently overwrite a *live* row.
      The attention itself is unaffected (the position mask already
      hides rows beyond each query).

    Writes scatter the ``S`` new rows through the table
    (``store[table[b, p//bs], p%bs] = kv[b, p]``); the attention gathers
    each row's table span back into a per-sequence view and runs the same
    position-masked :func:`cached_attention`. The gathered span is the
    full ``max_blocks`` when positions are traced (the serving engine's
    compiled bodies — shapes must not depend on values), but callers with
    CONCRETE positions get the span tightened to the batch-max active
    block count ``ceil(max(lengths)/bs)``: fully-masked table tail
    entries are provably never read, so they are not gathered either.
    Per-row valid lengths are ``pos_offset + S`` (post-write); a
    ``'lengths'`` entry in ``kv_cache`` overrides them.

    An int8 store's dequant scales fold into the attention contractions
    per-block (scores scaled after the QK product, probabilities before
    the PV product) — the dequantized f32 dense view is never
    materialized, read bytes stay int8-sized.

    A truthy ``'use_kernel'`` entry routes the read side through the
    fused Pallas kernel (:func:`chainermn_tpu.parallel.paged_kernel.
    paged_attend`): table-indexed block gather, in-register dequant and
    online-softmax attention in one pass, streaming only each row's
    ``ceil(len/bs)`` active blocks. The scatter (write side) is XLA on
    every path — it moves ``S`` rows, the kernel owns the O(length)
    read. ``'use_kernel'`` must be a static Python bool (it selects a
    trace, it is not an operand).

    Static shapes throughout — table contents change, programs never
    recompile. Returns ``(out, new_cache)`` where ``new_cache`` carries
    the updated store (and scales) WITHOUT the table: the table is
    host-managed state threaded in per call."""
    store_k, store_v = kv_cache["k"], kv_cache["v"]
    table = kv_cache["table"]
    quant = "k_scale" in kv_cache
    bs = store_k.shape[1]
    b, s = q.shape[0], q.shape[1]
    if jnp.ndim(pos_offset) == 0:
        pos_offset = jnp.full((b,), pos_offset, jnp.int32)
    pos = pos_offset[:, None] + jnp.arange(s)[None, :]        # [B, S]
    blk = jnp.take_along_axis(table, pos // bs, axis=1).reshape(-1)
    off = (pos % bs).reshape(-1)
    valid = kv_cache.get("valid")
    if valid is not None:
        # redirect rows past each sequence's valid count into the scratch
        # block so a clamped table lookup can never clobber a live row
        rv = (jnp.arange(s)[None, :] < valid[:, None]).reshape(-1)
        blk = jnp.where(rv, blk, 0)
        off = jnp.where(rv, off, 0)

    def write(store, scales, rows):
        rows = rows.reshape((b * s,) + rows.shape[2:])        # [B*S, H, D]
        if not quant:
            return store.at[blk, off].set(rows.astype(store.dtype)), None
        r32 = rows.astype(jnp.float32)
        # symmetric per-row-per-head scale; the epsilon keeps all-zero
        # rows (warmup, padding) from dividing by zero
        sc = jnp.maximum(jnp.max(jnp.abs(r32), axis=-1) / 127.0, 1e-8)
        q8 = jnp.clip(jnp.round(r32 / sc[..., None]), -127, 127)
        return (store.at[blk, off].set(q8.astype(jnp.int8)),
                scales.at[blk, off].set(sc))

    new_k, new_ks = write(store_k, kv_cache.get("k_scale"), k)
    new_v, new_vs = write(store_v, kv_cache.get("v_scale"), v)

    lengths = kv_cache.get("lengths")
    if lengths is None:
        lengths = pos_offset + s                              # post-write
    m_used = table.shape[1]
    if not isinstance(lengths, jax.core.Tracer):
        # concrete positions: tighten the span to the batch-max active
        # block count — the masked tail is provably never read
        m_used = max(1, min(m_used, -(-int(jnp.max(lengths)) // bs)))

    if kv_cache.get("use_kernel"):
        from chainermn_tpu.parallel.paged_kernel import paged_attend
        out = paged_attend(q, new_k, new_v, table, lengths,
                           k_scale=new_ks, v_scale=new_vs, scale=scale,
                           max_blocks=m_used)
    else:
        flat = table[:, :m_used].reshape(-1)                  # [B*m]

        def gather(store, scales):
            rows = jnp.take(store, flat, axis=0)   # [B*m, bs, H, D]
            rows = rows.reshape((b, -1) + rows.shape[2:])
            if not quant:
                return rows.astype(q.dtype), None
            sc = jnp.take(scales, flat, axis=0)    # [B*m, bs, H]
            return rows, sc.reshape((b, -1) + sc.shape[2:])

        kbuf, ksc = gather(new_k, new_ks)
        vbuf, vsc = gather(new_v, new_vs)
        if quant:
            out = _dequant_cached_attention(q, kbuf, ksc, vbuf, vsc,
                                            pos_offset, scale=scale)
        else:
            out = cached_attention(q, kbuf, vbuf, pos_offset, scale=scale)
    new_cache = {"k": new_k, "v": new_v}
    if quant:
        new_cache["k_scale"] = new_ks
        new_cache["v_scale"] = new_vs
    return out, new_cache


def update_cache_and_attend(kv_cache, q, k, v, pos_offset, *,
                            scale: Optional[float] = None):
    """Write ``S`` new K/V rows into the cache at ``pos_offset`` and attend
    the matching queries against the updated buffers — the one shared
    decode-step body for the dense and tensor-parallel cached paths.
    Returns ``(out, new_cache)`` with ``new_cache`` the same ``{'k','v'}``
    dict shape. Causal by construction (the position mask).

    A ``[B]`` ``pos_offset`` writes each batch row's K/V at that row's own
    position (vmapped per-row update) — the slot-pool decode step, where
    every slot sits at a different depth in its sequence.

    A ``kv_cache`` carrying a ``'table'`` entry takes the **paged** path
    (:func:`paged_update_cache_and_attend`): the buffers are then a shared
    block store indexed per row through the block table."""
    if "table" in kv_cache:
        return paged_update_cache_and_attend(kv_cache, q, k, v, pos_offset,
                                             scale=scale)
    if jnp.ndim(pos_offset) == 0:
        kbuf = lax.dynamic_update_slice(
            kv_cache["k"], k.astype(kv_cache["k"].dtype),
            (0, pos_offset, 0, 0))
        vbuf = lax.dynamic_update_slice(
            kv_cache["v"], v.astype(kv_cache["v"].dtype),
            (0, pos_offset, 0, 0))
    else:
        row_update = jax.vmap(
            lambda buf, new, p: lax.dynamic_update_slice(buf, new, (p, 0, 0)))
        kbuf = row_update(kv_cache["k"], k.astype(kv_cache["k"].dtype),
                          pos_offset)
        vbuf = row_update(kv_cache["v"], v.astype(kv_cache["v"].dtype),
                          pos_offset)
    out = cached_attention(q, kbuf, vbuf, pos_offset, scale=scale)
    return out, {"k": kbuf, "v": vbuf}


def full_attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
                   precision=None):
    """Single-device exact attention, same layout/semantics — the reference
    implementation the parallel variants are tested against, and the
    fallback when no sequence axis is sharded.

    ``precision``: forwarded to the einsums. TPU matmuls at the default
    precision round f32 operands through bf16 passes (~1e-3 abs error) —
    oracle uses (e.g. the on-chip parity battery) pass ``"highest"`` so the
    reference is actually f32-accurate."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32,
                   precision=precision)
    s = s * scale
    if causal:
        t, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None, :, :], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32, precision=precision)
    return out.astype(q.dtype)


def sequence_parallel_attention(
    kind: str,
    axis_name: Optional[str],
    *,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Pick an attention implementation by name: ``'ring'`` |
    ``'ring_flash'`` (ring with Pallas kernel blocks) | ``'zigzag'``
    (load-balanced causal ring; data must be zigzag-permuted) |
    ``'ulysses'`` | ``'full'`` | ``'flash'``. Returns ``f(q, k, v) -> o``
    for use inside a traced step. ``'flash'`` is the Pallas-kernel local
    attention (:mod:`chainermn_tpu.ops.flash_attention`) — same semantics
    as ``'full'``, O(T) memory; use it when the sequence is NOT sharded."""
    if kind == "flash":
        if axis_name is not None:
            raise ValueError(
                "attention='flash' is local (unsharded-sequence) attention; "
                "it cannot attend across a sharded sequence axis "
                f"({axis_name!r}) — use 'ring' or 'ulysses' there"
            )
        from chainermn_tpu.ops import flash_attention

        return functools.partial(flash_attention, causal=causal, scale=scale)
    if kind == "full" or axis_name is None:
        return functools.partial(full_attention, causal=causal, scale=scale)
    if kind not in ("ring", "ring_flash", "zigzag", "zigzag_flash",
                    "ulysses", "ulysses_flash"):
        raise ValueError(
            f"unknown attention kind {kind!r}; use "
            "ring|ring_flash|zigzag|zigzag_flash|ulysses|ulysses_flash|"
            "full|flash"
        )
    impl = {"ring": ring_attention, "ring_flash": ring_flash_attention,
            "zigzag": zigzag_ring_attention,
            "zigzag_flash": zigzag_flash_attention,
            "ulysses": ulysses_attention,
            "ulysses_flash": ulysses_flash_attention}[kind]

    def f(q, k, v):
        try:
            _axis_size(axis_name)
        except NameError:
            # axis not bound: we're outside shard_map (flax init, eval on a
            # gathered sequence) — the whole sequence is local, so exact
            # full attention IS the correct semantics (params are identical).
            # CAVEAT for 'zigzag': data fed to the sharded model is
            # zigzag-PERMUTED; outside the mesh, un-permute it first
            # (jnp.argsort(zigzag_permutation(...))) or these causal
            # positions are wrong. Init is value-independent, so module
            # construction is unaffected.
            return full_attention(q, k, v, causal=causal, scale=scale)
        return impl(q, k, v, axis_name, causal=causal, scale=scale)

    return f
