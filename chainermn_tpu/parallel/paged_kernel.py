"""Fused Pallas paged-attention decode kernel (ROADMAP item 5).

The XLA paged decode path (:func:`chainermn_tpu.parallel.sequence.
paged_update_cache_and_attend`) reads the shared block store through a
``jnp.take`` gather that materializes each row's FULL table span as a
dense ``[B, max_blocks*bs, H, D]`` view — in f32 when the store is int8,
so the ``kv_quant`` bandwidth win (PERF.md "KV memory model") is thrown
away at read time, and rows past each sequence's length are streamed
just to be masked. This kernel fuses the whole read path per batch row:

- **block-table gather in the index map**: the ``[B, max_blocks]`` table
  and the per-row ``lengths`` ride as scalar-prefetch operands
  (``PrefetchScalarGridSpec``), so the K/V streaming index maps resolve
  ``table[b, j]`` on the fly — blocks are DMA'd straight from the store,
  and the dense per-sequence view never exists;
- **clamp-skip past ``lengths``** (the paged analog of the flash
  kernels' causal DMA clamp, PERF.md "Causal DMA clamp + block-1024
  ceiling"): grid steps past ``ceil(lengths[b]/bs)`` alias the row's
  last active block in the index map — Mosaic's pipeline elides the
  repeat copy — and skip their compute via ``pl.when``, so a row streams
  only the blocks it actually occupies;
- **one DMA per live block, all heads**: heads fold into the row
  dimension (free contiguous reshapes — ``q`` as ``[B, S*H, D]``, store
  blocks as ``[bs*H, D]`` tiles) and each ``(b, j)`` grid cell computes
  one dense all-head-pairs score tile with a head-match mask. Mosaic's
  tiling rules force this shape anyway (single-head ``(..., 1, D)``
  blocks and strided middle-dim slices are both unloadable), and it is
  the right read schedule: a store block's bytes move once per decode
  step, not once per head;
- **in-register int8 dequant**: the per-row-per-head scales
  ``[bs, H]`` tiles fold into the score/output contractions
  (``s *= k_scale[t]`` after the QK dot; ``p *= v_scale[t]`` before the
  PV dot) — bytes moved stay int8 + the tiny f32 scale vectors;
- **position-masked online softmax**: the flash (m, l, acc) recurrence
  in f32 VMEM scratch across the block sweep, flushed once at the last
  grid step — exactly :func:`_fwd_kernel`'s structure with the k-chunk
  axis replaced by table-indexed store blocks.

Shapes are the serving decode family: ``S = 1`` (per-token decode), the
``decode_window`` fori_loop body, and the speculative verify window
(``S = k+1``); ``lengths = pos + S`` per row. The ``valid`` scratch
redirect affects only WRITES (handled XLA-side before the kernel runs);
the attention itself is position-masked identically to
:func:`cached_attention`. Off TPU the kernel runs in Pallas interpret
mode (the same code path CPU tier-1 tests pin); real-hardware evidence
lands per PERF.md's chip-free AOT discipline
(``scripts/aot_paged_kernel.py``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chainermn_tpu.ops.flash_attention import (
    _LANE,
    _NEG_BIG,
    _compiler_params,
    _interpret_default,
    _out_vma,
    _prec,
    _sds,
)


def kernel_supported() -> tuple[bool, str]:
    """Cheap host-side availability probe for the fused kernel path.

    ``(True, "")`` when the Pallas TPU frontend imports and the kernel is
    not explicitly disabled; ``(False, reason)`` otherwise. Engines built
    with ``paged_kernel=True`` call this once at construction and fall
    back to the XLA path (emitting the ``paged_kernel_fallback`` event)
    instead of failing warmup — the kernel is an optimization, never a
    capability."""
    if os.environ.get("CHAINERMN_TPU_NO_PAGED_KERNEL"):
        return False, "disabled by CHAINERMN_TPU_NO_PAGED_KERNEL"
    try:  # pragma: no cover - import failure is environment-specific
        from jax.experimental.pallas import tpu as _  # noqa: F401
    except Exception as exc:  # pragma: no cover
        return False, f"pallas unavailable: {type(exc).__name__}: {exc}"
    return True, ""


def _decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                   scale: float, bs: int, n_j: int, n_heads: int,
                   quant: bool):
    """Grid ``(batch row b, table slot j)``, j INNERMOST: the
    online-softmax state (m, l, acc) lives in f32 VMEM scratch across the
    row's block sweep and the output block flushes once at the last slot.
    ``k_ref``/``v_ref`` blocks arrive via the table-indexed clamped maps
    (:func:`_store_map`), so slot j past the row's active block count
    re-delivers the last active block — its compute is skipped below, so
    values are unchanged and Mosaic elides the repeat DMA.

    Heads are NOT a grid axis, and they are not sliced in-kernel either:
    the caller flattens them into the row dimension (``q`` arrives as
    ``[1, S*H, D]`` blocks with row ``t*H + h``; K/V store blocks as
    ``[1, bs*H, D]``), so every operation here touches full 2D tiles —
    Mosaic's tiling rules reject both single-head ``(..., 1, D)`` blocks
    and strided middle-dim ref slices. One dense ``(S·H, bs·H)`` score
    tile per block covers all head pairs; the cross-head entries
    (``row % H != col % H``) are masked to the sentinel and zeroed in
    ``p`` exactly like dead positions, so they add exact +0.0 terms to
    the contractions. That spends H× the MXU work of a per-head sweep —
    free in practice: decode attention is DMA-bound (PERF.md's roofline),
    and this shape is what buys one DMA per live block for ALL heads."""
    if quant:
        ks_ref, vs_ref, o_ref, m_acc, l_acc, o_acc = rest
    else:
        o_ref, m_acc, l_acc, o_acc = rest
    sh = q_ref.shape[1]                                    # S * H
    kvh = k_ref.shape[1]                                   # bs * H
    s_len = sh // n_heads
    b = pl.program_id(0)
    j = pl.program_id(1)
    length = len_ref[b]

    @pl.when(j == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, _NEG_BIG)
        l_acc[...] = jnp.zeros_like(l_acc)
        o_acc[...] = jnp.zeros_like(o_acc)

    def compute():
        q = q_ref[0]                                       # [S*H, D]
        kb = k_ref[0]                                      # [bs*H, D]
        vb = v_ref[0]
        m = m_acc[:, 0]
        l = l_acc[:, 0]
        if quant:
            # int8 rows hit the MXU through an in-register cast; the
            # dequant SCALES fold into the contractions instead of
            # scaling the tiles (same math, fewer multiplies, and the
            # f32 dense view never exists anywhere). q rides along to
            # f32 (exact): Mosaic's matmul wants matching operand types
            # and XLA's mixed-dtype dot promotes to f32 anyway.
            kb = kb.astype(jnp.float32)
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(q, kb),
        ) * scale
        if quant:
            s = s * ks_ref[0]                              # [1, bs*H]
        # row i is (token t = i // H, head i % H) at global position
        # lengths-S+t; col c is (store row c // H, head c % H) at
        # position j*bs + c//H — keep causal AND same-head entries
        ri = jax.lax.broadcasted_iota(jnp.int32, (sh, kvh), 0)
        ci = jax.lax.broadcasted_iota(jnp.int32, (sh, kvh), 1)
        q_pos = (length - s_len) + ri // n_heads
        k_pos = j * bs + ci // n_heads
        keep = (k_pos <= q_pos) & (ri % n_heads == ci % n_heads)
        s = jnp.where(keep, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        # explicit zero for masked entries (see _fwd_kernel: a fully-
        # masked row within a visited block would otherwise accumulate
        # mean-of-V garbage through exp(sentinel - sentinel) == 1);
        # here the zeroing also erases the cross-head columns
        p = jnp.where(s <= _NEG_BIG / 2, 0.0, jnp.exp(s - m_new[:, None]))
        l_new = l * corr + jnp.sum(p, axis=-1)
        if quant:
            p = p * vs_ref[0]
        # the PV product runs f32·f32 with V upcast IN-REGISTER —
        # matching cached_attention's `p @ v.astype(f32)` numerics, NOT
        # the flash kernels' storage-dtype MXU trick: greedy decode
        # argmax-ties against the XLA paged path (the token-parity
        # acceptance bar) are far tighter than a bf16 probability
        # matrix's ~0.4% rounding. Streamed bytes are unaffected (the
        # cast happens after the DMA).
        pv = jax.lax.dot_general(
            p, vb.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(p),
        )
        m_acc[...] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[...] = jnp.broadcast_to(l_new[:, None], l_acc.shape)
        o_acc[...] = o_acc[...] * corr[:, None] + pv

    # blocks wholly past the row's length never contribute — skip the
    # math (their DMA is already aliased away by the clamped map)
    pl.when(j * bs < length)(compute)

    @pl.when(j == n_j - 1)
    def _flush():
        l = l_acc[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (o_acc[...] / l_safe[:, None]).astype(o_ref.dtype)


def _store_map(bs: int):
    """Streaming-side index map for the K/V store (and its scale
    arrays): slot j of row b maps to store block ``table[b, j]``, and
    slots past the row's last active block alias that block — the paged
    analog of :func:`_kv_clamped_map`'s causal DMA clamp, driven by the
    scalar-prefetched per-row ``lengths`` instead of a static delta."""
    def kv_map(b, j, table_ref, len_ref):
        n_active = (len_ref[b] + bs - 1) // bs
        jc = jnp.minimum(j, jnp.maximum(n_active - 1, 0))
        return (table_ref[b, jc], 0, 0)

    return kv_map


def paged_attend(q, store_k, store_v, table, lengths, *,
                 k_scale=None, v_scale=None, scale: Optional[float] = None,
                 max_blocks: Optional[int] = None,
                 interpret: Optional[bool] = None):
    """Paged-attention decode over the shared block store, fused.

    - ``q``: ``[B, S, H, D]`` queries for global positions
      ``lengths[b]-S .. lengths[b]-1`` of each row (``S`` is 1 for
      per-token decode, ``k+1`` for the speculative verify window);
    - ``store_k``/``store_v``: ``[n_blocks, bs, H, D]`` — the shared
      store, already holding this step's writes (the scatter stays XLA:
      it moves ``S`` rows; the kernel owns the O(length) read side);
    - ``table``: ``[B, max_blocks]`` int32 block table;
    - ``lengths``: ``[B]`` int32 — valid KV rows per row AFTER the
      write (``pos + S``). Blocks past ``ceil(lengths[b]/bs)`` are
      clamp-skipped: neither streamed nor computed;
    - ``k_scale``/``v_scale``: ``[n_blocks, bs, H]`` f32, present iff
      the store is int8 (dequant folds into the contractions);
    - ``max_blocks``: optional static cap on table slots to sweep
      (callers with static positions pass the batch-max active count —
      the grid then never visits provably-dead table tail entries).

    Returns ``[B, S, H, D]`` in ``q.dtype`` — position-masked exactly
    like :func:`~chainermn_tpu.parallel.sequence.cached_attention` over
    the gathered table span, to fp tolerance (same masked set, flash
    summation order). Off TPU runs in interpret mode by default."""
    b, s_len, h, d = q.shape
    bs = store_k.shape[1]
    n_j = table.shape[1]
    if max_blocks is not None:
        n_j = max(1, min(n_j, int(max_blocks)))
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    quant = k_scale is not None
    table = jnp.asarray(table, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)

    kv_map = _store_map(bs)
    # heads fold into the ROW dimension (free contiguous reshapes) so
    # every block is a full 2D tile: Mosaic's tiling rules reject both
    # single-head (..., 1, D) blocks and strided middle-dim slices, and
    # the flat shape is the better schedule anyway — one DMA per live
    # block for ALL heads. Scales flatten to [n_blocks, 1, bs*H] row
    # vectors for the same reason.
    n_blocks = store_k.shape[0]
    qf = q.reshape(b, s_len * h, d)
    kf = store_k.reshape(n_blocks, bs * h, d)
    vf = store_v.reshape(n_blocks, bs * h, d)
    qo_map = lambda b_, j_, table_ref, len_ref: (b_, 0, 0)
    in_specs = [
        pl.BlockSpec((1, s_len * h, d), qo_map),
        pl.BlockSpec((1, bs * h, d), kv_map),
        pl.BlockSpec((1, bs * h, d), kv_map),
    ]
    operands = [qf, kf, vf]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, bs * h), kv_map),
                     pl.BlockSpec((1, 1, bs * h), kv_map)]
        operands += [k_scale.reshape(n_blocks, 1, bs * h),
                     v_scale.reshape(n_blocks, 1, bs * h)]
    vma = _out_vma(q, store_k, store_v, table, lengths)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bs=bs, n_j=n_j,
                          n_heads=h, quant=quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_j),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, s_len * h, d), qo_map),
            scratch_shapes=[
                pltpu.VMEM((s_len * h, _LANE), jnp.float32),  # running max m
                pltpu.VMEM((s_len * h, _LANE), jnp.float32),  # running l
                pltpu.VMEM((s_len * h, d), jnp.float32),      # unnorm. acc
            ],
        ),
        out_shape=_sds((b, s_len * h, d), q.dtype, vma),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(table, lengths, *operands)
    return out.reshape(b, s_len, h, d)


def bytes_read_model(lengths, *, block_size: int, max_blocks: int,
                     n_heads: int, head_dim: int, n_layers: int = 1,
                     kv_quant: str = "none") -> dict:
    """Per-decode-step KV bytes-READ model (PERF.md "Paged-decode
    kernel"): what one step's attention streams from the store, XLA
    gather path vs fused kernel, summed over rows and layers.

    The XLA path gathers every row's full ``max_blocks`` table span and
    — when int8 — materializes the dequantized f32 dense view (counted
    as its write + read back through the attention contractions). The
    kernel streams ``ceil(len/bs)`` blocks per row in storage dtype and
    never builds the view. Host-side arithmetic on host values: this is
    the cost MODEL the bench record carries next to measured tokens/s,
    not a measurement."""
    lengths = np.asarray(lengths, np.int64)
    row_elems = n_heads * head_dim
    esize = 1 if kv_quant == "int8" else 4
    kv_rows_xla = int(lengths.size) * max_blocks * block_size
    kv_rows_kern = int(
        np.sum(-(-np.maximum(lengths, 0) // block_size)) * block_size)
    per_row_scale = n_heads * 4 if kv_quant == "int8" else 0
    # k + v, per layer
    xla = 2 * kv_rows_xla * (row_elems * esize + per_row_scale)
    kern = 2 * kv_rows_kern * (row_elems * esize + per_row_scale)
    if kv_quant == "int8":
        # the f32 dense view: written once, read back by the einsums
        xla += 2 * 2 * kv_rows_xla * row_elems * 4
    return {
        "xla_bytes": int(xla * n_layers),
        "kernel_bytes": int(kern * n_layers),
        "read_amplification": round(xla / max(kern, 1), 3),
    }


__all__ = ["bytes_read_model", "kernel_supported", "paged_attend"]
