"""Expert parallelism (MoE) over a mesh axis — TPU extension.

SURVEY.md S2.16 marks EP **absent** in the reference (a 2017 framework);
this module adds it the TPU-idiomatic way: experts are sharded over the
communicator's mesh axis, tokens are routed with a top-1 gate and moved to
their expert's rank by ONE ``all_to_all`` each way (the same collective
shape as the reference's channel-parallel convolution and Ulysses attention
— ``lax.all_to_all`` inside ``shard_map``), and every shape is static
(capacity-bounded dispatch) so the whole layer compiles into the step.

Design notes:
- **Capacity + drop**: each expert processes at most
  ``capacity = ceil(tokens_per_rank / n_experts) * capacity_factor`` tokens
  per sending rank. Overflow tokens are dropped (standard Switch-style
  routing; the residual path carries them unchanged). This keeps the
  dispatch tensor static-shaped — data-dependent shapes would break XLA.
- **Combine weights**: the gate probability scales the expert output
  (straight-through for dropped tokens), so the layer is differentiable
  end-to-end; gradients flow through the same all_to_alls transposed.
- **Load-balance loss**: ``aux_loss`` (Switch Transformer form: n_e *
  dot(fraction_routed, mean_gate_prob)) is returned for the trainer to add.

Usage (inside a step traced over ``comm``'s mesh)::

    layer = ExpertParallelMLP(n_experts=comm.size, d_model=64, d_ff=256,
                              axis_name=comm.axis_name)
    params = layer.init(key, tokens)          # tokens: [B_local, T, D]
    y, aux = layer.apply(params, tokens)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class ExpertParallelMLP(nn.Module):
    """Top-1-routed MoE FFN with experts sharded over ``axis_name``.

    ``n_experts`` must be divisible by the axis size; each rank owns
    ``n_experts / axis_size`` experts. Call with ``[B, T, D]`` (per-rank
    local batch); returns ``(out [B, T, D], aux_loss scalar)``.
    """

    n_experts: int
    d_model: int
    d_ff: int
    axis_name: str
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        if d != self.d_model:
            raise ValueError(f"input dim {d} != d_model {self.d_model}")
        n_ranks = lax.psum(1, self.axis_name)
        if self.n_experts % n_ranks:
            raise ValueError(
                f"n_experts={self.n_experts} not divisible by axis size {n_ranks}"
            )
        local_e = self.n_experts // n_ranks
        tokens = x.reshape(b * t, d).astype(self.compute_dtype)
        n_tok = b * t

        # --- gate: top-1 expert per token ------------------------------ #
        gate_logits = nn.Dense(self.n_experts, dtype=self.compute_dtype,
                               name="gate")(tokens)
        gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        expert_idx = jnp.argmax(gate_probs, axis=-1)            # [n_tok]
        gate_val = jnp.take_along_axis(
            gate_probs, expert_idx[:, None], axis=-1
        )[:, 0]                                                  # [n_tok]

        # Switch-style load-balance aux loss (computed over the LOCAL shard;
        # the trainer's loss mean over ranks makes it global)
        frac_routed = jnp.mean(
            jax.nn.one_hot(expert_idx, self.n_experts, dtype=jnp.float32), axis=0
        )
        mean_prob = jnp.mean(gate_probs, axis=0)
        aux_loss = self.n_experts * jnp.sum(frac_routed * mean_prob)

        # --- capacity-bounded dispatch --------------------------------- #
        capacity = int(max(1, (n_tok + self.n_experts - 1) // self.n_experts
                           * self.capacity_factor))
        # position of each token within its expert's queue
        one_hot = jax.nn.one_hot(expert_idx, self.n_experts,
                                 dtype=jnp.int32)                # [n_tok, E]
        pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1) * one_hot
        pos = jnp.sum(pos_in_expert, axis=-1)                    # [n_tok]
        keep = pos < capacity                                    # overflow drop

        # dispatch[e, c, d]: token payload bound for expert e at slot c.
        # Dropped tokens scatter to index == size: genuinely out of bounds,
        # so mode="drop" discards them (-1 would WRAP to the last slot).
        n_slots = self.n_experts * capacity
        dispatch = jnp.zeros((n_slots, d), tokens.dtype)
        scatter_idx = jnp.where(keep, expert_idx * capacity + pos, n_slots)
        dispatch = dispatch.at[scatter_idx].set(tokens, mode="drop")
        dispatch = dispatch.reshape(self.n_experts, capacity, d)

        # --- move tokens to their expert's rank ------------------------ #
        # [n_ranks, local_e, C, D] --all_to_all(split 0, concat 1)-->
        # [local_e, n_ranks, C, D]: rank r receives, for each local expert,
        # every source rank's capacity block (the EP analog of the
        # parallel-conv alltoall).
        shaped = dispatch.reshape(n_ranks, local_e, capacity, d)
        recv = lax.all_to_all(shaped, self.axis_name, split_axis=0,
                              concat_axis=1, tiled=False)
        recv = recv.reshape(local_e, n_ranks * capacity, d)

        # --- per-expert FFN (batched einsum: one MXU-friendly matmul) -- #
        # Expert weights are declared GLOBAL [n_experts, ...] and each rank
        # slices its local block by axis index: init stays ordinary flax and
        # storage is replicated (flax validates param shapes against the
        # declaration, so a shard_map in_spec cannot feed local-shape
        # leaves); at-rest sharding of expert weights is the partitioner's
        # job (fsdp_shard's layout under plain jit), not an in_spec trick.
        # batch_axis=0: each expert inits as an independent (in, out) matrix
        # — a plain lecun_normal would fold n_experts into fan_in and shrink
        # the per-expert std by sqrt(n_experts)
        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", batch_axis=(0,)
        )
        w1 = self.param("w1", expert_init,
                        (self.n_experts, d, self.d_ff), self.compute_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (self.n_experts, 1, self.d_ff), self.compute_dtype)
        w2 = self.param("w2", expert_init,
                        (self.n_experts, self.d_ff, d), self.compute_dtype)
        b2 = self.param("b2", nn.initializers.zeros,
                        (self.n_experts, 1, d), self.compute_dtype)
        r = lax.axis_index(self.axis_name)

        def local(p):
            return lax.dynamic_slice_in_dim(p, r * local_e, local_e, 0)

        h = nn.relu(jnp.einsum("ecd,edf->ecf", recv, local(w1)) + local(b1))
        out = jnp.einsum("ecf,efd->ecd", h, local(w2)) + local(b2)

        # --- route results back (transposed all_to_all) ----------------- #
        # [local_e, n_ranks, C, D] --all_to_all(split 1, concat 0)-->
        # [n_ranks, local_e, C, D]: back on the sender, expert-major order
        # (n_ranks * local_e == E) matches the dispatch layout exactly.
        out = out.reshape(local_e, n_ranks, capacity, d)
        back = lax.all_to_all(out, self.axis_name, split_axis=1,
                              concat_axis=0, tiled=False)
        back = back.reshape(n_slots, d)

        # gather each token's slot; dropped tokens read index n_slots ->
        # fill 0 (identity through the residual path)
        combined = back.at[scatter_idx].get(mode="fill", fill_value=0.0)
        y = combined * gate_val[:, None].astype(combined.dtype)
        return y.reshape(b, t, d).astype(x.dtype), aux_loss


__all__ = ["ExpertParallelMLP"]
