"""Expert parallelism (MoE) over a mesh axis — TPU extension.

SURVEY.md S2.16 marks EP **absent** in the reference (a 2017 framework);
this module adds it the TPU-idiomatic way: experts are sharded over the
communicator's mesh axis, tokens are routed with a top-1 gate and moved to
their expert's rank by ONE ``all_to_all`` each way (the same collective
shape as the reference's channel-parallel convolution and Ulysses attention
— ``lax.all_to_all`` inside ``shard_map``), and every shape is static
(capacity-bounded dispatch) so the whole layer compiles into the step.

Design notes:
- **Capacity + drop**: each expert processes at most
  ``capacity = ceil(tokens_per_rank / n_experts) * capacity_factor`` tokens
  per sending rank. Overflow tokens are dropped (standard Switch-style
  routing; the residual path carries them unchanged). This keeps the
  dispatch tensor static-shaped — data-dependent shapes would break XLA.
- **Combine weights**: the gate probability scales the expert output
  (straight-through for dropped tokens), so the layer is differentiable
  end-to-end; gradients flow through the same all_to_alls transposed.
- **Load-balance loss**: ``aux_loss`` (Switch Transformer form: n_e *
  dot(fraction_routed, mean_gate_prob)) is returned for the trainer to add.

Usage (inside a step traced over ``comm``'s mesh)::

    layer = ExpertParallelMLP(n_experts=comm.size, d_model=64, d_ff=256,
                              axis_name=comm.axis_name)
    params = layer.init(key, tokens)          # tokens: [B_local, T, D]
    y, aux = layer.apply(params, tokens)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _route(gate_probs, n_experts: int, top_k: int, capacity_factor: float):
    """Shared top-k routing: the ONE home of the combine-weight, capacity,
    priority, and drop math for both MoE implementations (the shard_map
    ExpertParallelMLP and the plain-jit GShardMoE are documented numeric
    twins; keeping this logic single-sourced is what keeps them so).

    ``gate_probs [n_tok, E]`` (f32) ->
    ``(combine_w [n_tok, k], flat_idx [k*n_tok], pos [k*n_tok],
    keep [k*n_tok], first_choice_frac [E], capacity)``. Assignments are
    copy-major (all first choices before all second choices), so when
    capacity binds the second choices drop first (GShard priority).
    top_k=1 keeps the raw Switch-style p1 combine weight; top_k=2
    renormalizes the two probs to sum to 1.
    """
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    n_tok = gate_probs.shape[0]
    topk_probs, topk_idx = lax.top_k(gate_probs, top_k)
    if top_k == 1:
        combine_w = topk_probs
    else:
        combine_w = topk_probs / topk_probs.sum(-1, keepdims=True)
    first_choice_frac = jnp.mean(
        jax.nn.one_hot(topk_idx[:, 0], n_experts, dtype=jnp.float32), axis=0
    )
    capacity = int(max(1, (top_k * n_tok + n_experts - 1)
                       // n_experts * capacity_factor))
    flat_idx = topk_idx.T.reshape(-1)                    # [k * n_tok]
    one_hot = jax.nn.one_hot(flat_idx, n_experts, dtype=jnp.int32)
    pos = jnp.sum((jnp.cumsum(one_hot, axis=0) - 1) * one_hot, axis=-1)
    keep = pos < capacity
    return combine_w, flat_idx, pos, keep, first_choice_frac, capacity


class ExpertParallelMLP(nn.Module):
    """Top-k-routed MoE FFN (k = 1 Switch-style, k = 2 GShard-style) with
    experts sharded over ``axis_name``.

    ``n_experts`` must be divisible by the axis size; each rank owns
    ``n_experts / axis_size`` experts. Call with ``[B, T, D]`` (per-rank
    local batch); returns ``(out [B, T, D], aux_loss scalar)``. Routing
    telemetry — ``drop_frac`` (fraction of expert assignments dropped to
    the capacity bound, globally averaged) and ``frac_routed`` (per-expert
    first-choice load) — is sown into the ``"moe_stats"`` collection:
    ``model.apply(..., mutable=["moe_stats"])`` surfaces it without
    changing the return contract. Silent drops were round 3's gap: at
    ``capacity_factor=1.25`` an unbalanced early gate can drop a large
    fraction of tokens with nothing visible in the loss curve.
    """

    n_experts: int
    d_model: int
    d_ff: int
    axis_name: str
    capacity_factor: float = 1.25
    # top_k=2: each token goes to its two best experts; combine weights are
    # the two gate probs renormalized to sum to 1 (top_k=1 keeps the raw
    # Switch-style p1). Second choices get strictly lower capacity priority
    # than every first choice.
    top_k: int = 1
    # Aux loss statistics reduced over the expert axis (pmean) so the
    # balance objective is the global Switch loss, not the mean of per-shard
    # products (those differ when shards see different token mixes).
    global_aux: bool = True
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        if d != self.d_model:
            raise ValueError(f"input dim {d} != d_model {self.d_model}")
        n_ranks = lax.psum(1, self.axis_name)
        if self.n_experts % n_ranks:
            raise ValueError(
                f"n_experts={self.n_experts} not divisible by axis size {n_ranks}"
            )
        local_e = self.n_experts // n_ranks
        tokens = x.reshape(b * t, d).astype(self.compute_dtype)
        n_tok = b * t
        kk = self.top_k

        # --- gate + shared top-k routing (see _route) ------------------ #
        gate_logits = nn.Dense(self.n_experts, dtype=self.compute_dtype,
                               name="gate")(tokens)
        gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        combine_w, flat_idx, pos, keep, frac_routed, capacity = _route(
            gate_probs, self.n_experts, kk, self.capacity_factor
        )

        # Load-balance aux loss (Switch form over FIRST choices). With
        # global_aux the statistics are pmean'd over the axis first, so the
        # objective is exactly n_e * <frac_routed, mean_prob> of the global
        # batch.
        mean_prob = jnp.mean(gate_probs, axis=0)
        if self.global_aux:
            frac_routed = lax.pmean(frac_routed, self.axis_name)
            mean_prob = lax.pmean(mean_prob, self.axis_name)
        aux_loss = self.n_experts * jnp.sum(frac_routed * mean_prob)

        # telemetry: fraction of assignments dropped, globally averaged —
        # sown (not returned) so the (out, aux) contract is unchanged.
        # NOT during init: sowing there would bake a stale "moe_stats"
        # collection into the init output, polluting the param tree and
        # shadowing apply-time values (sow APPENDS to existing entries).
        if not self.is_initializing():
            drop_frac = lax.pmean(1.0 - jnp.mean(keep.astype(jnp.float32)),
                                  self.axis_name)
            self.sow("moe_stats", "drop_frac", drop_frac)
            self.sow("moe_stats", "frac_routed", frac_routed)

        # dispatch[e, c, d]: token payload bound for expert e at slot c.
        # Dropped assignments scatter to index == size: genuinely out of
        # bounds, so mode="drop" discards them (-1 would WRAP to the last
        # slot).
        n_slots = self.n_experts * capacity
        dispatch = jnp.zeros((n_slots, d), tokens.dtype)
        scatter_idx = jnp.where(keep, flat_idx * capacity + pos, n_slots)
        payload = jnp.tile(tokens, (kk, 1))              # copy-major order
        dispatch = dispatch.at[scatter_idx].set(payload, mode="drop")
        dispatch = dispatch.reshape(self.n_experts, capacity, d)

        # --- move tokens to their expert's rank ------------------------ #
        # Row-exchange all_to_all (split_axis == concat_axis == 0, tiled):
        # row r of the send buffer is this rank's capacity block for rank
        # r's experts; after the exchange, row s holds rank s's block for
        # MY experts. This form is its own transpose, so the backward pass
        # is the identical collective (the split!=concat form has a VJP
        # cotangent-layout bug upstream for local_e > 1, caught by
        # test_gradients_flow_multi_expert_per_rank).
        send = dispatch.reshape(n_ranks, local_e * capacity, d)
        recv = lax.all_to_all(send, self.axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
        # [n_ranks, local_e, C, D] -> [local_e, n_ranks*C, D]: each local
        # expert batches every source rank's slots through one einsum
        recv = recv.reshape(n_ranks, local_e, capacity, d)
        recv = recv.transpose(1, 0, 2, 3).reshape(local_e, n_ranks * capacity, d)

        # --- per-expert FFN (batched einsum: one MXU-friendly matmul) -- #
        # Expert weights are declared GLOBAL [n_experts, ...] and each rank
        # slices its local block by axis index: init stays ordinary flax and
        # storage is replicated (flax validates param shapes against the
        # declaration, so a shard_map in_spec cannot feed local-shape
        # leaves); at-rest sharding of expert weights is the partitioner's
        # job (fsdp_shard's layout under plain jit), not an in_spec trick.
        # batch_axis=0: each expert inits as an independent (in, out) matrix
        # — a plain lecun_normal would fold n_experts into fan_in and shrink
        # the per-expert std by sqrt(n_experts)
        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", batch_axis=(0,)
        )
        w1 = self.param("w1", expert_init,
                        (self.n_experts, d, self.d_ff), self.compute_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (self.n_experts, 1, self.d_ff), self.compute_dtype)
        w2 = self.param("w2", expert_init,
                        (self.n_experts, self.d_ff, d), self.compute_dtype)
        b2 = self.param("b2", nn.initializers.zeros,
                        (self.n_experts, 1, d), self.compute_dtype)
        r = lax.axis_index(self.axis_name)

        def local(p):
            return lax.dynamic_slice_in_dim(p, r * local_e, local_e, 0)

        h = nn.relu(jnp.einsum("ecd,edf->ecf", recv, local(w1)) + local(b1))
        out = jnp.einsum("ecf,efd->ecd", h, local(w2)) + local(b2)

        # --- route results back (the same row exchange, inverted) ------- #
        # [local_e, n_ranks, C, D] -> rows by source rank -> exchange:
        # back on the sender, row r holds r's experts' results for my
        # tokens — global-expert-major order matches the dispatch layout.
        out = out.reshape(local_e, n_ranks, capacity, d)
        out = out.transpose(1, 0, 2, 3).reshape(n_ranks, local_e * capacity, d)
        back = lax.all_to_all(out, self.axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
        back = back.reshape(n_slots, d)

        # gather each assignment's slot; dropped assignments read index
        # n_slots -> fill 0 (identity through the residual path), then the
        # k copies combine weighted by their (re)normalized gate probs
        combined = back.at[scatter_idx].get(mode="fill", fill_value=0.0)
        w = combine_w.T.reshape(-1)[:, None].astype(combined.dtype)
        y = (combined * w).reshape(kk, n_tok, d).sum(axis=0)
        return y.reshape(b, t, d).astype(x.dtype), aux_loss


class GShardMoE(nn.Module):
    """Einsum-dispatch MoE FFN for **plain-jit (GSPMD) execution** — the
    partitioner twin of :class:`ExpertParallelMLP`.

    No explicit collectives: routing is expressed as two dispatch/combine
    einsums over a ``[tokens, E, C]`` one-hot tensor, so the module traces
    under plain ``jit`` with no mesh axis bound. Shard the expert stacks
    ``w1/b1/w2/b2`` over a mesh axis at rest
    (:func:`chainermn_tpu.parallel.gspmd.megatron_param_specs` does this
    for ``TransformerLM(moe_impl='gshard')``) and XLA derives the token
    exchange the explicit implementation hand-writes — weights at rest are
    1/n per device, which the replicated-expert-stack EP module cannot do.

    Same contract as ExpertParallelMLP: ``(out [B,T,D], aux_loss)``, with
    ``drop_frac`` / ``frac_routed`` sown into ``"moe_stats"``. Top-1 and
    top-2 routing with the same priority and combine-weight semantics.
    """

    n_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.25
    top_k: int = 1
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        if d != self.d_model:
            raise ValueError(f"input dim {d} != d_model {self.d_model}")
        tokens = x.reshape(b * t, d).astype(self.compute_dtype)
        n_tok = b * t
        kk = self.top_k

        gate_logits = nn.Dense(self.n_experts, dtype=self.compute_dtype,
                               name="gate")(tokens)
        gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        combine_p, flat_idx, pos, keep, frac_routed, capacity = _route(
            gate_probs, self.n_experts, kk, self.capacity_factor
        )
        # the whole (global) batch is visible under plain jit, so the aux
        # statistics are global with no pmean
        mean_prob = jnp.mean(gate_probs, axis=0)
        aux_loss = self.n_experts * jnp.sum(frac_routed * mean_prob)

        if not self.is_initializing():
            self.sow("moe_stats", "drop_frac",
                     1.0 - jnp.mean(keep.astype(jnp.float32)))
            self.sow("moe_stats", "frac_routed", frac_routed)

        # dispatch[a, e, c] = 1 iff assignment a goes to expert e slot c
        dispatch = (jax.nn.one_hot(flat_idx, self.n_experts,
                                   dtype=tokens.dtype)[:, :, None]
                    * jax.nn.one_hot(pos, capacity, dtype=tokens.dtype
                                     )[:, None, :]
                    * keep[:, None, None].astype(tokens.dtype))
        payload = jnp.tile(tokens, (kk, 1))              # [k*n_tok, D]
        expert_in = jnp.einsum("ad,aec->ecd", payload, dispatch)

        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", batch_axis=(0,)
        )
        w1 = self.param("w1", expert_init,
                        (self.n_experts, d, self.d_ff), self.compute_dtype)
        b1 = self.param("b1", nn.initializers.zeros,
                        (self.n_experts, 1, self.d_ff), self.compute_dtype)
        w2 = self.param("w2", expert_init,
                        (self.n_experts, self.d_ff, d), self.compute_dtype)
        b2 = self.param("b2", nn.initializers.zeros,
                        (self.n_experts, 1, d), self.compute_dtype)
        h = nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, w1) + b1)
        out = jnp.einsum("ecf,efd->ecd", h, w2) + b2

        # combine: weight each assignment's slot by its gate prob and sum
        # the k copies per token
        w = combine_p.T.reshape(-1)                      # [k * n_tok]
        combined = jnp.einsum("ecd,aec->ad", out,
                              dispatch * w[:, None, None].astype(out.dtype))
        y = combined.reshape(kk, n_tok, d).sum(axis=0)
        return y.reshape(b, t, d).astype(x.dtype), aux_loss


def drop_frac_from_sown(sown) -> jnp.ndarray:
    """Mean ``drop_frac`` over the MoE layers from a ``moe_stats``
    collection returned by ``model.apply(..., mutable=['moe_stats'])``.

    ``sow`` APPENDS (tuple-valued entries), so the LAST leaf per entry is
    taken in case the caller's variables carried stale stats in. Returns
    0.0 when no layer sowed (``moe_experts`` set but no block actually MoE,
    e.g. ``n_layers=1`` with ``moe_every=2``) — report, don't crash. The
    single home of this extraction for the shard_map step
    (:func:`chainermn_tpu.training.jit_lm_train_step`) and the GSPMD step
    (:func:`chainermn_tpu.parallel.gspmd.gspmd_lm_train_step`)."""
    entries = [v for path, v in jax.tree_util.tree_flatten_with_path(
        sown, is_leaf=lambda x: isinstance(x, tuple))[0]
        if "drop_frac" in jax.tree_util.keystr(path)]
    drops = [e[-1] if isinstance(e, tuple) else e for e in entries]
    return jnp.mean(jnp.stack(drops)) if drops else jnp.float32(0.0)


class MoeStatsAccumulator:
    """Aggregate per-step MoE routing telemetry into an epoch summary.

    Per-step prints were round 4's stopping point (VERDICT weak #7): a user
    saw each step's drop fraction but no drop-rate curve. Feed this the
    ``stats`` dict every LM step returns (``{}`` from dense models is a
    no-op) and read ``summary()`` at epoch/log boundaries::

        acc = MoeStatsAccumulator()
        for batch in epoch:
            params, opt_state, loss, stats = step(params, opt_state, *batch)
            acc.update(stats)
        log(acc.summary())   # {'moe_drop_frac_mean': ..., '_max': ..., 'steps': N}
        acc.reset()

    State is a running (sum, max, count) of device scalars — O(1) memory
    over any run length, no device->host sync inside the step loop, and
    ``summary()`` costs two transfers regardless of step count."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._sum = None
        self._max = None
        self._count = 0

    def update(self, stats: dict) -> None:
        if stats and "moe_drop_frac" in stats:
            d = stats["moe_drop_frac"]
            if self._count == 0:
                self._sum, self._max = d, d
            else:
                self._sum = self._sum + d
                self._max = jnp.maximum(self._max, d)
            self._count += 1

    @property
    def steps(self) -> int:
        return self._count

    def summary(self) -> dict:
        if not self._count:
            return {"moe_drop_frac_mean": 0.0, "moe_drop_frac_max": 0.0,
                    "steps": 0}
        return {
            "moe_drop_frac_mean": float(self._sum) / self._count,
            "moe_drop_frac_max": float(self._max),
            "steps": self._count,
        }


__all__ = ["ExpertParallelMLP", "GShardMoE", "MoeStatsAccumulator",
           "drop_frac_from_sown"]
