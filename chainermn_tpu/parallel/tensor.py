"""Tensor parallelism (Megatron-style) over a mesh axis — TPU extension.

The reference's only tensor-parallel construct is the channel-parallel
convolution example (SURVEY.md S2.16: "no general TP engine"); this module
provides the general engine for transformer-shaped models: column-parallel
and row-parallel projections whose composition moves ONE ``psum`` per MLP
and one per attention block (the Megatron f/g schedule), with the backward
collectives derived by autodiff instead of hand-written.

Layout convention (mirrors :mod:`chainermn_tpu.parallel.moe`): parameters are
declared with their GLOBAL shapes — ordinary ``model.init`` outside
``shard_map`` gives the correct initialization distribution and replicated
storage — and each rank slices its block at apply time by axis index.
Storage is therefore replicated (flax validates param shapes against the
declaration, so shard_map in_specs cannot feed these modules local-shape
leaves); TP here buys *compute* and *activation* sharding. Weights-at-rest
sharding is the partitioner's job — the :mod:`chainermn_tpu.parallel.fsdp`
layout under plain ``jit`` — not a shard_map in_spec trick.

Training with TP layers — the **global-objective pattern** (tested leaf-exact
in ``tests/parallel_tests/test_tensor.py``)::

    def loss(params):                       # params INVARIANT (no pcast)
        local = local_loss(model.apply(params, x))
        return global_objective(local, (dp_axis, tp_axis))

    grads = jax.grad(loss)(params)          # exact global grads, replicated

With invariant params and an invariant (pmean'd) loss, shard_map's
replication tracking assembles every leaf's exact global gradient: sliced
leaves psum their zero-padded slice cotangents, replicated-compute leaves
(row bias, embeddings, layernorms) average their identical copies — no
per-leaf bookkeeping in user code. Do NOT ``pcast`` the params to varying
here (the canonical DP step's trick): with a ``psum`` inside the forward, a
varying loss differentiates the SUM of per-rank losses, which inflates every
pre-psum leaf's gradient by ``n_tp``.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


def _axis_size(axis_name: str) -> int:
    return lax.psum(1, axis_name)


class ColumnParallelDense(nn.Module):
    """``y = x @ W[:, my_slice] + b[my_slice]`` — output feature-sharded.

    ``features`` is the GLOBAL output width; the module returns the local
    ``features / n`` slice. No communication in forward; the backward's
    input-gradient psum is inserted by shard_map's replication tracking
    (Megatron's "f" identity). ``kernel``/``bias`` are *sliced* leaves for
    :func:`tp_grad_mean`.
    """

    features: int
    axis_name: str
    use_bias: bool = True
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n = _axis_size(self.axis_name)
        if self.features % n:
            raise ValueError(
                f"global features {self.features} not divisible by "
                f"tensor-axis size {n}"
            )
        local_f = self.features // n
        w = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), self.compute_dtype,
        )
        r = lax.axis_index(self.axis_name)
        w = lax.dynamic_slice_in_dim(w, r * local_f, local_f, axis=-1)
        y = x.astype(self.compute_dtype) @ w
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.features,), self.compute_dtype)
            b = lax.dynamic_slice_in_dim(b, r * local_f, local_f, axis=-1)
            y = y + b
        return y


class RowParallelDense(nn.Module):
    """``y = psum_tp(x_local @ W[my_slice, :]) + b`` — input feature-sharded,
    output replicated. The one forward collective of the pair (Megatron's
    "g"). ``kernel`` is a *sliced* leaf; ``bias`` adds after the psum on
    every rank identically, so it is a *replicated-compute* leaf.
    """

    features: int
    axis_name: str
    in_features: Optional[int] = None  # GLOBAL input width (default: local*n)
    use_bias: bool = True
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n = _axis_size(self.axis_name)
        local_in = x.shape[-1]
        global_in = self.in_features or local_in * n
        if global_in % n:
            raise ValueError(
                f"global in_features {global_in} not divisible by "
                f"tensor-axis size {n}"
            )
        if global_in // n != local_in:
            raise ValueError(
                f"input is {local_in}-wide locally but global in_features "
                f"{global_in} / {n} ranks = {global_in // n}"
            )
        w = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (global_in, self.features), self.compute_dtype,
        )
        r = lax.axis_index(self.axis_name)
        w = lax.dynamic_slice_in_dim(w, r * local_in, local_in, axis=0)
        y = lax.psum(x.astype(self.compute_dtype) @ w, self.axis_name)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.features,), self.compute_dtype)
        return y


class TensorParallelMLP(nn.Module):
    """column(d_ff) -> activation -> row(d_model): one psum total."""

    d_model: int
    d_ff: int
    axis_name: str
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.d_ff, self.axis_name,
                                compute_dtype=self.compute_dtype)(x)
        h = nn.gelu(h)
        return RowParallelDense(self.d_model, self.axis_name,
                                in_features=self.d_ff,
                                compute_dtype=self.compute_dtype)(h)


class TensorParallelAttention(nn.Module):
    """Multi-head attention with HEADS sharded over the tensor axis:
    column-parallel qkv (each rank computes its ``n_heads/n`` heads),
    local attention, row-parallel output projection (one psum).

    The inner attention is pluggable exactly like ``TransformerBlock``'s
    (``attention='full'|'ring'|'ulysses'|'flash'`` + ``sequence_axis``): the
    sequence-parallel kinds operate per-head, so TP (heads over one mesh
    axis) composes with SP/CP (sequence over another) with no extra code.
    """

    d_model: int
    n_heads: int
    axis_name: str
    causal: bool = True
    attention: str = "full"
    sequence_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, pos_offset=0, kv_cache=None):
        from chainermn_tpu.parallel.sequence import sequence_parallel_attention

        if kv_cache is not None and self.sequence_axis is not None:
            raise ValueError(
                "kv_cache decoding needs an unsharded sequence — rebuild "
                "without sequence_axis for inference"
            )
        if kv_cache is not None and not self.causal:
            raise ValueError(
                "kv_cache decoding is causal by construction (the position "
                "mask); causal=False with a cache would silently mask "
                "attention to later cached positions"
            )
        n = _axis_size(self.axis_name)
        if self.n_heads % n:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by tensor-axis size {n}"
            )
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by n_heads {self.n_heads}"
            )
        d_head = self.d_model // self.n_heads
        local_h = self.n_heads // n
        qkv = ColumnParallelDense(
            3 * self.d_model, self.axis_name,
            compute_dtype=self.compute_dtype, name="qkv_tpcol",
        )(x)
        # local width is 3 * local_h * d_head. The global feature order is
        # thereby DEFINED as (rank, 3, local_head, d_head)-major: rank r's
        # contiguous slice is its own (q, k, v) block for its own heads.
        # Init is i.i.d., so this ordering is as valid as torch/flax's
        # (3, head, d_head); parity tests permute accordingly. NOTE this
        # bakes the TP degree into the stored kernel — restoring a
        # checkpoint at a DIFFERENT degree needs reshard_tp_qkv (restoring
        # unpermuted silently scrambles q/k/v across heads).
        b, t = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(b, t, 3, local_h, d_head)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if kv_cache is not None:
            # per-rank cache over LOCAL heads [B, Tc, local_h, d_head]
            from chainermn_tpu.parallel.sequence import update_cache_and_attend

            o, new_cache = update_cache_and_attend(kv_cache, q, k, v,
                                                   pos_offset)
        else:
            attn_fn = sequence_parallel_attention(
                self.attention, self.sequence_axis, causal=self.causal
            )
            o = attn_fn(q, k, v)
        o = o.reshape(b, t, local_h * d_head)
        out = RowParallelDense(
            self.d_model, self.axis_name, in_features=self.d_model,
            compute_dtype=self.compute_dtype, name="proj_tprow",
        )(o)
        return (out, new_cache) if kv_cache is not None else out


def reshard_tp_qkv(tree, n_heads: int, d_head: int, old_tp: int,
                   new_tp: int):
    """Permute a :class:`TensorParallelAttention` checkpoint between TP
    degrees.

    The fused qkv kernel's column order is DEFINED as
    ``(rank, 3, local_head, d_head)``-major (see the module body), which
    bakes the tensor-axis size into the stored weights: restoring a
    checkpoint trained at one TP degree into a different degree (or into a
    dense block) silently scrambles q/k/v across heads. This helper
    re-orders every ``qkv_tpcol`` kernel/bias in ``tree`` from the
    ``old_tp`` layout to the ``new_tp`` layout via the degree-independent
    canonical ``(3, head, d_head)`` order (head ownership is contiguous:
    rank ``r`` owns heads ``[r*h/n, (r+1)*h/n)``). The row-parallel
    ``proj_tprow`` needs no permutation — its rows are head-major at every
    degree. Raises if either degree does not divide ``n_heads``.
    """
    import jax

    if n_heads % old_tp or n_heads % new_tp:
        raise ValueError(
            f"n_heads {n_heads} must divide by both TP degrees "
            f"({old_tp}, {new_tp})")
    width = 3 * n_heads * d_head

    def to_canonical(cols, n):
        # [..., (rank, 3, lh, dh)] -> [..., (3, head, dh)]
        lead = cols.shape[:-1]
        c = cols.reshape(*lead, n, 3, n_heads // n, d_head)
        c = jnp.moveaxis(c, -4, -3)          # [..., 3, n, lh, dh]
        return c.reshape(*lead, 3, n_heads, d_head)

    def from_canonical(c, n):
        lead = c.shape[:-3]
        c = c.reshape(*lead, 3, n, n_heads // n, d_head)
        c = jnp.moveaxis(c, -3, -4)          # [..., n, 3, lh, dh]
        return c.reshape(*lead, width)

    n_fixed = 0

    def fix(path, leaf):
        nonlocal n_fixed
        keys = jax.tree_util.keystr(path)
        if "qkv_tpcol" not in keys:
            return leaf
        if leaf.shape[-1] != width:
            # a silent skip here would reproduce the exact scramble this
            # helper exists to prevent (wrong n_heads/d_head passed)
            raise ValueError(
                f"qkv_tpcol leaf at {keys} has last dim {leaf.shape[-1]} "
                f"but n_heads={n_heads}, d_head={d_head} imply "
                f"3*h*dh={width} — wrong head geometry for this checkpoint")
        n_fixed += 1
        return from_canonical(to_canonical(leaf, old_tp), new_tp)

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = jax.tree_util.tree_unflatten(
        treedef, [fix(p, l) for p, l in flat])
    if n_fixed == 0:
        raise ValueError(
            "reshard_tp_qkv found no 'qkv_tpcol' leaves in the tree — "
            "nothing was resharded (wrong tree, or a dense checkpoint that "
            "needs no permutation)")
    return out


def vocab_parallel_cross_entropy(local_logits, targets, axis_name: str):
    """Per-token cross entropy over a VOCAB-SHARDED logits tensor, without
    ever materializing the full ``[..., vocab]`` logits (the classic
    large-vocab memory win of a vocab-parallel head).

    ``local_logits [..., V/n]`` is rank ``r``'s contiguous vocab slice
    ``[r*V/n, (r+1)*V/n)`` — e.g. the output of
    ``ColumnParallelDense(vocab_size, axis)``; ``targets`` hold GLOBAL vocab
    ids. Three scalar-per-token collectives: pmax for the stable shift, psum
    of the local sum-exp for the denominator, and a masked psum that routes
    each target's logit from the one rank whose shard holds it. Output is
    invariant over ``axis_name`` (matches
    ``optax.softmax_cross_entropy_with_integer_labels`` on the gathered
    logits — pinned in tests), and autodiff through it yields the sharded
    head's exact gradients under the global-objective pattern.
    """
    r = lax.axis_index(axis_name)
    v_local = local_logits.shape[-1]
    logits = local_logits.astype(jnp.float32)
    start = r * v_local
    gmax = lax.pmax(
        lax.stop_gradient(jnp.max(logits, axis=-1)), axis_name
    )
    shifted = logits - gmax[..., None]
    denom = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
    in_shard = (targets >= start) & (targets < start + v_local)
    local_idx = jnp.clip(targets - start, 0, v_local - 1)
    t_local = jnp.take_along_axis(shifted, local_idx[..., None], axis=-1)[..., 0]
    t_logit = lax.psum(jnp.where(in_shard, t_local, 0.0), axis_name)
    return jnp.log(denom) - t_logit


def global_objective(local_loss, axes):
    """``pmean`` the per-rank loss over every mesh axis it still varies on —
    the closing line of the global-objective pattern (module docstring).

    Why not a plain ``lax.pmean(local, axes)``: after a row-parallel psum the
    loss is already invariant over the tensor axis, and JAX rejects reducing
    an axis the value does not vary on; which axes remain varying depends on
    the model's final layers. This reduces exactly the still-varying subset
    (``jax.typeof(...).vma``), so one call is correct for pure-TP, pure-DP,
    and hybrid steps alike.
    """
    import jax

    if isinstance(axes, str):
        axes = (axes,)
    if not hasattr(jax, "typeof"):
        # Legacy JAX has no vma tracking at all: pmean over EVERY requested
        # axis. Math is unchanged — pmean of a value that happens to be
        # replicated over an axis returns the same value — and the backward
        # psums the pattern needs come from pmean's own transpose.
        return lax.pmean(local_loss, axes)
    # The pattern is built ON vma tracking: with check_vma=False every value
    # reads as vma-empty, no pmean would ever fire, and the "grads" would be
    # per-rank garbage — fail loudly instead (axis_index is varying by
    # construction, so an empty vma on it means tracking is off).
    if not jax.typeof(lax.axis_index(axes[0])).vma:
        raise ValueError(
            "global_objective requires replication (vma) tracking, but this "
            "shard_map was built with check_vma=False — the global-objective "
            "gradient pattern cannot work there (no automatic psum assembly)"
        )
    vary = tuple(a for a in axes if a in jax.typeof(local_loss).vma)
    return lax.pmean(local_loss, vary) if vary else local_loss


__all__ = [
    "ColumnParallelDense",
    "RowParallelDense",
    "TensorParallelMLP",
    "TensorParallelAttention",
    "global_objective",
    "vocab_parallel_cross_entropy",
]
