"""Mesh construction and rank geometry.

TPU-native equivalent of the reference's communication bootstrap utilities
(``[U] chainermn/communicators/_communication_utility.py`` — ``init_ranks``,
``init_intra_mpi_comm``, ``init_inter_mpi_comm``, ``init_nccl_comm``; SURVEY.md
S2.9, unverified upstream-layout cite):

- hostname-gather rank geometry            -> ``jax.devices()`` metadata
  (``process_index`` plays the role of the hostname: devices with the same
  process are "intra-node"/ICI-local, across processes is "inter-node"/DCN)
- NCCL unique-id broadcast over MPI        -> handled inside ``jax.distributed``
  (its KV store is the bootstrap side channel; nothing for us to do)
- intra-/inter-node sub-MPI-communicators  -> factoring the device list into a
  2-D mesh with ``inter`` x ``intra`` axes

Nothing here moves bytes; a ``Mesh`` is pure metadata consumed by ``shard_map``
/ ``pjit``, which is where XLA inserts the actual ICI/DCN collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DEFAULT_AXIS = "ranks"
INTER_AXIS = "inter"  # across processes (DCN on multi-host pods)
INTRA_AXIS = "intra"  # within a process (ICI-local devices)


def _sorted_devices(devices: Sequence[jax.Device] | None) -> list[jax.Device]:
    """Devices in (process_index, id) order so mesh rank order is stable and
    contiguous ranks are ICI-local — the same property the reference's
    hierarchical communicators get from hostname-sorted MPI ranks."""
    if devices is None:
        devices = jax.devices()
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def make_mesh(
    devices: Sequence[jax.Device] | None = None,
    axis_name: str = DEFAULT_AXIS,
) -> Mesh:
    """One flat communicator axis over all devices (the ``pure_nccl`` shape:
    a single collective ring over every participant)."""
    devs = _sorted_devices(devices)
    return Mesh(np.array(devs), (axis_name,))


def make_hierarchical_mesh(
    devices: Sequence[jax.Device] | None = None,
    inter_axis: str = INTER_AXIS,
    intra_axis: str = INTRA_AXIS,
) -> Mesh:
    """Two-level ``inter x intra`` mesh mirroring the reference's
    intra-node / inter-node communicator split.

    On a real multi-host pod the ``intra`` axis is ICI-local to each process
    and ``inter`` crosses DCN. In a single process we factor the device count
    into the most square (inter, intra) grid so the two-level collective
    algorithms remain exercisable (the reference's tests likewise fake
    multi-node with multiple ranks on one box).
    """
    devs = _sorted_devices(devices)
    n = len(devs)
    per_proc: dict[int, int] = {}
    for d in devs:
        per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
    n_proc = len(per_proc)
    local = n // n_proc
    if n_proc > 1 and all(v == local for v in per_proc.values()):
        grid = (n_proc, local)
    else:
        # Single process (or ragged): factor n as the most square grid.
        inter = int(np.sqrt(n))
        while n % inter:
            inter -= 1
        grid = (inter, n // inter)
    arr = np.array(devs).reshape(grid)
    return Mesh(arr, (inter_axis, intra_axis))


def _straddle_warning(shape, proc_counts: dict[int, int], n: int):
    """Warning text when an auto-factored (dp, sp, tp) shape's inner axes
    would straddle host boundaries, else None. Pure function of the chosen
    shape and the per-process device counts so the policy is testable
    without multi-host hardware."""
    if len(proc_counts) <= 1:
        return None  # host-local mesh: nothing can straddle
    per_proc = min(proc_counts.values())
    _, sp, tp = shape
    # aligned means the inner blocks tile host boundaries exactly: tp must
    # divide per_proc, and the sp x tp block must either fit evenly inside
    # a host (divide per_proc) or cover whole hosts (be a multiple of it)
    sptp = sp * tp
    if per_proc % tp:
        straddler = f"tp={tp}"
    elif per_proc % sptp and sptp % per_proc:
        straddler = f"sp x tp = {sptp}"
    else:
        return None
    return (
        f"make_3d_mesh auto-factored {n} devices into dp x sp x tp = "
        f"{tuple(shape)}, but {straddler} does not align with the "
        f"{per_proc} devices per process ({len(proc_counts)} processes): "
        "the inner axes will straddle host boundaries and their "
        "collectives ride DCN — pass shape=(dp, sp, tp) with tp (and "
        "ideally sp x tp) dividing the per-process device count"
    )


def make_3d_mesh(
    devices: Sequence[jax.Device] | None = None,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    tp_axis: str = "tp",
    shape: tuple[int, int, int] | None = None,
) -> Mesh:
    """Three-level ``dp x sp x tp`` mesh for hybrid data x sequence x tensor
    parallel training (extension beyond the reference's two-level split).

    ``tp`` is the innermost axis: device order is (process, id)-sorted, so
    the innermost axis spans ICI-nearest neighbors — the right place for
    tensor parallelism's per-block psums, with sequence-parallel ring hops
    one level out and the data-parallel gradient reduction outermost.
    Without ``shape``, the device count is factored into the most balanced
    (dp, sp, tp) triple — which is process-oblivious: on a MULTI-HOST pod
    pass ``shape`` explicitly with ``tp`` (x ``sp``) dividing the
    per-process device count, or the innermost axes can straddle hosts and
    the per-block psums ride DCN (make_hierarchical_mesh aligns to process
    boundaries automatically; this heuristic does not — it WARNS when its
    auto-chosen tp would straddle).
    """
    devs = _sorted_devices(devices)
    n = len(devs)
    if shape is None:
        best: tuple[int, int, int] = (1, 1, n)
        for a in range(1, n + 1):
            if n % a:
                continue
            m = n // a
            for b in range(1, m + 1):
                if m % b:
                    continue
                cand = (a, b, m // b)
                if max(cand) - min(cand) < max(best) - min(best):
                    best = cand
        shape = best
        # The balanced factorization is process-oblivious; on a multi-host
        # pod inner axes that do not divide the per-process device count
        # straddle hosts and their collectives ride DCN. Surface it instead
        # of silently degrading (pass shape= to fix). Derive the per-process
        # count from the devices actually passed (a host-local subset must
        # not warn against the GLOBAL process count).
        proc_counts: dict[int, int] = {}
        for d in devs:
            pi = getattr(d, "process_index", 0)
            proc_counts[pi] = proc_counts.get(pi, 0) + 1
        msg = _straddle_warning(shape, proc_counts, n)
        if msg is not None:
            import warnings

            warnings.warn(msg, stacklevel=2)
    if int(np.prod(shape)) != n:
        raise ValueError(f"shape {shape} does not cover {n} devices")
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, (dp_axis, sp_axis, tp_axis))


@dataclasses.dataclass(frozen=True)
class RankGeometry:
    """Host-side rank geometry, ChainerMN-shaped (``[U] _communication_utility.
    init_ranks`` returned (global, intra, inter) ranks per process).

    In the SPMD rebuild there are two rank spaces:

    - **device ranks** (0..size-1): positions along the communicator's mesh
      axis. Collectives operate in this space; inside traced code the current
      device rank is ``lax.axis_index(axis)``.
    - **process ranks** (0..process_count-1): host-side identity, used for
      object communication, root-only logging, and data loading. This is what
      the fields below describe for *the calling process*.
    """

    size: int            # total devices on the comm axis
    rank: int            # this process's rank (process space)
    intra_rank: int      # device-space offset of this process within its node
    inter_rank: int      # this process's node (host) index
    intra_size: int      # devices per node; inter_size * intra_size == size
    inter_size: int      # number of nodes (hosts)
    process_size: int    # number of processes (== inter_size unless a
    #                      multi-process-per-host launch is declared; the
    #                      data path — dataset scattering, per-rank
    #                      checkpoints — shards over THIS, not hosts)
    local_device_ranks: tuple[int, ...]  # device ranks this process controls

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "RankGeometry":
        """Geometry for the calling process.

        Supported launches run ONE jax process per host (the standard TPU
        pattern), so ``intra_rank`` is 0 and ``inter_*`` is process-space.
        Multi-process-per-host launches (e.g. one process per chip on a GPU-
        style rig) must declare it via ``CHAINERMN_TPU_PROCS_PER_HOST=k`` —
        jax exposes no portable physical-host identity, so this is an
        explicit contract rather than a silent (and then wrong) assumption;
        an undeclared mismatch raises instead of mis-numbering ranks.
        """
        import os

        devs = list(mesh.devices.flat)
        pidx = jax.process_index()
        procs = sorted({d.process_index for d in devs})
        local = tuple(i for i, d in enumerate(devs) if d.process_index == pidx)
        n_proc = len(procs)
        pph = int(os.environ.get("CHAINERMN_TPU_PROCS_PER_HOST", "1"))
        if pph < 1 or (n_proc % pph and pidx in procs):
            raise ValueError(
                f"CHAINERMN_TPU_PROCS_PER_HOST={pph} does not divide the "
                f"{n_proc} participating processes"
            )
        my = procs.index(pidx) if pidx in procs else 0
        n_local = max(1, len(local))
        return cls(
            size=len(devs),
            rank=pidx,
            intra_rank=(my % pph) * n_local,
            inter_rank=my // pph,
            intra_size=n_local * pph,
            inter_size=max(1, n_proc // pph),
            process_size=max(1, n_proc),
            local_device_ranks=local,
        )
