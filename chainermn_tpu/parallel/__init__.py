from chainermn_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    INTER_AXIS,
    INTRA_AXIS,
    RankGeometry,
    make_hierarchical_mesh,
    make_mesh,
)
from chainermn_tpu.parallel.moe import ExpertParallelMLP
from chainermn_tpu.parallel.sequence import (
    full_attention,
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

__all__ = [
    "DEFAULT_AXIS",
    "INTER_AXIS",
    "INTRA_AXIS",
    "RankGeometry",
    "make_mesh",
    "make_hierarchical_mesh",
    "ExpertParallelMLP",
    "full_attention",
    "ring_attention",
    "ulysses_attention",
    "sequence_parallel_attention",
]
