from chainermn_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    INTER_AXIS,
    INTRA_AXIS,
    RankGeometry,
    make_3d_mesh,
    make_hierarchical_mesh,
    make_mesh,
)
from chainermn_tpu.parallel.fsdp import (
    fsdp_shard,
    fsdp_spec,
    jit_fsdp_train_step,
)
from chainermn_tpu.parallel.moe import (
    ExpertParallelMLP,
    GShardMoE,
    MoeStatsAccumulator,
)
from chainermn_tpu.parallel.gspmd import (
    gspmd_lm_train_step,
    megatron_opt_shard,
    megatron_param_specs,
    megatron_shard,
)
from chainermn_tpu.parallel.tensor import (
    ColumnParallelDense,
    RowParallelDense,
    TensorParallelAttention,
    TensorParallelMLP,
    reshard_tp_qkv,
)
from chainermn_tpu.parallel.sequence import (
    full_attention,
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)

__all__ = [
    "DEFAULT_AXIS",
    "INTER_AXIS",
    "INTRA_AXIS",
    "RankGeometry",
    "make_mesh",
    "make_hierarchical_mesh",
    "make_3d_mesh",
    "ExpertParallelMLP",
    "GShardMoE",
    "MoeStatsAccumulator",
    "gspmd_lm_train_step",
    "megatron_param_specs",
    "megatron_shard",
    "megatron_opt_shard",
    "fsdp_shard",
    "fsdp_spec",
    "jit_fsdp_train_step",
    "ColumnParallelDense",
    "RowParallelDense",
    "TensorParallelAttention",
    "TensorParallelMLP",
    "reshard_tp_qkv",
    "full_attention",
    "ring_attention",
    "ulysses_attention",
    "sequence_parallel_attention",
]
