from chainermn_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    INTER_AXIS,
    INTRA_AXIS,
    RankGeometry,
    make_hierarchical_mesh,
    make_mesh,
)

__all__ = [
    "DEFAULT_AXIS",
    "INTER_AXIS",
    "INTRA_AXIS",
    "RankGeometry",
    "make_mesh",
    "make_hierarchical_mesh",
]
