"""Per-replica health scoring over detector states + replica lifecycle.

The detectors (:mod:`chainermn_tpu.monitor.timeseries`) each answer one
narrow question ("is TTFT p99 drifting", "is decode stalled"); a router
needs one composed verdict per replica. :class:`HealthMonitor` watches a
set of keys (replica ids), each with its detectors plus two lifecycle
probes, and folds them into a :class:`HealthScore`:

- ``healthy`` (0) — nothing firing;
- ``degraded`` (1) — at least one ``severity="degraded"`` detector
  firing (drift, queue pressure, KV pressure);
- ``critical`` (2) — a ``severity="critical"`` detector firing (decode
  stall deadman), the replica's lifecycle state is RESTARTING /
  QUARANTINED / STOPPED, or a warm restart happened since the previous
  evaluation (the *restart latch*: a supervisor recovery faster than one
  collector cadence still produces exactly one CRITICAL verdict, so the
  healthy -> critical -> healthy transition is observable no matter how
  fast the warm restart is).

Every score names its **contributing signals** (which detectors /
lifecycle probes drove the verdict), publishes a ``health_state
{replica=}`` gauge, and emits an edge-triggered ``health_changed`` event
on state transitions. :meth:`HealthMonitor.report` is the ``/health``
HTTP payload; ``FleetRouter.attach_health`` makes the scores a routing
penalty (healthier replicas win placement *before* load is consulted —
degraded replicas are deprioritized long before the supervisor would
quarantine).

Evaluation runs from the owning collector's tick (single evaluator by
contract); the monitor's own lock is a ``sanitizer.make_lock`` leaf
guarding only the watch/score maps, so routers and scrape threads read
``level()`` / ``report()`` without ever stacking on another lock.

This module must not import ``chainermn_tpu.extensions`` (or jax, or the
fleet/serving packages) at module level — the ``fleet_health`` wiring
helper takes the router duck-typed; pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.timeseries import (
    Collector,
    DeadmanDetector,
    Ratio,
    ThresholdDetector,
    TimeSeriesStore,
    ZScoreDetector,
)

HEALTHY, DEGRADED, CRITICAL = "healthy", "degraded", "critical"
_STATE_BY_LEVEL = {0: HEALTHY, 1: DEGRADED, 2: CRITICAL}
_LEVEL_BY_SEVERITY = {"degraded": 1, "critical": 2}

# replica lifecycle states that are NOT critical by themselves (the
# fleet's ReplicaState enum values; anything else — restarting,
# quarantined, stopped — maps straight to CRITICAL). Draining/retired
# are deliberate control-plane transitions, not failures: a gracefully
# retiring replica must not drag the fleet's worst-of verdict down.
_BENIGN_LIFECYCLE = ("starting", "healthy", "draining", "retired")


@dataclass
class HealthScore:
    """One key's composed verdict: state + who drove it."""

    state: str
    level: int
    contributing: list = field(default_factory=list)
    detail: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"state": self.state, "level": self.level,
                "contributing": list(self.contributing),
                "detail": dict(self.detail)}


class _Watch:
    __slots__ = ("detectors", "state_fn", "restarts_fn", "seen_restarts")

    def __init__(self, detectors, state_fn, restarts_fn) -> None:
        self.detectors = list(detectors)
        self.state_fn = state_fn
        self.restarts_fn = restarts_fn
        self.seen_restarts: Optional[int] = None


class HealthMonitor:
    """Compose detector + lifecycle signals into per-key health scores
    (module docstring). ``store`` is the series store the detectors read
    — normally the owning :class:`~chainermn_tpu.monitor.timeseries.
    Collector`'s."""

    def __init__(self, *, registry=None, events=None,
                 store: Optional[TimeSeriesStore] = None,
                 clock=None) -> None:
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        self.store = store if store is not None else TimeSeriesStore()
        self._clock = clock if clock is not None else time.monotonic
        # leaf: guards only the watch/score maps — scoring (detector
        # evaluation, gauge/event publication) runs outside it, so
        # routers and scrapes read level()/report() lock-cheap
        self._lock = sanitizer.make_lock("HealthMonitor._lock", leaf=True)
        self._watches: dict[str, _Watch] = sanitizer.guarded(
            {}, lock=self._lock, name="HealthMonitor._watches")
        self._scores: dict[str, HealthScore] = sanitizer.guarded(
            {}, lock=self._lock, name="HealthMonitor._scores")

    def watch(self, key, *, detectors=(), state_fn: Optional[Callable]
              = None, restarts_fn: Optional[Callable] = None
              ) -> "HealthMonitor":
        """Score ``key`` (a replica id) from ``detectors`` plus optional
        lifecycle probes: ``state_fn() -> ReplicaState|str`` and
        ``restarts_fn() -> int`` (monotonic warm-restart count — an
        increment between evaluations latches one CRITICAL verdict)."""
        w = _Watch(detectors, state_fn, restarts_fn)
        with self._lock:
            self._watches[str(key)] = w
        return self

    def unwatch(self, key) -> "HealthMonitor":
        """Stop scoring ``key`` and forget its last score (a retired
        replica must drop out of the worst-of fleet verdict, not linger
        at whatever state it last held)."""
        with self._lock:
            self._watches.pop(str(key), None)
            self._scores.pop(str(key), None)
        return self

    def add_detectors(self, key, *detectors) -> "HealthMonitor":
        """Extend an existing watch with more detectors (the canary
        path wires regression probes onto an already-watched replica)."""
        with self._lock:
            self._watches[str(key)].detectors.extend(detectors)
        return self

    @property
    def keys(self) -> list:
        with self._lock:
            return sorted(self._watches)

    # -- evaluation -------------------------------------------------------- #

    def _score_watch(self, key: str, w: _Watch, now: float) -> HealthScore:
        level = 0
        contributing: list = []
        detail: dict = {}
        if w.state_fn is not None:
            st = w.state_fn()
            name = str(getattr(st, "value", st))
            detail["replica_state"] = name
            if name not in _BENIGN_LIFECYCLE:
                level = 2
                contributing.append("replica_state")
        if w.restarts_fn is not None:
            restarts = int(w.restarts_fn())
            seen, w.seen_restarts = w.seen_restarts, restarts
            detail["restarts"] = restarts
            if seen is not None and restarts > seen:
                level = 2
                contributing.append("replica_restart")
        for det in w.detectors:
            verdict = det.evaluate(self.store, now,
                                   registry=self._registry,
                                   events=self._events)
            detail[det.name] = verdict
            if verdict.get("firing"):
                contributing.append(det.name)
                level = max(level, _LEVEL_BY_SEVERITY[det.severity])
        return HealthScore(state=_STATE_BY_LEVEL[level], level=level,
                           contributing=contributing, detail=detail)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One scoring pass over every watched key (driven by the
        collector tick, or a test with an injected ``now``): updates the
        score map, publishes ``health_state{replica=}`` gauges, and
        emits an edge-triggered ``health_changed`` event per state
        transition. Returns ``{key: HealthScore}``."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            watches = list(self._watches.items())
            prev = {k: s.state for k, s in self._scores.items()}
        scores = {key: self._score_watch(key, w, now)
                  for key, w in watches}
        with self._lock:
            self._scores.update(scores)
        for key, score in scores.items():
            self._registry.gauge("health_state",
                                 {"replica": key}).set(score.level)
            if prev.get(key) != score.state:
                self._events.emit("health_changed", replica=key,
                                  state=score.state,
                                  was=prev.get(key),
                                  contributing=list(score.contributing))
        return scores

    # -- read side (router / HTTP / reports) ------------------------------- #

    def level(self, key) -> int:
        """0 healthy / 1 degraded / 2 critical; unknown keys are healthy
        (a replica nobody scored yet must not be routed away from)."""
        with self._lock:
            score = self._scores.get(str(key))
        return 0 if score is None else score.level

    def score(self, key) -> Optional[HealthScore]:
        with self._lock:
            return self._scores.get(str(key))

    def score_json(self, key) -> Optional[dict]:
        score = self.score(key)
        return score.to_json() if score is not None else None

    def report(self) -> dict:
        """The ``/health`` payload: per-key scores + the fleet's worst
        state (what an autoscaler would alert on)."""
        with self._lock:
            scores = dict(self._scores)
        worst = max((s.level for s in scores.values()), default=0)
        return {
            "replicas": {k: s.to_json() for k, s in sorted(scores.items())},
            "worst": _STATE_BY_LEVEL[worst],
            "n_watched": len(scores),
        }


# ---------------------------------------------------------------------- #
# standard sensor sets + fleet wiring                                     #
# ---------------------------------------------------------------------- #


def _instrument_key(name: str, instance: str) -> str:
    return f'{name}{{instance="{instance}"}}'


def standard_replica_sensors(instance: str, *,
                             stall_timeout_s: float = 10.0,
                             max_queue_depth: float = 64.0,
                             min_kv_blocks_free: Optional[float] = None,
                             spec: bool = False, z: float = 3.0,
                             active_fn: Optional[Callable] = None,
                             tag: Optional[str] = None) -> tuple:
    """The default ``(signals, detectors)`` for one serving instance
    (``instance`` = its :class:`~chainermn_tpu.serving.metrics.
    ServingMetrics` label): TTFT-p99 z-score drift, queue-depth
    threshold, decode-progress deadman; optionally a free-KV-blocks
    floor and (``spec=True``) a speculative accept-rate ratio signal
    with a downward-drift z-score. ``tag`` names the detectors
    (defaults to the instance) so fleets get per-replica
    ``detector_state`` series."""
    tag = instance if tag is None else str(tag)
    signals: list = []
    detectors: list = [
        ZScoreDetector(
            f"ttft_p99_drift@{tag}",
            _instrument_key("serving_ttft_seconds", instance) + ":p99",
            z=z, direction="above", severity="degraded"),
        ThresholdDetector(
            f"queue_depth@{tag}",
            _instrument_key("serving_queue_depth_now", instance),
            threshold=max_queue_depth, direction="above",
            severity="degraded"),
        DeadmanDetector(
            f"decode_stall@{tag}",
            _instrument_key("serving_tokens_total", instance),
            timeout_s=stall_timeout_s, active_fn=active_fn,
            severity="critical"),
    ]
    if min_kv_blocks_free is not None:
        detectors.append(ThresholdDetector(
            f"kv_blocks_free@{tag}",
            _instrument_key("kv_blocks_free", instance),
            threshold=min_kv_blocks_free, direction="below",
            severity="degraded"))
    if spec:
        accept = f"spec_accept_rate@{tag}"
        signals.append(Ratio(
            _instrument_key("spec_tokens_accepted_total", instance)
            + ":rate",
            _instrument_key("spec_tokens_proposed_total", instance)
            + ":rate",
            name=accept))
        detectors.append(ZScoreDetector(
            f"spec_accept_drift@{tag}", accept, z=z, direction="below",
            severity="degraded"))
    return signals, detectors


def wire_replica(collector: Collector, monitor: HealthMonitor, replica, *,
                 stall_timeout_s: float = 10.0, spec: bool = False,
                 **sensor_kw) -> None:
    """Wire ONE fleet replica into an existing collector + monitor: the
    standard sensor set (keyed by the replica's metrics instance, tagged
    by replica id), lifecycle + restart-latch probes, and the metrics-
    report ``health`` block. :func:`fleet_health` calls this for the
    constructor-time fleet; the control plane calls it again for every
    replica it spawns, so scaled-up capacity is scored from its first
    tick."""
    signals, detectors = standard_replica_sensors(
        replica.metrics.instance, stall_timeout_s=stall_timeout_s,
        spec=spec, tag=str(replica.replica_id),
        active_fn=(lambda r=replica: r.busy), **sensor_kw)
    for sig in signals:
        collector.add_signal(sig)
    monitor.watch(str(replica.replica_id), detectors=detectors,
                  state_fn=(lambda r=replica: r.state),
                  restarts_fn=(lambda r=replica: r.restarts))
    replica.metrics.attach_health(
        lambda m=monitor, k=str(replica.replica_id): m.score_json(k))


def fleet_health(router, *, cadence_s: float = 0.25, registry=None,
                 events=None, clock=None, maxlen: int = 512,
                 stall_timeout_s: float = 10.0,
                 spec: bool = False, **sensor_kw) -> Collector:
    """Wire the whole pipeline onto a :class:`~chainermn_tpu.fleet.
    router.FleetRouter`: one store + collector, the standard sensor set
    per replica (keyed by each replica's metrics instance, tagged by
    replica id), lifecycle + restart-latch probes, and the router's
    routing penalty (``router.attach_health``). Each replica's
    :meth:`~chainermn_tpu.serving.metrics.ServingMetrics.report` also
    grows the ``health`` block. Returns the collector — call
    ``start()`` for the background cadence, or drive ``tick(now=)``
    deterministically in tests."""
    store = TimeSeriesStore(maxlen=maxlen)
    monitor = HealthMonitor(registry=registry, events=events, store=store,
                            clock=clock)
    collector = Collector(registry=registry, events=events, store=store,
                          cadence_s=cadence_s, clock=clock)
    for replica in router.replicas:
        wire_replica(collector, monitor, replica,
                     stall_timeout_s=stall_timeout_s, spec=spec,
                     **sensor_kw)
    collector.attach_health(monitor)
    router.attach_health(monitor)
    return collector


__all__ = [
    "CRITICAL",
    "DEGRADED",
    "HEALTHY",
    "HealthMonitor",
    "HealthScore",
    "fleet_health",
    "standard_replica_sensors",
    "wire_replica",
]
