"""Process-wide metrics registry: counters, gauges, histograms with labels.

The reference framework has no metrics surface at all (SURVEY.md S5: users
bolt Chainer hooks onto the trainer); the serving subsystem (PR 1) grew one
private list per latency series. This module is the one place both sides
publish into: get-or-create instruments keyed by ``name`` + sorted labels,
a JSON-able :meth:`MetricsRegistry.snapshot`, Prometheus-style text
:meth:`MetricsRegistry.exposition`, and cross-rank
:meth:`MetricsRegistry.aggregate` so rank 0 can report fleet-wide p50/p99.

Histograms keep a bounded reservoir of raw samples and report through the
same percentile convention as :func:`chainermn_tpu.extensions.profiling.
latency_report` (``mean/p50/p99``, ``_s``-suffixed for seconds-valued
series), so registry snapshots stay field-compatible with the
``BENCH_*.json`` records the earlier rounds accumulated.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Mapping, Optional

import numpy as np

from chainermn_tpu.analysis import sanitizer

# module import, not the package facade: chainermn_tpu.extensions/__init__
# may be mid-initialization when the communicator layer pulls monitor in
# NOTE: `latency_report` is imported lazily inside Histogram.stats().
# `extensions/__init__` imports `checkpoint`, which imports this package
# (registry counters + flight-recorder events on checkpoint I/O); a
# module-level import here would close that cycle.

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _labels_key(labels: Optional[Mapping[str, str]]) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(lk: tuple) -> str:
    if not lk:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in lk)
    return "{" + inner + "}"


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, labels_key: tuple) -> None:
        self.name = name
        self.labels_key = labels_key
        # leaf: instruments are updated under arbitrary subsystem locks
        # (scheduler, router), so this lock must stay terminal — the
        # sanitizer enforces that nothing is acquired while it is held
        self._lock = sanitizer.make_lock("_Instrument._lock", leaf=True)

    @property
    def key(self) -> str:
        return self.name + _render_labels(self.labels_key)


class Counter(_Instrument):
    """Monotonic counter (requests served, steps run, recompiles)."""

    kind = "counter"

    def __init__(self, name: str, labels_key: tuple) -> None:
        super().__init__(name, labels_key)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge(_Instrument):
    """Point-in-time value (queue depth now, device bytes in use)."""

    kind = "gauge"

    def __init__(self, name: str, labels_key: tuple) -> None:
        super().__init__(name, labels_key)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Instrument):
    """Distribution with a bounded raw-sample reservoir.

    ``unit='s'`` marks a seconds-valued series: its stats come from
    :func:`latency_report` (``mean_s/p50_s/p99_s``) so every latency
    surface in the framework reports through one convention. Unit-less
    series get plain ``mean/p50/p99``. The reservoir keeps the newest
    ``max_samples`` observations — percentile memory is bounded no matter
    how long the process serves.
    """

    kind = "histogram"

    def __init__(self, name: str, labels_key: tuple, unit: str = "",
                 max_samples: int = 4096) -> None:
        super().__init__(name, labels_key)
        self.unit = unit
        self._samples: deque = deque(maxlen=max_samples)
        # observation times (time.monotonic), same maxlen so the two
        # deques stay aligned — the SLO engine's windowed reads
        self._times: deque = deque(maxlen=max_samples)
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float, t: Optional[float] = None) -> None:
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self._times.append(time.monotonic() if t is None else float(t))
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def samples(self) -> list:
        """Newest retained raw samples (bounded; for percentile math)."""
        return list(self._samples)

    def recent(self, window_s: float, now: Optional[float] = None) -> list:
        """Retained samples observed within the last ``window_s`` seconds
        (``now`` defaults to ``time.monotonic()``) — the SLO engine's
        multi-window burn-rate input. Bounded by the reservoir: a window
        wider than the reservoir's history returns what is retained."""
        cutoff = (time.monotonic() if now is None else now) - float(window_s)
        with self._lock:
            return [v for v, t in zip(self._samples, self._times)
                    if t >= cutoff]

    def stats(self) -> dict:
        out: dict = {"count": int(self._count), "sum": float(self._sum)}
        samples = self.samples
        if not samples:
            return out
        if self.unit == "s":
            from chainermn_tpu.extensions.profiling import latency_report

            rep = latency_report(samples, "h")       # h_mean_s, h_p50_s, ...
            out.update({k[len("h_"):]: v for k, v in rep.items()})
        else:
            t = np.asarray(samples, np.float64)
            out["mean"] = float(t.mean())
            out["p50"] = float(np.percentile(t, 50))
            out["p99"] = float(np.percentile(t, 99))
        return out

    def percentile(self, q: float) -> float:
        samples = self.samples
        return float(np.percentile(samples, q)) if samples else 0.0


class MetricsRegistry:
    """Get-or-create instrument registry.

    One process-wide default instance lives in ``chainermn_tpu.monitor``;
    subsystems may also carry private registries (tests, isolation).
    Same ``(name, labels)`` always returns the same instrument; the same
    name with a different *kind* is a programming error and raises.
    """

    def __init__(self) -> None:
        self._lock = sanitizer.make_lock("MetricsRegistry._lock")
        self._instruments: dict[tuple, _Instrument] = sanitizer.guarded(
            {}, lock=self._lock, name="MetricsRegistry._instruments")

    # ------------------------------------------------------------------ #
    # instrument creation                                                 #
    # ------------------------------------------------------------------ #

    def _get(self, cls, name: str, labels, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        lk = _labels_key(labels)
        key = (name, lk)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, lk, **kwargs)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, labels: Optional[Mapping] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[Mapping] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[Mapping] = None, *,
                  unit: str = "", max_samples: int = 4096) -> Histogram:
        return self._get(Histogram, name, labels, unit=unit,
                         max_samples=max_samples)

    # ------------------------------------------------------------------ #
    # reporting                                                           #
    # ------------------------------------------------------------------ #

    def _by_kind(self):
        with self._lock:
            insts = list(self._instruments.values())
        counters = [i for i in insts if isinstance(i, Counter)]
        gauges = [i for i in insts if isinstance(i, Gauge)]
        hists = [i for i in insts if isinstance(i, Histogram)]
        return counters, gauges, hists

    def snapshot(self) -> dict:
        """JSON-able state of every instrument: ``{"counters": {key: int},
        "gauges": {key: float}, "histograms": {key: {count, sum, mean,
        p50, p99}}}`` where ``key`` is ``name{label="v",...}``."""
        counters, gauges, hists = self._by_kind()
        return {
            "counters": {c.key: int(c.value) for c in counters},
            "gauges": {g.key: float(g.value) for g in gauges},
            "histograms": {h.key: h.stats() for h in hists},
        }

    def exposition(self) -> str:
        """Prometheus text exposition. Counters/gauges verbatim; histograms
        as summaries (``quantile`` series + ``_sum``/``_count``) — the
        format a scrape endpoint or pushgateway ingests directly."""
        counters, gauges, hists = self._by_kind()
        lines: list[str] = []
        seen_type: set[str] = set()

        def type_line(name: str, kind: str) -> None:
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {name} {kind}")

        for c in sorted(counters, key=lambda i: i.key):
            type_line(c.name, "counter")
            lines.append(f"{c.key} {int(c.value)}")
        for g in sorted(gauges, key=lambda i: i.key):
            type_line(g.name, "gauge")
            lines.append(f"{g.key} {float(g.value):g}")
        for h in sorted(hists, key=lambda i: i.key):
            type_line(h.name, "summary")
            for q in (0.5, 0.99):
                ql = self._with_label(h, "quantile", str(q))
                lines.append(f"{h.name}{ql} {h.percentile(q * 100):g}")
            suffix = _render_labels(h.labels_key)
            lines.append(f"{h.name}_sum{suffix} {h.sum:g}")
            lines.append(f"{h.name}_count{suffix} {h.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _with_label(inst: _Instrument, k: str, v: str) -> str:
        lk = tuple(sorted(inst.labels_key + ((k, v),)))
        return _render_labels(lk)

    # ------------------------------------------------------------------ #
    # cross-rank aggregation                                              #
    # ------------------------------------------------------------------ #

    def _rank_payload(self) -> dict:
        counters, gauges, hists = self._by_kind()
        return {
            "counters": {c.key: int(c.value) for c in counters},
            "gauges": {g.key: float(g.value) for g in gauges},
            "hist": {
                h.key: {"unit": h.unit, "count": h.count, "sum": h.sum,
                        "samples": h.samples}
                for h in hists
            },
        }

    def aggregate(self, comm) -> dict:
        """Fleet-wide snapshot over a communicator.

        Rides the same process-space object transport as
        :class:`~chainermn_tpu.extensions.observation_aggregator.
        ObservationAggregator` (one ``allgather_obj`` of the per-rank
        state), then merges: counters SUM across ranks, gauges MEAN (the
        ObservationAggregator convention), histogram reservoirs
        concatenate so the reported p50/p99 are over the fleet's pooled
        samples — rank 0's log then reflects the whole job, not one
        shard. Every rank returns the same merged dict.
        """
        gathered = comm.allgather_obj(self._rank_payload())
        return merge_rank_payloads(gathered)


def merge_rank_payloads(payloads: list) -> dict:
    """Merge per-rank :meth:`MetricsRegistry._rank_payload` dicts into one
    fleet snapshot (split out of :meth:`MetricsRegistry.aggregate` so the
    merge semantics are unit-testable without processes)."""
    counters: dict[str, int] = {}
    gauge_vals: dict[str, list] = {}
    hist: dict[str, dict] = {}
    for p in payloads:
        for k, v in p.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in p.get("gauges", {}).items():
            gauge_vals.setdefault(k, []).append(float(v))
        for k, h in p.get("hist", {}).items():
            ent = hist.setdefault(
                k, {"unit": h.get("unit", ""), "count": 0, "sum": 0.0,
                    "samples": []})
            ent["count"] += int(h.get("count", 0))
            ent["sum"] += float(h.get("sum", 0.0))
            ent["samples"].extend(h.get("samples", ()))
    histograms = {}
    for k, ent in hist.items():
        out = {"count": ent["count"], "sum": ent["sum"]}
        samples = ent["samples"]
        if samples:
            if ent["unit"] == "s":
                from chainermn_tpu.extensions.profiling import latency_report

                rep = latency_report(samples, "h")
                out.update({f[len("h_"):]: v for f, v in rep.items()})
            else:
                t = np.asarray(samples, np.float64)
                out["mean"] = float(t.mean())
                out["p50"] = float(np.percentile(t, 50))
                out["p99"] = float(np.percentile(t, 99))
        histograms[k] = out
    return {
        "ranks": len(payloads),
        "counters": counters,
        "gauges": {k: float(np.mean(v)) for k, v in gauge_vals.items()},
        "histograms": histograms,
    }


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "merge_rank_payloads",
]
