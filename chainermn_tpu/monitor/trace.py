"""Request-scoped tracing: span trees, context propagation, Chrome export.

The monitor's metrics (PR 2) are all *aggregate*: when one request's TTFT
lands in the p99 or one training step stalls, nothing says where the time
went — queue wait vs prefix match vs bucketed prefill vs decode stall, or
host collate vs dispatch vs device step. This module is the Dapper-style
causal layer under those aggregates:

- :class:`Span` — one named wall-clock interval (``trace_id`` /
  ``span_id`` / ``parent_id``, monotonic start/end, labels);
- :class:`Trace` — one request's (or one training step's) span tree, a
  context that rides the work across threads: the serving scheduler
  attaches spans to a request's trace from the engine thread while the
  submitter holds the handle;
- :class:`Tracer` — the process-wide collector: head sampling
  (``sample=N`` keeps every Nth started trace) with **forced retention on
  error / deadline miss** (the traces worth keeping are exactly the ones
  sampling would lose), a bounded ring of finished traces, and export as
  Chrome trace-event JSON (loadable in ``chrome://tracing`` / Perfetto).

Cost model: recording a span is a handful of host dict/list operations
under a per-trace lock — no device work, no I/O, no serialization (export
pays those, at scrape time). ``sample=0`` disables tracing entirely:
:meth:`Tracer.trace` then returns the singleton :data:`NULL_TRACE`, whose
every method is a no-op, so instrumented code never branches on "is
tracing on".

Ambient spans: ``with tracer.trace("train_step", step=i):`` installs the
trace as the calling thread's current context, and the module-level
:func:`span` helper attaches a child to whatever context is current (a
no-op otherwise) — deep callees (the loss-window fetch, an async
checkpoint enqueue) annotate themselves without threading a handle
through every signature. Cross-thread work (serving) passes the
:class:`Trace` handle explicitly instead.

This module must not import ``chainermn_tpu.extensions`` (or jax) at
module level — see the lazy-``latency_report`` note in ``registry.py``;
pinned by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional


class Span:
    """One named interval in a trace. ``t0``/``t1`` are
    ``time.perf_counter()`` values (monotonic); ``t1 is None`` while the
    span is open. Treat as read-only outside the owning :class:`Trace`."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "labels")

    def __init__(self, name: str, trace_id: str, span_id: int,
                 parent_id: Optional[int], t0: float,
                 labels: Optional[dict] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.labels = labels or {}

    @property
    def duration_s(self) -> float:
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0

    def __repr__(self) -> str:
        return (f"<Span {self.name!r} {self.duration_s * 1e3:.3f}ms "
                f"trace={self.trace_id}>")


class _SpanCtx:
    """Context-manager handle for one open span: closes it on exit and
    (when the span was opened ambiently) pops it from the thread's
    current-span stack."""

    __slots__ = ("_trace", "_span", "_ambient")

    def __init__(self, trace: "Trace", span: Span, ambient: bool) -> None:
        self._trace = trace
        self._span = span
        self._ambient = ambient

    @property
    def span(self) -> Span:
        return self._span

    def label(self, **labels) -> None:
        self._span.labels.update(labels)

    def __enter__(self) -> "_SpanCtx":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is not None:
            self._span.labels.setdefault("error", exc_type.__name__)
            self._trace.mark_error(exc_type.__name__)
        self._trace.end_span(self._span)
        if self._ambient:
            self._trace._tracer._pop_ambient(self._span)


class Trace:
    """One trace: a bounded span tree plus the flags that drive retention.

    Spans may be attached from any thread (per-trace lock); the tree is
    append-only until :meth:`finish`. ``max_spans`` bounds memory per
    trace — spans past the cap are counted (``dropped_spans``), not
    stored, so a pathological request can't grow without limit.
    """

    def __init__(self, tracer: "Tracer", trace_id: str, name: str,
                 kind: str, seq: int, labels: dict,
                 max_spans: int) -> None:
        self._tracer = tracer
        self.trace_id = trace_id
        self.kind = kind
        self.seq = seq
        self.max_spans = max_spans
        self.dropped_spans = 0
        self.error: Optional[str] = None
        self.deadline_miss = False
        self.forced = False
        self.finished = False
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.root = Span(name, trace_id, 0, None, time.perf_counter(),
                         dict(labels))
        self.spans: list[Span] = [self.root]

    enabled = True

    # -- span construction ------------------------------------------------ #

    def start_span(self, name: str, parent: Optional[Span] = None,
                   **labels) -> Span:
        """Open a child span (of ``parent``, default the root). Close it
        with :meth:`end_span` — use :meth:`span` for the common
        context-managed form."""
        parent = parent if parent is not None else self.root
        sp = Span(name, self.trace_id, next(self._ids), parent.span_id,
                  time.perf_counter(), labels)
        with self._lock:
            if self.finished or len(self.spans) >= self.max_spans:
                self.dropped_spans += 1
            else:
                self.spans.append(sp)
        return sp

    def end_span(self, sp: Span, **labels) -> None:
        if sp.t1 is None:
            sp.t1 = time.perf_counter()
        if labels:
            sp.labels.update(labels)

    def span(self, name: str, parent: Optional[Span] = None,
             **labels) -> _SpanCtx:
        """``with trace.span("prefill", bucket=64): ...``"""
        return _SpanCtx(self, self.start_span(name, parent, **labels),
                        ambient=False)

    def add_span(self, name: str, t0: float, t1: float,
                 parent: Optional[Span] = None, **labels) -> None:
        """Attach an already-measured interval (``perf_counter`` values) —
        the form the serving scheduler uses when one device call covers a
        whole admission group and each member gets its own span."""
        sp = self.start_span(name, parent, **labels)
        sp.t0, sp.t1 = t0, t1

    # -- flags ------------------------------------------------------------ #

    def mark_error(self, error: str = "error") -> None:
        """Force retention: errored traces are kept regardless of the
        sampling decision (they are the ones worth reading)."""
        self.error = self.error or str(error)

    def mark_deadline_miss(self) -> None:
        self.deadline_miss = True

    def force(self) -> None:
        self.forced = True

    # -- lifecycle --------------------------------------------------------- #

    def finish(self, **labels) -> None:
        """Close the root (and any still-open span), then hand the trace
        to the tracer for the keep/drop decision. Idempotent."""
        with self._lock:
            if self.finished:
                return
            self.finished = True
            now = time.perf_counter()
            for sp in self.spans:
                if sp.t1 is None:
                    sp.t1 = now
        if labels:
            self.root.labels.update(labels)
        self._tracer._finish(self)

    def __enter__(self) -> "Trace":
        self._tracer._push_ambient(self.root, self)
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self._tracer._pop_ambient(self.root)
        if exc_type is not None:
            self.mark_error(exc_type.__name__)
        self.finish()

    # -- reporting --------------------------------------------------------- #

    @property
    def duration_s(self) -> float:
        return self.root.duration_s

    def breakdown(self) -> dict:
        """Critical-path attribution: total root time, per-name summed
        durations of the root's DIRECT children (``decode_step`` spans
        collapse into one ``decode_step`` bucket with a count), and the
        ``untracked`` remainder — where the time went, as one dict."""
        with self._lock:
            spans = list(self.spans)
        phases: dict[str, float] = {}
        counts: dict[str, int] = {}
        child_total = 0.0
        for sp in spans:
            if sp.parent_id != 0:
                continue
            d = sp.duration_s
            phases[sp.name] = phases.get(sp.name, 0.0) + d
            counts[sp.name] = counts.get(sp.name, 0) + 1
            child_total += d
        total = self.duration_s
        out = {
            "trace_id": self.trace_id,
            "total_s": round(total, 6),
            "phases_s": {k: round(v, 6) for k, v in phases.items()},
            "phase_counts": counts,
            "untracked_s": round(max(0.0, total - child_total), 6),
        }
        if self.error:
            out["error"] = self.error
        if self.deadline_miss:
            out["deadline_miss"] = True
        return out


class _NullTrace:
    """The disabled-tracing singleton: every method is a no-op, every
    context manager is empty, so call sites never branch."""

    enabled = False
    trace_id = ""
    error = None
    deadline_miss = False
    spans: list = []
    root = None

    def start_span(self, name, parent=None, **labels):
        return None

    def end_span(self, sp, **labels):
        pass

    def span(self, name, parent=None, **labels):
        return self

    def add_span(self, name, t0, t1, parent=None, **labels):
        pass

    def label(self, **labels):
        pass

    def mark_error(self, error="error"):
        pass

    def mark_deadline_miss(self):
        pass

    def force(self):
        pass

    def finish(self, **labels):
        pass

    def breakdown(self):
        return {}

    @property
    def duration_s(self):
        return 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NULL_TRACE = _NullTrace()


class Tracer:
    """Process-wide trace collector.

    Parameters
    ----------
    sample : int
        Head-sampling rate: keep every ``sample``-th started trace
        (``1`` = all, the default — the ring bounds memory either way).
        ``0`` disables tracing: :meth:`trace` returns :data:`NULL_TRACE`
        and nothing records. Error / deadline-miss / forced traces are
        retained regardless of the sampling decision.
    ring : int
        Finished traces retained (newest win).
    max_spans : int
        Per-trace span cap (see :class:`Trace`).
    """

    def __init__(self, *, sample: int = 1, ring: int = 256,
                 max_spans: int = 512) -> None:
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._tls = threading.local()
        self.configure(sample=sample, ring=ring, max_spans=max_spans)

    def configure(self, *, sample: Optional[int] = None,
                  ring: Optional[int] = None,
                  max_spans: Optional[int] = None) -> None:
        """Reconfigure in place (the default tracer is process-global, so
        examples/benches tune it rather than replace it). Changing
        ``ring`` keeps the newest already-finished traces."""
        with self._lock:
            if sample is not None:
                self.sample = int(sample)
            if max_spans is not None:
                self.max_spans = int(max_spans)
            if ring is not None:
                old = list(getattr(self, "_ring", ()))
                self._ring: deque = deque(old, maxlen=int(ring))

    @property
    def enabled(self) -> bool:
        return self.sample > 0

    # -- trace creation ---------------------------------------------------- #

    def trace(self, name: str, *, kind: str = "request", **labels):
        """Start a trace (or return :data:`NULL_TRACE` when disabled).
        Usable as a context manager (ambient form — training loops) or
        held and finished explicitly (serving requests)."""
        if self.sample <= 0:
            return NULL_TRACE
        seq = next(self._seq)
        trace_id = f"{os.getpid():x}-{seq:x}"
        return Trace(self, trace_id, name, kind, seq, labels,
                     self.max_spans)

    def _finish(self, trace: Trace) -> None:
        keep = (trace.forced or trace.error is not None
                or trace.deadline_miss
                or (self.sample > 0 and trace.seq % self.sample == 0))
        if not keep:
            return
        with self._lock:
            self._ring.append(trace)

    # -- ambient (thread-local) context ------------------------------------ #

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push_ambient(self, span: Span, trace: Trace) -> None:
        self._stack().append((span, trace))

    def _pop_ambient(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1][0] is span:
            st.pop()

    def current(self) -> Optional[Trace]:
        """The calling thread's innermost ambient trace, or None."""
        st = self._stack()
        return st[-1][1] if st else None

    def span(self, name: str, **labels):
        """Child span of the calling thread's current ambient span — a
        no-op context manager when no trace is ambient. The deep-callee
        annotation hook (loss-window fetch, checkpoint enqueue)."""
        st = self._stack()
        if not st:
            return NULL_TRACE
        parent, trace = st[-1]
        sp = trace.start_span(name, parent, **labels)
        self._push_ambient(sp, trace)
        return _SpanCtx(trace, sp, ambient=True)

    def mark_current_error(self, error: str) -> None:
        """Flag the ambient trace (if any) for forced retention — the
        RecompileGuard hook: a step that recompiled is always worth its
        trace."""
        cur = self.current()
        if cur is not None:
            cur.mark_error(error)

    # -- retrieval / export ------------------------------------------------ #

    def finished(self, kind: Optional[str] = None,
                 since: Optional[float] = None) -> list[Trace]:
        """Retained traces, oldest first; filter by ``kind`` and/or root
        end time (``perf_counter`` value)."""
        with self._lock:
            traces = list(self._ring)
        if kind is not None:
            traces = [t for t in traces if t.kind == kind]
        if since is not None:
            traces = [t for t in traces
                      if t.root.t1 is not None and t.root.t1 >= since]
        return traces

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_events(self, traces: Optional[list] = None) -> list[dict]:
        """Chrome trace-event list: one ``ph="X"`` (complete) event per
        closed span, ``ts``/``dur`` in microseconds, one pid per process
        and one tid per trace, plus ``M`` metadata events naming each
        trace row — the layout Perfetto renders as one lane per
        request/step."""
        if traces is None:
            traces = self.finished()
        events: list[dict] = []
        pid = os.getpid()
        for t in traces:
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": t.seq,
                "args": {"name": f"{t.kind} {t.trace_id}"},
            })
            for sp in t.spans:
                if sp.t1 is None:
                    continue
                args = {"trace_id": t.trace_id, "span_id": sp.span_id,
                        "parent_id": sp.parent_id}
                args.update(sp.labels)
                events.append({
                    "name": sp.name,
                    "cat": t.kind,
                    "ph": "X",
                    "ts": round(sp.t0 * 1e6, 3),
                    "dur": round((sp.t1 - sp.t0) * 1e6, 3),
                    "pid": pid,
                    "tid": t.seq,
                    "args": args,
                })
        return events

    def export_chrome(self, file: Optional[str] = None,
                      traces: Optional[list] = None) -> dict:
        """The full Chrome trace object (``{"traceEvents": [...]}``);
        written as JSON to ``file`` when given. Load the file in
        ``chrome://tracing`` or https://ui.perfetto.dev."""
        out = {
            "traceEvents": self.chrome_events(traces),
            "displayTimeUnit": "ms",
        }
        if file:
            with open(file, "w") as f:
                # default=str: labels are caller-supplied and may carry
                # numpy scalars etc. — a trace dump must never raise
                json.dump(out, f, default=str)
        return out

    def stats(self) -> dict:
        with self._lock:
            n = len(self._ring)
            errs = sum(1 for t in self._ring if t.error is not None)
            misses = sum(1 for t in self._ring if t.deadline_miss)
        return {"retained": n, "errored": errs, "deadline_missed": misses,
                "sample": self.sample}


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-wide default :class:`Tracer` (serving, training, and
    the HTTP ``/traces`` endpoint all share it)."""
    return _TRACER


def span(name: str, **labels):
    """Module-level ambient-span helper on the default tracer:
    ``with trace.span("checkpoint_enqueue"): ...`` annotates the current
    trace if one is ambient on this thread, else does nothing."""
    return _TRACER.span(name, **labels)


__all__ = ["NULL_TRACE", "Span", "Trace", "Tracer", "get_tracer", "span"]
