"""Canonical metric-name and event-kind catalog.

Metric and event names are wire protocol: dashboards, the ``/metrics``
scrape endpoint, the SLO engine, and bench baselines all key on them. A
typo forks the time series silently. Every literal name passed to
``MetricsRegistry.counter/gauge/histogram`` or ``EventLog.emit`` must
appear here; graftlint's consistency checker fails the build on a name
missing from the catalog, a catalog entry no code emits, or a
convention violation (``^[a-z][a-z0-9_]*$``, counters end ``_total``,
``_seconds`` exactly for ``unit="s"`` histograms).

Stdlib-only on purpose: the analyzer parses this file, it never imports
it, and monitor stays extension-free.
"""

from __future__ import annotations

METRIC_NAMES = frozenset({
    # dataflow / dispatch
    "device_bytes_in_use",
    "device_peak_bytes_in_use",
    "dispatch_inflight",
    "dispatch_lag_steps",
    "loss_fetch_seconds",
    "loss_fetch_total",
    "prefetch_batches_total",
    "prefetch_h2d_seconds",
    "prefetch_queue_depth",
    "prefetch_stall_seconds",
    "prefetch_stall_total",
    # training / resilience
    "checkpoint_async_errors_total",
    "checkpoint_async_save_seconds",
    "checkpoint_corrupt_total",
    "checkpoint_load_seconds",
    "checkpoint_save_seconds",
    "faults_injected_total",
    "recompiles_total",
    "retries_exhausted_total",
    "retries_total",
    "step_time_seconds",
    "steps_total",
    "trace_phase_seconds",
    "trainer_failures_total",
    "trainer_mttr_seconds",
    "trainer_restores_total",
    # serving engine / scheduler
    "cached_prefix_frac",
    "kv_block_appends_total",
    "kv_blocks_free",
    "kv_blocks_in_use",
    "kv_blocks_per_request",
    "kv_preemptions_total",
    # chunked prefill + KV migration (disaggregated prefill/decode tiers)
    "chunk_tokens",
    "kv_migrated_blocks_total",
    "kv_migrations_total",
    "migration_seconds",
    # fleet-wide KV reuse (prefix sharing + decode rebalancing)
    "kv_rebalances_total",
    "kv_shares_total",
    "share_payload_cache_evictions_total",
    "share_payload_cache_hits_total",
    "prefill_chunks_total",
    "prefill_batch_size",
    "prefix_cache_evictions_total",
    "prefix_cache_hits_total",
    "prefix_cache_inserted_blocks_total",
    "prefix_cache_misses_total",
    "serving_active_slots",
    "serving_decode_steps_total",
    "serving_engine_restarts_total",
    "serving_prefills_total",
    "serving_queue_depth",
    "serving_queue_depth_now",
    "serving_requests_cancelled_total",
    "serving_requests_completed_total",
    "serving_requests_errored_total",
    "serving_requests_rejected_total",
    "serving_requests_shed_total",
    "serving_requests_submitted_total",
    "serving_scheduler_restarts_total",
    "serving_slot_occupancy",
    # overload robustness (priority classes + fairness + brownout)
    "brownout_level",
    "serving_class_preemptions_total",
    "serving_class_queue_depth",
    "serving_tenant_sheds_total",
    "serving_tokens_total",
    "serving_tpot_seconds",
    "serving_ttft_seconds",
    "serving_weight_version",
    "spec_accept_length",
    "spec_tokens_accepted_total",
    "spec_tokens_proposed_total",
    # fleet / deploy
    "deploy_swap_failures_total",
    "deploy_swap_seconds",
    "deploy_swaps_total",
    "fleet_affinity_hits_total",
    "fleet_affinity_misses_total",
    "fleet_replica_restarts_total",
    "fleet_replica_state",
    "fleet_requests_total",
    "fleet_reroutes_total",
    "fleet_route_fallbacks_total",
    "fleet_shed_total",
    # fleet edge overload protection (retry budgets + circuit breaker)
    "fleet_breaker_state",
    "fleet_retry_denied_total",
    # control plane (autoscaler + canary deploys + rebalancing)
    "canary_deploys_total",
    "canary_promotes_total",
    "canary_rollbacks_total",
    "controller_canary_phase",
    "controller_scale_downs_total",
    "controller_scale_ups_total",
    "controller_target_replicas",
    "controller_ticks_total",
    "fleet_admission_weight",
    # cost accounting (per-tenant resource ledger + goodput breakdown)
    "cost_conservation_error",
    "goodput_fraction",
    "tenant_device_seconds_total",
    "tenant_kv_block_seconds_total",
    # SLO
    "slo_breaches_total",
    "slo_burn_rate",
    "slo_compliant",
    # continuous telemetry (time-series collector + health scoring)
    "detector_state",
    "health_state",
    "ts_collect_lag_seconds",
    "ts_samples_total",
    # concurrency sanitizer
    "lock_hold_seconds",
})

EVENT_KINDS = frozenset({
    # training / resilience
    "checkpoint_async_error",
    "checkpoint_corrupt",
    "checkpoint_load",
    "checkpoint_save",
    "checkpoint_save_async_enqueued",
    "compile",
    "fault_injected",
    "recompile",
    "retry",
    "retry_exhausted",
    "step_end",
    "step_start",
    "trainer_failure",
    "trainer_giving_up",
    "trainer_recovered",
    "trainer_restore",
    "trainer_resume",
    "trainer_snapshot",
    # serving engine / scheduler
    "admission_error",
    "decode_step",
    "engine_error",
    "engine_restart",
    "first_token",
    "kv_admit_defer",
    "kv_append",
    "kv_migrate",
    "kv_preempt",
    "paged_kernel_fallback",
    "prefill",
    "prefill_chunk",
    "prefix_evict",
    "prefix_insert",
    "prefix_insert_error",
    "reject",
    "serving_warmup",
    "shed",
    "slot_admit",
    "slot_retire",
    "spec_rollback",
    "submit",
    "swap_fence",
    # fleet / deploy
    "breaker_close",
    "breaker_open",
    "brownout_step",
    "fleet_publish",
    "fleet_replica_error",
    "fleet_replica_quarantine",
    "fleet_retire",
    "fleet_route",
    "fleet_route_fallback",
    "fleet_shed",
    "fleet_spawn",
    "fleet_spawn_restore",
    # fleet-wide KV reuse (mid-stream decode rebalancing)
    "rebalance",
    # control plane (edge-triggered controller decisions)
    "canary_promote",
    "canary_rollback",
    "canary_start",
    "controller_rebalance",
    "controller_scale_down",
    "controller_scale_up",
    "publish",
    "publish_failed",
    "swap_exec",
    "weight_swap",
    # cost accounting (ledger folds + noisy-neighbor edges)
    "cost_flush",
    "noisy_neighbor",
    # SLO
    "slo_breach",
    # continuous telemetry (detector edges + health transitions)
    "detector_cleared",
    "detector_fired",
    "health_changed",
    # concurrency sanitizer
    "lock_contended",
})

__all__ = ["EVENT_KINDS", "METRIC_NAMES"]
