"""Recompile + memory tracking, and the per-step instrumentation wrapper.

``RecompileGuard`` generalizes the zero-recompile assertion the serving
tests pinned in PR 1 (``engine.compile_counts() == {'prefill': 1,
'decode': 1}``) into a reusable watcher over any jitted function's
executable count (``fn._cache_size()``): growth past the first compile is
a *recompile* — counted, event-logged, and optionally warned/raised on.
Shape-driven retraces are the classic silent TPU performance cliff; this
makes them a number.

``MonitoredFunction`` (via :func:`instrument`) wraps a step-shaped
callable with the whole telemetry spine: step start/end events, a step
counter + step-time histogram in the registry, recompile detection, a
profiler annotation, and periodic device-memory gauges. Attribute access
delegates to the wrapped function, so ``.lower()`` / ``._cache_size()``
callers (bench AOT path, ``collective_stats``) see no difference.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.annotations import annotate
from chainermn_tpu.monitor.events import EventLog
from chainermn_tpu.monitor.registry import MetricsRegistry


def _cache_size(fn) -> Optional[int]:
    """Executable count of a jitted function, or None when the wrapped
    object has no jit cache (AOT-compiled executables, plain callables)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def record_memory_gauges(registry: MetricsRegistry) -> None:
    """Per-device HBM gauges (``device_bytes_in_use`` / ``_peak``) from
    ``memory_stats()``. Backends exposing none (CPU) record nothing;
    never raises (called from hot loops and reporting paths)."""
    try:
        import jax

        for i, d in enumerate(jax.devices()):
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            labels = {"device": str(i)}
            if "bytes_in_use" in stats:
                registry.gauge("device_bytes_in_use", labels).set(
                    stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                registry.gauge("device_peak_bytes_in_use", labels).set(
                    stats["peak_bytes_in_use"])
    except Exception:
        pass


class RecompileGuard:
    """Watch jitted functions for executable-cache growth.

    ``watch(name, fn)`` registers a function (baseline = its current
    ``_cache_size()``); ``check()`` re-reads every watched count and
    returns ``{name: new_executables}`` for those that grew *past their
    first compile*. Growth 0 -> 1 is the expected warmup compile (a
    ``compile`` event, not a recompile); any later growth increments
    ``recompiles_total{fn=name}`` and emits a ``recompile`` event — and,
    per ``on_recompile``, stays silent (``'count'``), prints to stderr
    (``'warn'``), or raises (``'raise'`` — the reusable form of the
    serving zero-recompile assertion).
    """

    def __init__(self, *, registry: Optional[MetricsRegistry] = None,
                 events: Optional[EventLog] = None,
                 on_recompile: str = "count") -> None:
        if on_recompile not in ("count", "warn", "raise"):
            raise ValueError(
                f"on_recompile must be count|warn|raise, got {on_recompile!r}")
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        self._mode = on_recompile
        self._watched: dict[str, tuple] = {}   # name -> (fn, last_count)
        self._recompiles: dict[str, int] = {}

    def watch(self, name: str, fn) -> None:
        self._watched[name] = (fn, _cache_size(fn) or 0)

    def check(self) -> dict[str, int]:
        grown: dict[str, int] = {}
        for name, (fn, last) in list(self._watched.items()):
            cur = _cache_size(fn)
            if cur is None or cur <= last:
                continue
            self._watched[name] = (fn, cur)
            if last == 0 and cur == 1:
                self._events.emit("compile", fn=name, executables=cur)
                continue
            delta = cur - max(last, 1)
            if delta <= 0:            # 0 -> n>1 in one step: n-1 recompiles
                continue
            grown[name] = delta
            self._recompiles[name] = self._recompiles.get(name, 0) + delta
            self._registry.counter(
                "recompiles_total", {"fn": name}).inc(delta)
            self._events.emit("recompile", fn=name, executables=cur)
            # a step that recompiled is always worth its trace: flag the
            # ambient trace (if any) for forced retention
            from chainermn_tpu.monitor.trace import get_tracer

            get_tracer().mark_current_error(f"recompile:{name}")
            msg = (f"chainermn_tpu.monitor.RecompileGuard: {name!r} "
                   f"recompiled ({cur} executables) — a shape/dtype/static-"
                   "arg changed on a hot path")
            if self._mode == "warn":
                print(msg, file=sys.stderr, flush=True)
            elif self._mode == "raise":
                raise RuntimeError(msg)
        return grown

    @property
    def recompiles(self) -> dict[str, int]:
        """Total recompiles observed per watched name (beyond warmup)."""
        return dict(self._recompiles)

    def counts(self) -> dict[str, int]:
        """Current executable count per watched function."""
        return {
            name: _cache_size(fn) or 0
            for name, (fn, _) in self._watched.items()
        }

    def assert_no_recompiles(self) -> None:
        self.check()
        if self._recompiles:
            raise AssertionError(
                f"recompiles detected: {self._recompiles} (expected every "
                "watched function to keep its warmup executable)")


class MonitoredFunction:
    """Telemetry wrapper around a step-shaped callable (built by
    :func:`instrument`). Call-transparent: same signature, same result,
    and unknown attributes (``lower``, ``_cache_size``) delegate to the
    wrapped function so AOT/introspection callers keep working."""

    def __init__(self, fn: Callable, name: str, *,
                 registry: Optional[MetricsRegistry] = None,
                 events: Optional[EventLog] = None,
                 memory_interval: int = 64) -> None:
        self._fn = fn
        self._name = name
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        self._memory_interval = int(memory_interval)
        labels = {"step": name}
        self._c_steps = self._registry.counter("steps_total", labels)
        self._h_time = self._registry.histogram(
            "step_time_seconds", labels, unit="s")
        self._guard = RecompileGuard(
            registry=self._registry, events=self._events)
        self._guard.watch(name, fn)
        self._n = 0

    @property
    def inner(self) -> Callable:
        return self._fn

    def __call__(self, *args, **kwargs):
        self._n += 1
        n = self._n
        ev = self._events
        ev.emit("step_start", step=self._name, n=n)
        t0 = time.perf_counter()
        with annotate(f"chainermn.step.{self._name}"):
            out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        self._c_steps.inc()
        self._h_time.observe(dt)
        ev.emit("step_end", step=self._name, n=n, dur_s=round(dt, 6))
        self._guard.check()
        if self._memory_interval and n % self._memory_interval == 0:
            record_memory_gauges(self._registry)
        return out

    def __getattr__(self, name: str):
        return getattr(self._fn, name)

    def __repr__(self) -> str:
        return f"<MonitoredFunction {self._name!r} of {self._fn!r}>"


def instrument(fn: Callable, name: str, **kwargs) -> MonitoredFunction:
    """Wrap ``fn`` with step events + metrics + recompile/memory tracking.
    Idempotent-ish: instrumenting a MonitoredFunction wraps the original
    function under a new name instead of stacking wrappers."""
    if isinstance(fn, MonitoredFunction):
        fn = fn.inner
    return MonitoredFunction(fn, name, **kwargs)


__all__ = [
    "MonitoredFunction",
    "RecompileGuard",
    "instrument",
    "record_memory_gauges",
]
