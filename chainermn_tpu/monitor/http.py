"""Stdlib-only HTTP scrape surface for the monitor spine.

One background :class:`ThreadingHTTPServer` exposes the process's
telemetry to anything that can speak HTTP — a Prometheus scraper, a
browser, ``curl``, a future fleet router polling replica burn rates:

- ``/metrics`` — Prometheus text exposition of the registry;
- ``/traces``  — the tracer ring as Chrome trace-event JSON (save the
  body to a file and load it in Perfetto / ``chrome://tracing``);
  ``?kind=serving`` filters by trace kind;
- ``/slo``     — a fresh :meth:`~chainermn_tpu.monitor.slo.SLOEngine.
  evaluate` pass as JSON (scraping IS the periodic evaluation driver);
- ``/events``  — the flight-recorder tail as JSON (``?last=N``, default
  64);
- ``/timeseries`` — the continuous-telemetry ring buffers
  (:class:`~chainermn_tpu.monitor.timeseries.TimeSeriesStore`) as JSON
  when a store/collector was passed to :func:`serve`; ``?last=N``
  bounds points per series (default 128), ``?prefix=`` filters series
  by name;
- ``/health``  — per-replica :class:`~chainermn_tpu.monitor.health.
  HealthMonitor` scores (``healthy``/``degraded``/``critical`` with
  contributing signals) when a monitor was passed to :func:`serve`;
- ``/fleet``   — the serving fleet's :meth:`~chainermn_tpu.fleet.router.
  FleetRouter.fleet_report` as JSON (replica states, reroute/shed
  counters, affinity hit rate, fleet-pooled latency percentiles) when a
  router was passed to :func:`serve`; ``{}`` otherwise;
- ``/control`` — the fleet control plane's :meth:`~chainermn_tpu.fleet.
  control.FleetController.report` (autoscaler state, canary phase,
  version history, decision ring) when a controller was passed to
  :func:`serve`;
- ``/costs``   — the cost ledger's :meth:`~chainermn_tpu.monitor.costs.
  CostLedger.report` (per-tenant device/block/queue seconds, goodput
  breakdown, conservation check) when a ledger was passed to
  :func:`serve`;
- ``/``        — a plain-text index of the above.

Serving is read-only and allocation-light: every handler renders from
the live in-memory structures at request time (no background snapshot
thread). ``port=0`` binds an ephemeral port (tests); the bound port is
on :attr:`MonitorServer.port`. Handlers run on the server's worker
threads — the registry/event-log/tracer are all lock-protected, so a
scrape never blocks the serving or training hot path for more than a
dict copy.

This module must not import ``chainermn_tpu.extensions`` (or jax) at
module level — pinned by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from chainermn_tpu.monitor._state import get_event_log, get_registry


class MonitorServer:
    """Owns the background HTTP server; build via :func:`serve`."""

    def __init__(self, host: str, port: int, *, registry, events, tracer,
                 slo, fleet=None, timeseries=None, health=None,
                 controller=None, costs=None) -> None:
        self._registry = registry
        self._events = events
        self._tracer = tracer
        self._slo = slo
        self._fleet = fleet
        self._controller = controller
        self._costs = costs
        # a Collector is accepted where a TimeSeriesStore is expected —
        # the scrape serves the collector's store either way
        self._timeseries = getattr(timeseries, "store", timeseries)
        self._health = health
        owner = self

        class _Handler(BaseHTTPRequestHandler):
            # quiet: scrape traffic must not spam stderr
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    status, ctype, body = owner._render(self.path)
                except Exception as e:  # noqa: BLE001 — scrape must answer
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = f"{type(e).__name__}: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"chainermn-monitor-http-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- rendering --------------------------------------------------------- #

    def _render(self, path: str) -> tuple[int, str, bytes]:
        parsed = urlparse(path)
        q = parse_qs(parsed.query)
        route = parsed.path.rstrip("/") or "/"
        if route == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    self._registry.exposition().encode())
        if route == "/traces":
            kind = q.get("kind", [None])[0]
            traces = self._tracer.finished(kind=kind)
            body = json.dumps(self._tracer.export_chrome(traces=traces),
                              default=str).encode()
            return 200, "application/json", body
        if route == "/slo":
            payload = self._slo.evaluate() if self._slo is not None else {}
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/events":
            last = int(q.get("last", ["64"])[0])
            body = json.dumps({"events": self._events.tail(last)},
                              default=str).encode()
            return 200, "application/json", body
        if route == "/fleet":
            payload = (self._fleet.fleet_report()
                       if self._fleet is not None else {})
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/timeseries":
            last = int(q.get("last", ["128"])[0])
            prefix = q.get("prefix", [None])[0]
            payload = (self._timeseries.to_json(last=last, prefix=prefix)
                       if self._timeseries is not None else {})
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/health":
            payload = (self._health.report()
                       if self._health is not None else {})
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/control":
            payload = (self._controller.report()
                       if self._controller is not None else {})
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/costs":
            payload = (self._costs.report()
                       if self._costs is not None else {})
            return (200, "application/json",
                    json.dumps(payload, default=str).encode())
        if route == "/":
            index = ("chainermn_tpu monitor\n"
                     "  /metrics     Prometheus text exposition\n"
                     "  /traces      Chrome trace-event JSON (?kind=)\n"
                     "  /slo         SLO burn-rate evaluation\n"
                     "  /events      flight-recorder tail (?last=N)\n"
                     "  /fleet       serving-fleet report (replica "
                     "states, pooled percentiles)\n"
                     "  /timeseries  telemetry ring buffers "
                     "(?last=N&prefix=)\n"
                     "  /health      per-replica health scores\n"
                     "  /control     fleet control-plane report "
                     "(autoscaler, canary, rebalance)\n"
                     "  /costs       per-tenant cost ledger "
                     "(device seconds, goodput, conservation)\n")
            return 200, "text/plain; charset=utf-8", index.encode()
        return 404, "text/plain; charset=utf-8", b"not found\n"

    # -- lifecycle --------------------------------------------------------- #

    def close(self) -> None:
        """Stop serving and join the server thread; idempotent."""
        srv, self._server = self._server, None
        if srv is None:
            return
        srv.shutdown()
        srv.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MonitorServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve(port: int = 0, host: str = "127.0.0.1", *, registry=None,
          events=None, tracer=None, slo=None, fleet=None,
          timeseries=None, health=None, controller=None,
          costs=None) -> MonitorServer:
    """Stand up the scrape endpoint on a background thread and return the
    running :class:`MonitorServer` (``.port`` carries the bound port when
    ``port=0``). Defaults wire the process-wide registry, flight
    recorder, tracer, and SLO engine; pass private instances for
    isolation (tests), and a :class:`~chainermn_tpu.fleet.router.
    FleetRouter` as ``fleet=`` to light up ``/fleet`` (there is no
    process-wide default router — fleets are explicitly owned). Likewise
    ``timeseries=`` (a :class:`~chainermn_tpu.monitor.timeseries.
    TimeSeriesStore` or :class:`~chainermn_tpu.monitor.timeseries.
    Collector`) lights up ``/timeseries`` and ``health=`` (a
    :class:`~chainermn_tpu.monitor.health.HealthMonitor`) lights up
    ``/health`` — continuous telemetry is explicitly owned too, as is
    ``controller=`` (a :class:`~chainermn_tpu.fleet.control.
    FleetController`) for ``/control`` and ``costs=`` (a
    :class:`~chainermn_tpu.monitor.costs.CostLedger`) for ``/costs``.
    Close with :meth:`MonitorServer.close` (also a context manager)."""
    if registry is None:
        registry = get_registry()
    if events is None:
        events = get_event_log()
    if tracer is None:
        from chainermn_tpu.monitor.trace import get_tracer

        tracer = get_tracer()
    if slo is None:
        from chainermn_tpu.monitor.slo import get_slo_engine

        slo = get_slo_engine()
    return MonitorServer(host, port, registry=registry, events=events,
                         tracer=tracer, slo=slo, fleet=fleet,
                         timeseries=timeseries, health=health,
                         controller=controller, costs=costs)


__all__ = ["MonitorServer", "serve"]
