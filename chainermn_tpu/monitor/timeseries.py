"""Continuous telemetry: a time-series store + background collector over
the metrics registry, with derived signals and anomaly detectors.

The registry (PR 6) answers "what is the value NOW"; the SLO engine
answers "is the latency budget burning". Neither retains *history*, so
nothing in the process can see a drift, a stall, or a slow leak — the
sensors exist but the signal processing doesn't (ROADMAP item 3's gap).
This module adds the missing layer in three tiers:

- :class:`TimeSeriesStore` — named bounded ring-buffer series of
  ``(t, value)`` points behind one leaf lock (readers copy out, nothing
  is ever acquired while it is held, so scrapes never stack on the
  collector).
- :class:`Collector` — samples EVERY registry instrument at a fixed
  cadence into the store: counters become both a cumulative series and a
  ``:rate`` series (delta over the tick interval), gauges sample
  directly, histograms contribute windowed ``:p50``/``:p99`` over the
  samples observed since the previous tick. The clock is injectable
  (``time.monotonic`` scale, like :meth:`SLOEngine.evaluate`), so tests
  drive :meth:`Collector.tick` deterministically at zero wall-clock
  cost; production uses :meth:`Collector.start`'s daemon thread.
  Collector accounting is itself cataloged (``ts_samples_total``,
  ``ts_collect_lag_seconds``).
- **Derived signals** (:class:`Rate`, :class:`EWMA`, :class:`Ratio`,
  :class:`WindowPercentile`) — a declarative post-sample graph evaluated
  in declaration order each tick, writing new series back into the store
  (e.g. speculative accept rate = accepted-rate / proposed-rate).
- **Detectors** (:class:`ThresholdDetector`, :class:`ZScoreDetector`,
  :class:`DeadmanDetector`) — pluggable verdicts over store series,
  each EDGE-TRIGGERED: a ``detector_fired`` / ``detector_cleared``
  event only on transition (the SLO engine's breach convention) plus a
  live ``detector_state{detector=}`` gauge (0 clear, 1 degraded,
  2 critical). :mod:`chainermn_tpu.monitor.health` composes detector
  states into per-replica health verdicts.

Threading: one collector thread is the only :meth:`Collector.tick`
driver (start/stop reaps it); detectors and signals keep private state
and are evaluated only from that tick, so the only shared structure is
the store — guarded by its own ``sanitizer.make_lock`` leaf lock.

This module must not import ``chainermn_tpu.extensions`` (or jax) at
module level — pinned by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.registry import Counter, Gauge, Histogram

_SEVERITY_CODE = {"degraded": 1, "critical": 2}


class Series:
    """One named ring of ``(t, value)`` points (plain container; all
    access goes through the owning :class:`TimeSeriesStore`'s lock)."""

    __slots__ = ("name", "kind", "_points")

    def __init__(self, name: str, kind: str = "gauge",
                 maxlen: int = 512) -> None:
        self.name = name
        self.kind = kind
        self._points: deque = deque(maxlen=maxlen)


class TimeSeriesStore:
    """Named bounded series, get-or-create, behind one leaf lock.

    ``maxlen`` bounds every ring: at the default 512 points and a 0.25 s
    cadence that is ~2 minutes of history per series — enough for the
    detectors' baselines and the ``/timeseries`` scrape, bounded no
    matter how long the process serves.
    """

    def __init__(self, maxlen: int = 512) -> None:
        if maxlen < 2:
            raise ValueError(f"maxlen must be >= 2, got {maxlen}")
        self.maxlen = int(maxlen)
        # leaf: appended to from the collector tick, read from scrape
        # threads and detector evaluation — nothing may be acquired
        # while it is held (readers copy out)
        self._lock = sanitizer.make_lock("TimeSeriesStore._lock", leaf=True)
        self._series: dict[str, Series] = sanitizer.guarded(
            {}, lock=self._lock, name="TimeSeriesStore._series")

    def append(self, name: str, t: float, v: float,
               kind: str = "gauge") -> None:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = Series(name, kind, self.maxlen)
                self._series[name] = s
            s._points.append((float(t), float(v)))

    def points(self, name: str) -> list:
        """``[(t, v), ...]`` oldest-first; ``[]`` for an unknown series."""
        with self._lock:
            s = self._series.get(name)
            return list(s._points) if s is not None else []

    def last(self, name: str) -> Optional[tuple]:
        with self._lock:
            s = self._series.get(name)
            return s._points[-1] if s is not None and s._points else None

    def values(self, name: str) -> list:
        return [v for _t, v in self.points(name)]

    def names(self) -> list:
        with self._lock:
            return sorted(self._series)

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def to_json(self, last: Optional[int] = None,
                prefix: Optional[str] = None) -> dict:
        """JSON-able dump (the ``/timeseries`` payload): ``{"series":
        {name: {"kind": k, "points": [[t, v], ...]}}}``, newest ``last``
        points per series, optionally filtered by name prefix."""
        with self._lock:
            items = [(n, s.kind, list(s._points))
                     for n, s in sorted(self._series.items())
                     if prefix is None or n.startswith(prefix)]
        out = {}
        for name, kind, pts in items:
            if last is not None:
                pts = pts[-int(last):]
            out[name] = {"kind": kind,
                         "points": [[round(t, 6), v] for t, v in pts]}
        return {"n_series": len(out), "series": out}


# ---------------------------------------------------------------------- #
# derived signals                                                         #
# ---------------------------------------------------------------------- #


class Rate:
    """d(source)/dt between the source's previous and newest point —
    turns any cumulative series into a per-second rate."""

    def __init__(self, source: str, name: Optional[str] = None) -> None:
        self.source = source
        self.name = name if name is not None else f"{source}:rate"
        self._prev: Optional[tuple] = None

    def evaluate(self, store: TimeSeriesStore, now: float) -> None:
        latest = store.last(self.source)
        if latest is None:
            return
        prev, self._prev = self._prev, latest
        if prev is None or latest[0] <= prev[0]:
            return
        store.append(self.name, latest[0],
                     (latest[1] - prev[1]) / (latest[0] - prev[0]),
                     kind="derived")


class EWMA:
    """Exponentially-weighted moving average of the source's newest
    value (updated only when the source advances)."""

    def __init__(self, source: str, alpha: float = 0.2,
                 name: Optional[str] = None) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.source = source
        self.alpha = float(alpha)
        self.name = name if name is not None else f"{source}:ewma"
        self._last_t: Optional[float] = None
        self._ewma: Optional[float] = None

    def evaluate(self, store: TimeSeriesStore, now: float) -> None:
        latest = store.last(self.source)
        if latest is None or latest[0] == self._last_t:
            return
        self._last_t = latest[0]
        self._ewma = (latest[1] if self._ewma is None
                      else (1 - self.alpha) * self._ewma
                      + self.alpha * latest[1])
        store.append(self.name, latest[0], self._ewma, kind="derived")


class Ratio:
    """num / den of two series' newest values (0-denominator ticks are
    skipped, not emitted as inf)."""

    def __init__(self, num: str, den: str, name: str) -> None:
        self.num = num
        self.den = den
        self.name = name

    def evaluate(self, store: TimeSeriesStore, now: float) -> None:
        n, d = store.last(self.num), store.last(self.den)
        if n is None or d is None or d[1] == 0.0:
            return
        store.append(self.name, max(n[0], d[0]), n[1] / d[1],
                     kind="derived")


class WindowPercentile:
    """q-th percentile of the source's points inside the trailing
    window — a percentile over *series history* (vs the collector's
    built-in ``:p50``/``:p99``, which are over one tick's histogram
    samples)."""

    def __init__(self, source: str, q: float = 99.0,
                 window_s: float = 10.0,
                 name: Optional[str] = None) -> None:
        self.source = source
        self.q = float(q)
        self.window_s = float(window_s)
        self.name = (name if name is not None
                     else f"{source}:w{q:g}")

    def evaluate(self, store: TimeSeriesStore, now: float) -> None:
        cutoff = now - self.window_s
        vals = [v for t, v in store.points(self.source) if t >= cutoff]
        if not vals:
            return
        store.append(self.name, now,
                     float(np.percentile(np.asarray(vals, np.float64),
                                         self.q)),
                     kind="derived")


# ---------------------------------------------------------------------- #
# detectors                                                               #
# ---------------------------------------------------------------------- #


class Detector:
    """Base detector: subclasses implement the pure :meth:`check`;
    :meth:`evaluate` wraps it with the shared edge-trigger machinery
    (``detector_state`` gauge, ``detector_fired`` / ``detector_cleared``
    events on transition only)."""

    def __init__(self, name: str, series: str,
                 severity: str = "degraded") -> None:
        if severity not in _SEVERITY_CODE:
            raise ValueError(
                f"severity must be degraded|critical, got {severity!r}")
        self.name = name
        self.series = series
        self.severity = severity
        self.firing = False
        self.last: dict = {}

    def check(self, store: TimeSeriesStore, now: float) -> dict:
        raise NotImplementedError

    def evaluate(self, store: TimeSeriesStore, now: float, *,
                 registry=None, events=None) -> dict:
        verdict = self.check(store, now)
        verdict["severity"] = self.severity
        firing, was = bool(verdict.get("firing")), self.firing
        self.firing = firing
        self.last = verdict
        if registry is not None:
            registry.gauge("detector_state", {"detector": self.name}).set(
                float(_SEVERITY_CODE[self.severity]) if firing else 0.0)
        if events is not None and firing != was:
            fields = {k: v for k, v in verdict.items()
                      if isinstance(v, (int, float, str, bool))}
            if firing:
                events.emit("detector_fired", detector=self.name,
                            series=self.series, **fields)
            else:
                events.emit("detector_cleared", detector=self.name,
                            series=self.series, **fields)
        return verdict


class ThresholdDetector(Detector):
    """Newest value beyond a fixed bound (queue depth too high, free KV
    blocks too low)."""

    def __init__(self, name: str, series: str, threshold: float, *,
                 direction: str = "above",
                 severity: str = "degraded") -> None:
        super().__init__(name, series, severity)
        if direction not in ("above", "below"):
            raise ValueError(
                f"direction must be above|below, got {direction!r}")
        self.threshold = float(threshold)
        self.direction = direction

    def check(self, store: TimeSeriesStore, now: float) -> dict:
        latest = store.last(self.series)
        if latest is None:
            return {"firing": False, "value": None,
                    "threshold": self.threshold}
        v = latest[1]
        firing = (v > self.threshold if self.direction == "above"
                  else v < self.threshold)
        return {"firing": firing, "value": v, "threshold": self.threshold,
                "direction": self.direction}


class ZScoreDetector(Detector):
    """Newest value drifted ``z`` standard deviations from the rolling
    baseline (the preceding ``baseline`` points) — the TTFT-p99 /
    accept-rate drift alarm. ``min_points`` baseline points are required
    before it may fire; a near-constant baseline (std below ``eps``)
    never fires, so a flat warm series doesn't alarm on the first
    wobble."""

    def __init__(self, name: str, series: str, *, z: float = 3.0,
                 direction: str = "above", baseline: int = 64,
                 min_points: int = 8, eps: float = 1e-9,
                 severity: str = "degraded") -> None:
        super().__init__(name, series, severity)
        if direction not in ("above", "below", "both"):
            raise ValueError(
                f"direction must be above|below|both, got {direction!r}")
        self.z = float(z)
        self.direction = direction
        self.baseline = int(baseline)
        self.min_points = int(min_points)
        self.eps = float(eps)

    def check(self, store: TimeSeriesStore, now: float) -> dict:
        vals = store.values(self.series)[-(self.baseline + 1):]
        if len(vals) < self.min_points + 1:
            return {"firing": False, "points": len(vals)}
        base = np.asarray(vals[:-1], np.float64)
        mean, std = float(base.mean()), float(base.std())
        if std < self.eps:
            return {"firing": False, "value": vals[-1], "mean": mean,
                    "zscore": 0.0}
        zscore = (vals[-1] - mean) / std
        firing = {"above": zscore > self.z,
                  "below": zscore < -self.z,
                  "both": abs(zscore) > self.z}[self.direction]
        return {"firing": firing, "value": vals[-1],
                "mean": round(mean, 6), "zscore": round(zscore, 4),
                "z": self.z, "direction": self.direction}


class DeadmanDetector(Detector):
    """No progress on a cumulative series for longer than ``timeout_s``
    while the subject is supposed to be working — the per-replica
    decode-stall alarm (series: the replica's ``serving_tokens_total``
    cumulative samples; ``active_fn``: "does it have work right now").
    While ``active_fn`` reports idle, the stall clock rearms — an empty
    queue is not a stall."""

    def __init__(self, name: str, series: str, timeout_s: float, *,
                 active_fn: Optional[Callable[[], bool]] = None,
                 severity: str = "critical") -> None:
        super().__init__(name, series, severity)
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.active_fn = active_fn
        self._last_value: Optional[float] = None
        self._last_advance_t: Optional[float] = None

    def check(self, store: TimeSeriesStore, now: float) -> dict:
        latest = store.last(self.series)
        value = latest[1] if latest is not None else None
        if (value is not None
                and (self._last_value is None or value > self._last_value)):
            self._last_value = value
            self._last_advance_t = now
        active = bool(self.active_fn()) if self.active_fn is not None \
            else True
        if not active or self._last_advance_t is None:
            # idle (or never observed): rearm — only a *working* subject
            # that stops making progress is dead
            self._last_advance_t = now
            return {"firing": False, "value": value, "active": active,
                    "stalled_s": 0.0}
        stalled = now - self._last_advance_t
        return {"firing": stalled > self.timeout_s, "value": value,
                "active": active, "stalled_s": round(stalled, 3),
                "timeout_s": self.timeout_s}


# ---------------------------------------------------------------------- #
# the collector                                                           #
# ---------------------------------------------------------------------- #


class Collector:
    """Fixed-cadence sampler: registry -> store -> signals -> detectors
    (-> health, when a :class:`~chainermn_tpu.monitor.health.
    HealthMonitor` is attached).

    One :meth:`tick` is the whole pipeline, deterministic under an
    injected ``now`` — tests never sleep. :meth:`start` runs ticks on a
    daemon thread every ``cadence_s`` (reaped by :meth:`stop`); the
    thread observes its own scheduling lag into
    ``ts_collect_lag_seconds`` so collector overload is itself a
    detectable series. Tick state (counter deltas, detector latches) is
    single-writer by contract: the background thread — or the test
    driving ``tick(now=...)`` explicitly — is the only caller.
    """

    def __init__(self, *, registry=None, store: Optional[TimeSeriesStore]
                 = None, cadence_s: float = 0.25, clock=None,
                 signals=(), detectors=(), events=None,
                 maxlen: int = 512) -> None:
        if cadence_s <= 0:
            raise ValueError(f"cadence_s must be > 0, got {cadence_s}")
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        self.store = store if store is not None else TimeSeriesStore(
            maxlen=maxlen)
        self.cadence_s = float(cadence_s)
        self._clock = clock if clock is not None else time.monotonic
        self._signals = list(signals)
        self._detectors = list(detectors)
        self._health = None
        self._prev_counters: dict[str, tuple] = {}
        self._last_tick: Optional[float] = None
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._c_samples = self._registry.counter("ts_samples_total")
        self._h_lag = self._registry.histogram("ts_collect_lag_seconds",
                                               unit="s")

    def add_signal(self, signal) -> "Collector":
        self._signals.append(signal)
        return self

    def add_detector(self, detector: Detector) -> "Collector":
        self._detectors.append(detector)
        return self

    def attach_health(self, monitor) -> "Collector":
        """Evaluate ``monitor`` (a :class:`~chainermn_tpu.monitor.health.
        HealthMonitor`) at the end of every tick, over this collector's
        store and clock."""
        self._health = monitor
        return self

    @property
    def detectors(self) -> list:
        return list(self._detectors)

    @property
    def health(self):
        """The attached :class:`~chainermn_tpu.monitor.health.
        HealthMonitor` (``None`` until :meth:`attach_health`) — what
        callers hand to ``monitor.http.serve(health=...)``."""
        return self._health

    # -- one pass ---------------------------------------------------------- #

    def tick(self, now: Optional[float] = None) -> dict:
        """Sample every instrument, run signals then detectors (then
        health), all at one injectable timestamp; returns a summary
        (``samples`` appended, per-detector verdicts, health scores)."""
        now = self._clock() if now is None else float(now)
        window = (self.cadence_s if self._last_tick is None
                  else max(now - self._last_tick, 1e-9))
        with self._registry._lock:
            insts = list(self._registry._instruments.values())
        n = 0
        for inst in insts:
            key = inst.key
            if isinstance(inst, Counter):
                # float, not int: fractional counters (the cost ledger's
                # device-seconds) must not lose their sub-unit deltas —
                # integer counters sample identically either way
                v = float(inst.value)
                prev = self._prev_counters.get(key)
                self._prev_counters[key] = (now, v)
                self.store.append(key, now, v, kind="counter")
                n += 1
                if prev is not None and now > prev[0]:
                    self.store.append(key + ":rate", now,
                                      (v - prev[1]) / (now - prev[0]),
                                      kind="rate")
                    n += 1
            elif isinstance(inst, Gauge):
                self.store.append(key, now, float(inst.value), kind="gauge")
                n += 1
            elif isinstance(inst, Histogram):
                samples = inst.recent(window, now=now)
                if samples:
                    t = np.asarray(samples, np.float64)
                    self.store.append(key + ":p50", now,
                                      float(np.percentile(t, 50)),
                                      kind="percentile")
                    self.store.append(key + ":p99", now,
                                      float(np.percentile(t, 99)),
                                      kind="percentile")
                    n += 2
        for sig in self._signals:
            sig.evaluate(self.store, now)
        verdicts = {}
        for det in self._detectors:
            verdicts[det.name] = det.evaluate(
                self.store, now, registry=self._registry,
                events=self._events)
        health = None
        if self._health is not None:
            health = self._health.evaluate(now)
        self._last_tick = now
        self.ticks += 1
        self._c_samples.inc(n)
        return {"now": now, "samples": n, "detectors": verdicts,
                "health": health}

    # -- background thread ------------------------------------------------- #

    def start(self) -> "Collector":
        """Run :meth:`tick` every ``cadence_s`` on a daemon thread
        (idempotent while running)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="chainermn-ts-collector", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop and join the collector thread; idempotent."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def __enter__(self) -> "Collector":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        target = self._clock()
        while not self._stop.is_set():
            t0 = self._clock()
            self._h_lag.observe(max(0.0, t0 - target))
            try:
                self.tick(t0)
            except Exception as e:  # noqa: BLE001 — the observer must not die
                print(f"chainermn_tpu.monitor: collector tick failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
            target = t0 + self.cadence_s
            self._stop.wait(self.cadence_s)


__all__ = [
    "Collector",
    "DeadmanDetector",
    "Detector",
    "EWMA",
    "Rate",
    "Ratio",
    "Series",
    "ThresholdDetector",
    "TimeSeriesStore",
    "WindowPercentile",
    "ZScoreDetector",
]
