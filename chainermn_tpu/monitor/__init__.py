"""``chainermn_tpu.monitor`` — the unified telemetry subsystem.

The reference ChainerMN ships no observability of its own (SURVEY.md S5:
users bolt on Chainer hooks + nvprof; a lost collective hangs silently).
PR 1 left good but disconnected primitives (``extensions.profiling``,
``serving.metrics``); this package is the spine that connects them, in
four pillars:

- **Metrics** (:class:`MetricsRegistry`): process-wide counters / gauges /
  histograms with labels, JSON :func:`snapshot`, Prometheus-style
  :func:`exposition`, and cross-rank :func:`aggregate` (fleet-wide p50/p99
  on rank 0 over the communicator's object transport, merged with the
  ``latency_report`` field convention so records stay ``BENCH_*.json``-
  compatible).
- **Events** (:class:`EventLog`): a bounded ring of structured events
  (step start/end, prefill/decode, slot admit/retire, compile, watchdog
  arm/fire) dumped automatically — last N events + per-device
  ``memory_stats()`` — when ``Watchdog`` fires or ``global_except_hook``
  trips.
- **Profiler annotations** (:func:`annotate`): ``TraceAnnotation`` +
  ``named_scope`` in one context manager (no-op fallback on legacy JAX),
  permanently on inside train steps, serving prefill/decode, the
  scheduler's admit loop, and every ``MeshCommunicator`` collective.
- **Recompile + memory tracking** (:class:`RecompileGuard`,
  :func:`record_memory_gauges`): executable-cache growth as a counted,
  event-logged signal (the serving zero-recompile assertion, generalized),
  plus periodic device-memory gauges.
- **Request-scoped tracing** (:class:`Tracer` / :func:`get_tracer`):
  Dapper-style span trees with context propagation — a serving request's
  queue -> admit -> prefill -> decode -> retire, a training step's
  prefetch-wait -> dispatch -> loss fetch — head-sampled with forced
  retention on error/deadline miss, exported as Chrome trace-event JSON
  (Perfetto-loadable).
- **SLO engine** (:class:`SLOEngine`): declarative latency / error-rate
  objectives evaluated from registry histograms and counters with
  multi-window burn rates; breaches emit flight-recorder events naming
  the offending trace ids, and ``slo_burn_rate`` gauges pool fleet-wide
  through :func:`aggregate`.
- **Continuous telemetry** (:class:`TimeSeriesStore` / :class:`Collector`
  / :class:`HealthMonitor`): a fixed-cadence collector samples every
  registry instrument into bounded ring-buffer series (counters as
  rates, histograms as windowed p50/p99), a declarative derived-signal
  graph (:class:`Rate` / :class:`EWMA` / :class:`Ratio` /
  :class:`WindowPercentile`) feeds edge-triggered detectors (z-score
  drift, thresholds, decode-stall deadman), and detector states compose
  into per-replica ``healthy``/``degraded``/``critical`` scores the
  fleet router consults as a routing penalty (:func:`fleet_health`).
- **Scrape endpoint** (:func:`chainermn_tpu.monitor.http.serve`):
  stdlib-only background HTTP server exposing ``/metrics`` (Prometheus
  text), ``/traces`` (Chrome JSON), ``/slo``, ``/events``,
  ``/timeseries``, and ``/health``.

The per-step hot-path cost is a few dict/deque operations (<2% step time
even on millisecond CPU steps — asserted by ``bench.py --mode monitor``);
everything heavier happens at reporting or failure time.

Usage::

    from chainermn_tpu import monitor

    step = monitor.instrument(step, "train")      # events+metrics+recompiles
    with monitor.annotate("chainermn.eval"):      # profiler region
        ...
    monitor.emit("checkpoint", path=p)            # structured event
    print(monitor.exposition())                   # Prometheus text
    record["monitor"] = monitor.snapshot()        # JSON block
    fleet = monitor.aggregate(comm)               # rank-0 fleet percentiles
"""

from __future__ import annotations

from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.annotations import annotate
from chainermn_tpu.monitor.costs import (
    CostLedger,
    NoisyNeighborDetector,
    merge_cost_payloads,
    standard_tenant_sensors,
)
from chainermn_tpu.monitor.events import EventLog, device_memory_lines
from chainermn_tpu.monitor.health import (
    HealthMonitor,
    HealthScore,
    fleet_health,
    standard_replica_sensors,
)
from chainermn_tpu.monitor.instrument import (
    MonitoredFunction,
    RecompileGuard,
    instrument,
    record_memory_gauges,
)
from chainermn_tpu.monitor.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_rank_payloads,
)
from chainermn_tpu.monitor.slo import (
    ErrorRateObjective,
    LatencyObjective,
    SLOEngine,
    get_slo_engine,
)
from chainermn_tpu.monitor.timeseries import (
    Collector,
    DeadmanDetector,
    Detector,
    EWMA,
    Rate,
    Ratio,
    ThresholdDetector,
    TimeSeriesStore,
    WindowPercentile,
    ZScoreDetector,
)
from chainermn_tpu.monitor.trace import Span, Trace, Tracer, get_tracer
from chainermn_tpu.monitor import http  # noqa: F401 — monitor.http.serve


def emit(kind: str, **fields) -> None:
    """Emit a structured event into the default flight recorder."""
    get_event_log().emit(kind, **fields)


def snapshot(memory: bool = True) -> dict:
    """JSON-able snapshot of the default registry (refreshing the
    device-memory gauges first unless ``memory=False``) — the block every
    ``bench.py`` mode embeds in its record."""
    if memory:
        record_memory_gauges(get_registry())
    return get_registry().snapshot()


def exposition() -> str:
    """Prometheus text exposition of the default registry."""
    return get_registry().exposition()


def aggregate(comm) -> dict:
    """Fleet-wide merge of the default registry across ranks (counters
    summed, gauges averaged, histogram percentiles over pooled samples)."""
    return get_registry().aggregate(comm)


__all__ = [
    "Collector",
    "CostLedger",
    "Counter",
    "DeadmanDetector",
    "Detector",
    "EWMA",
    "ErrorRateObjective",
    "EventLog",
    "Gauge",
    "HealthMonitor",
    "HealthScore",
    "Histogram",
    "LatencyObjective",
    "MetricsRegistry",
    "MonitoredFunction",
    "NoisyNeighborDetector",
    "Rate",
    "Ratio",
    "RecompileGuard",
    "SLOEngine",
    "Span",
    "ThresholdDetector",
    "TimeSeriesStore",
    "Trace",
    "Tracer",
    "WindowPercentile",
    "ZScoreDetector",
    "aggregate",
    "annotate",
    "device_memory_lines",
    "emit",
    "exposition",
    "fleet_health",
    "get_event_log",
    "get_registry",
    "get_slo_engine",
    "get_tracer",
    "http",
    "instrument",
    "merge_cost_payloads",
    "merge_rank_payloads",
    "record_memory_gauges",
    "snapshot",
    "standard_replica_sensors",
    "standard_tenant_sensors",
]
