"""Per-request resource attribution and tenant cost accounting.

Every shared mechanism in the serving path deliberately blurs per-request
cost: a bucketed prefill runs ``prefill_batch`` padded rows for the whole
group in one dispatch, a decode round advances every slot (idle rows ride
along masked), speculative verify burns device time on drafts that get
rejected, shared prefix blocks are held by several requests at once, and
a preemption throws away work that must be replayed. This module is the
ledger that un-blurs it — splitting each *measured* device interval into
per-tenant shares by explicit rules:

- **prefill** — one bucketed call of ``batch_rows`` rows × ``bucket``
  tokens splits evenly across rows; each member row splits by token
  share into ``useful`` (its real suffix) and ``padding`` (the pad tail);
  rows the group didn't fill are ``padding`` booked to the reserved
  unattributed tenant ``"-"``.
- **decode** — one dispatch splits evenly across the ``n_rows`` compiled
  rows; an active row is ``useful``, an inactive row is ``idle`` (booked
  to ``"-"``). A speculative row further splits its share by verify
  positions: ``committed/(committed+rejected)`` stays useful, the
  rejected remainder is ``wasted``.
- **replay** — after a preemption the request regenerates its discarded
  tokens (and re-runs its prefill) from scratch; that re-done work books
  as ``replay`` instead of ``useful``, metered by a per-request token
  debt so a second preemption never double-books (debt only grows by
  what was *discarded*, and each replayed token consumes it once).
- **migrate** — the host-bounce handover that moves a request's KV
  blocks from a prefill-tier replica to a decode-tier one is device+PCIe
  time spent on exactly one request; the whole measured interval books
  to its tenant as ``migrate`` (overhead, not goodput — the bench's
  crossover math weighs it against the decode stalls it deletes).
- **KV block-seconds** — the integral of blocks held over wall time; a
  shared prefix block held by ``r`` requests contributes ``1/r`` per
  holder (the live refcount split), so the pool's occupancy always sums
  across tenants.

The load-bearing invariant is **conservation**: every ``record_*`` call
splits the measured interval into shares that sum back to it, so
attributed device-seconds can never silently lose or invent cost. The
ledger tracks the worst per-dispatch relative error and publishes it as
the ``cost_conservation_error`` gauge (should sit at float-epsilon).

Aggregates fold into the process registry on :meth:`CostLedger.flush`
(per-tenant ``tenant_device_seconds_total{kind=}`` /
``tenant_kv_block_seconds_total`` counters, fleet-visible
``goodput_fraction{kind=}`` gauges), which makes them scrapeable,
collectible by the continuous-telemetry spine, and — via
:func:`standard_tenant_sensors` — watchable by a noisy-neighbor detector
that names the offending tenant in a ``noisy_neighbor`` event.

This module must not import ``chainermn_tpu.extensions`` (or jax, or the
serving stack) at module level — it is pure host-side accounting, pinned
by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import time
from typing import Iterable, Optional, Sequence

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.timeseries import (
    Detector,
    TimeSeriesStore,
    ZScoreDetector,
)

#: attribution kinds; together they partition every measured interval
KINDS = ("useful", "padding", "idle", "wasted", "replay", "migrate")

#: reserved tenant for shares no request owns (empty prefill rows, idle
#: decode slots) — kept out of per-tenant rankings but inside goodput
UNATTRIBUTED = "-"

_EPS = 1e-12


def tenant_device_key(instance: str, tenant: str, kind: str) -> str:
    """Registry series key of one tenant's device-seconds counter (label
    keys sorted, matching ``MetricsRegistry`` rendering) — what the
    collector samples and :func:`standard_tenant_sensors` watches."""
    return (f'tenant_device_seconds_total{{instance="{instance}",'
            f'kind="{kind}",tenant="{tenant}"}}')


def tenant_block_key(instance: str, tenant: str) -> str:
    """Registry series key of one tenant's KV block-seconds counter."""
    return (f'tenant_kv_block_seconds_total{{instance="{instance}",'
            f'tenant="{tenant}"}}')


class CostLedger:
    """The per-instance resource ledger (one per scheduler, created by
    ``FCFSScheduler(cost_accounting=True)`` and attached to its
    ``ServingMetrics``). All ``record_*`` methods are cheap host-side
    dict arithmetic behind one leaf lock — safe from the scheduler's
    driving thread and the submit/cancel threads alike."""

    def __init__(self, *, instance: str, registry=None, events=None,
                 flush_event_every_s: float = 1.0) -> None:
        self.instance = str(instance)
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        self._flush_event_every_s = float(flush_event_every_s)
        # leaf: record_* runs under the scheduler's lock on some paths
        # (preempt), so nothing may be acquired while this is held —
        # flush() gathers deltas under it, then talks to the registry
        # (its own leaf locks) only after releasing
        self._lock = sanitizer.make_lock("CostLedger._lock", leaf=True)
        # (tenant, kind) -> cumulative attributed device seconds
        self._device: dict[tuple, float] = {}
        # tenant -> cumulative KV block-seconds (refcount-split integral)
        self._blocks: dict[str, float] = {}
        # tenant -> cumulative queue-wait wall seconds (not device time:
        # reported, but outside the conservation sum by definition)
        self._queue_wait: dict[str, float] = {}
        # conservation bookkeeping
        self._measured_s = 0.0
        self._attributed_s = 0.0
        self._dispatches = 0
        self._max_dispatch_err = 0.0
        # preempt-and-replay state: token debt still to regenerate, and
        # requests whose NEXT prefill is a replay of one already paid for
        self._replay_tokens: dict[int, int] = {}
        self._replay_prefill: set[int] = set()
        # flush watermarks (counter deltas are incs since last flush)
        self._flushed_device: dict[tuple, float] = {}
        self._flushed_blocks: dict[str, float] = {}
        self._t_last_event: Optional[float] = None
        self._last_summary: Optional[dict] = None

    # ------------------------------------------------------------------ #
    # recording (the splitting rules)                                     #
    # ------------------------------------------------------------------ #

    def record_queue_wait(self, tenant: str, seconds: float) -> None:
        """Wall seconds one request spent QUEUED before (re-)admission."""
        if seconds <= 0.0:
            return
        with self._lock:
            self._queue_wait[tenant] = (
                self._queue_wait.get(tenant, 0.0) + float(seconds))

    def record_prefill(self, interval_s: float, *, bucket: int,
                       batch_rows: int,
                       members: Sequence[tuple]) -> dict:
        """Split one bucketed-prefill dispatch of ``batch_rows`` rows
        across its ``members`` ``(req_id, tenant, suffix_tokens)`` by
        token share; pad tails and unfilled rows book as ``padding``.
        Returns this dispatch's ``{(tenant, kind): seconds}`` (summing to
        ``interval_s`` — the conservation contract)."""
        interval_s = float(interval_s)
        batch_rows = max(int(batch_rows), len(members), 1)
        bucket = max(int(bucket), 1)
        row_s = interval_s / batch_rows
        out: dict[tuple, float] = {}
        with self._lock:
            for req_id, tenant, suffix in members:
                real = min(max(int(suffix), 0), bucket)
                useful_s = row_s * (real / bucket)
                pad_s = row_s - useful_s
                kind = "useful"
                if req_id in self._replay_prefill:
                    # this prefill re-does one a preemption threw away
                    self._replay_prefill.discard(req_id)
                    kind = "replay"
                if useful_s > 0.0:
                    out[(tenant, kind)] = out.get((tenant, kind), 0.0) \
                        + useful_s
                if pad_s > 0.0:
                    out[(tenant, "padding")] = out.get(
                        (tenant, "padding"), 0.0) + pad_s
            empty = batch_rows - len(members)
            if empty > 0:
                out[(UNATTRIBUTED, "padding")] = out.get(
                    (UNATTRIBUTED, "padding"), 0.0) + row_s * empty
            self._book_locked(interval_s, out)
        return out

    def record_decode(self, interval_s: float, *, n_rows: int,
                      rows: Sequence[tuple]) -> dict:
        """Split one decode dispatch across the ``n_rows`` compiled rows:
        each ``(req_id, tenant, committed, rejected)`` active row's even
        share splits ``committed : rejected`` into useful vs ``wasted``
        (speculative verify; a plain decode row has ``rejected == 0``),
        inactive rows book as ``idle``. A row whose request still owes
        replay debt books its useful part as ``replay``, token-metered.
        Returns this dispatch's attribution (sums to ``interval_s``)."""
        interval_s = float(interval_s)
        n_rows = max(int(n_rows), len(rows), 1)
        row_s = interval_s / n_rows
        out: dict[tuple, float] = {}
        with self._lock:
            for req_id, tenant, committed, rejected in rows:
                committed = max(int(committed), 1)
                rejected = max(int(rejected), 0)
                positions = committed + rejected
                useful_s = row_s * (committed / positions)
                wasted_s = row_s - useful_s
                debt = self._replay_tokens.get(req_id, 0)
                if debt > 0:
                    replayed = min(debt, committed)
                    replay_s = useful_s * (replayed / committed)
                    useful_s -= replay_s
                    if debt - replayed > 0:
                        self._replay_tokens[req_id] = debt - replayed
                    else:
                        self._replay_tokens.pop(req_id, None)
                    out[(tenant, "replay")] = out.get(
                        (tenant, "replay"), 0.0) + replay_s
                if useful_s > 0.0:
                    out[(tenant, "useful")] = out.get(
                        (tenant, "useful"), 0.0) + useful_s
                if wasted_s > 0.0:
                    out[(tenant, "wasted")] = out.get(
                        (tenant, "wasted"), 0.0) + wasted_s
            idle = n_rows - len(rows)
            if idle > 0:
                out[(UNATTRIBUTED, "idle")] = out.get(
                    (UNATTRIBUTED, "idle"), 0.0) + row_s * idle
            self._book_locked(interval_s, out)
        return out

    def record_migration(self, interval_s: float, *, req_id: int,
                         tenant: str) -> dict:
        """Book one KV-block migration's wall interval (gather dispatch +
        host bounce + scatter dispatch) entirely to the owning tenant as
        ``migrate`` — a single-request transfer has no rows to split, so
        conservation is exact by construction. Returns the attribution
        (``{(tenant, 'migrate'): interval_s}``)."""
        interval_s = float(interval_s)
        out: dict[tuple, float] = {}
        if interval_s > 0.0:
            out[(tenant, "migrate")] = interval_s
        with self._lock:
            self._book_locked(interval_s, out)
        return out

    def record_block_seconds(self, dt_s: float,
                             holders: Iterable[tuple]) -> None:
        """Advance the block-seconds integral by ``dt_s`` wall seconds:
        each ``(tenant, share)`` holder held ``share`` refcount-weighted
        blocks (``sum(1/refs(b))`` over its table — a block shared by r
        requests counts 1/r per holder)."""
        dt_s = float(dt_s)
        if dt_s <= 0.0:
            return
        with self._lock:
            for tenant, share in holders:
                if share <= 0.0:
                    continue
                self._blocks[tenant] = (
                    self._blocks.get(tenant, 0.0) + dt_s * float(share))

    def note_preempt(self, req_id: int, tenant: str,
                     tokens_discarded: int) -> None:
        """A preemption discarded this request's generated-so-far tokens;
        its re-admission will replay the prefill and regenerate them.
        Grows the replay debt by exactly what was discarded — the
        double-booking guard: work already owed stays owed once, and a
        preempt-during-replay adds only the newly discarded tokens."""
        with self._lock:
            self._replay_prefill.add(req_id)
            if tokens_discarded > 0:
                self._replay_tokens[req_id] = (
                    self._replay_tokens.get(req_id, 0)
                    + int(tokens_discarded))

    def finalize(self, req_id: int) -> None:
        """Drop per-request replay state at any terminal transition
        (retire / cancel / shed / error / drain). Idempotent."""
        with self._lock:
            self._replay_tokens.pop(req_id, None)
            self._replay_prefill.discard(req_id)

    def _book_locked(self, measured_s: float, out: dict) -> None:
        """Fold one dispatch's attribution into the cumulative ledger
        and update the conservation bookkeeping (lock held)."""
        attributed = 0.0
        for key, s in out.items():
            self._device[key] = self._device.get(key, 0.0) + s
            attributed += s
        self._measured_s += measured_s
        self._attributed_s += attributed
        self._dispatches += 1
        err = abs(attributed - measured_s) / max(measured_s, _EPS)
        if err > self._max_dispatch_err:
            self._max_dispatch_err = err

    # ------------------------------------------------------------------ #
    # folding into the registry                                           #
    # ------------------------------------------------------------------ #

    def flush(self, force_event: bool = False) -> dict:
        """Fold accumulated deltas into the process registry: per-tenant
        ``tenant_device_seconds_total{kind=}`` and
        ``tenant_kv_block_seconds_total`` counters, the fleet-level
        ``goodput_fraction{kind=}`` gauge set and the
        ``cost_conservation_error`` gauge. Called once per scheduler
        step; a ``cost_flush`` event is emitted at most every
        ``flush_event_every_s`` (or always with ``force_event``).
        Returns the summary the event carries."""
        with self._lock:
            dev_deltas = {}
            for key, total in self._device.items():
                d = total - self._flushed_device.get(key, 0.0)
                if d > 0.0:
                    dev_deltas[key] = d
                    self._flushed_device[key] = total
            blk_deltas = {}
            for tenant, total in self._blocks.items():
                d = total - self._flushed_blocks.get(tenant, 0.0)
                if d > 0.0:
                    blk_deltas[tenant] = d
                    self._flushed_blocks[tenant] = total
            # idle fast path: flush() runs once per scheduler step, so a
            # quiet engine must not pay registry lookups every step
            if (not dev_deltas and not blk_deltas and not force_event
                    and self._last_summary is not None):
                return self._last_summary
            by_kind = self._by_kind_locked()
            measured = self._measured_s
            attributed = self._attributed_s
            dispatches = self._dispatches
            tenants = {t for t, _ in self._device if t != UNATTRIBUTED}
            err = abs(attributed - measured) / max(measured, _EPS)
            summary = {
                "measured_s": round(measured, 6),
                "attributed_s": round(attributed, 6),
                "conservation_error": round(err, 9),
                "dispatches": dispatches,
                "tenants": len(tenants),
            }
            self._last_summary = summary
        # registry/event work OUTSIDE the leaf lock (they take their own)
        reg = self._registry
        inst = self.instance
        for (tenant, kind), d in dev_deltas.items():
            reg.counter("tenant_device_seconds_total",
                        {"instance": inst, "tenant": tenant,
                         "kind": kind}).inc(d)
        for tenant, d in blk_deltas.items():
            reg.counter("tenant_kv_block_seconds_total",
                        {"instance": inst, "tenant": tenant}).inc(d)
        total = sum(by_kind.values())
        for kind in KINDS:
            frac = by_kind.get(kind, 0.0) / total if total > 0.0 else 0.0
            reg.gauge("goodput_fraction",
                      {"instance": inst, "kind": kind}).set(frac)
        reg.gauge("cost_conservation_error", {"instance": inst}).set(err)
        now = time.perf_counter()
        if (force_event or self._t_last_event is None
                or now - self._t_last_event >= self._flush_event_every_s):
            self._t_last_event = now
            self._events.emit("cost_flush", instance=inst, **summary)
        return summary

    # ------------------------------------------------------------------ #
    # reporting                                                           #
    # ------------------------------------------------------------------ #

    def _by_kind_locked(self) -> dict:
        by_kind: dict[str, float] = {}
        for (_, kind), s in self._device.items():
            by_kind[kind] = by_kind.get(kind, 0.0) + s
        return by_kind

    def payload(self) -> dict:
        """Plain-dict cumulative state for fleet pooling (see
        :func:`merge_cost_payloads`) — the cost analogue of
        ``ServingMetrics.payload()``."""
        with self._lock:
            return {
                "device": {f"{t}\x00{k}": s
                           for (t, k), s in self._device.items()},
                "blocks": dict(self._blocks),
                "queue_wait": dict(self._queue_wait),
                "measured_s": self._measured_s,
                "attributed_s": self._attributed_s,
                "dispatches": self._dispatches,
                "max_dispatch_error": self._max_dispatch_err,
            }

    def report(self) -> dict:
        """The ``/costs`` payload: per-tenant device-seconds by kind,
        block-seconds, queue wait; the goodput breakdown; and the
        conservation audit."""
        return _render_report(self.payload())

    def tenant_device_seconds(self) -> dict:
        """``{tenant: attributed device seconds}`` over real tenants
        (the unattributed ``"-"`` share excluded) — the cheap ranking
        the controller uses to name the top cost contributor."""
        out: dict[str, float] = {}
        with self._lock:
            for (tenant, _), s in self._device.items():
                if tenant != UNATTRIBUTED:
                    out[tenant] = out.get(tenant, 0.0) + s
        return out

    def top_tenant(self) -> Optional[tuple]:
        """``(tenant, device_seconds)`` of the heaviest real tenant, or
        ``None`` before any attributed work."""
        ranked = self.tenant_device_seconds()
        if not ranked:
            return None
        tenant = max(ranked, key=lambda t: (ranked[t], t))
        return tenant, ranked[tenant]

    @property
    def conservation_error(self) -> float:
        """|attributed − measured| / measured over the ledger's life."""
        with self._lock:
            return (abs(self._attributed_s - self._measured_s)
                    / max(self._measured_s, _EPS))


def merge_cost_payloads(payloads: Sequence[dict]) -> dict:
    """Pool N replicas' :meth:`CostLedger.payload` dicts into one
    fleet-level cost report (sums everywhere; fractions recomputed) —
    what ``FleetRouter.fleet_report()["costs"]`` embeds."""
    merged = {"device": {}, "blocks": {}, "queue_wait": {},
              "measured_s": 0.0, "attributed_s": 0.0, "dispatches": 0,
              "max_dispatch_error": 0.0}
    for p in payloads:
        for key, s in p.get("device", {}).items():
            merged["device"][key] = merged["device"].get(key, 0.0) + s
        for t, s in p.get("blocks", {}).items():
            merged["blocks"][t] = merged["blocks"].get(t, 0.0) + s
        for t, s in p.get("queue_wait", {}).items():
            merged["queue_wait"][t] = merged["queue_wait"].get(t, 0.0) + s
        merged["measured_s"] += p.get("measured_s", 0.0)
        merged["attributed_s"] += p.get("attributed_s", 0.0)
        merged["dispatches"] += p.get("dispatches", 0)
        merged["max_dispatch_error"] = max(
            merged["max_dispatch_error"], p.get("max_dispatch_error", 0.0))
    return _render_report(merged)


def _render_report(p: dict) -> dict:
    tenants: dict[str, dict] = {}
    by_kind: dict[str, float] = {}
    for key, s in p["device"].items():
        tenant, _, kind = key.partition("\x00")
        row = tenants.setdefault(
            tenant, {"device_s": {}, "device_total_s": 0.0,
                     "kv_block_s": 0.0, "queue_wait_s": 0.0})
        row["device_s"][kind] = round(
            row["device_s"].get(kind, 0.0) + s, 6)
        row["device_total_s"] = round(row["device_total_s"] + s, 6)
        by_kind[kind] = by_kind.get(kind, 0.0) + s
    for t, s in p["blocks"].items():
        row = tenants.setdefault(
            t, {"device_s": {}, "device_total_s": 0.0,
                "kv_block_s": 0.0, "queue_wait_s": 0.0})
        row["kv_block_s"] = round(row["kv_block_s"] + s, 6)
    for t, s in p["queue_wait"].items():
        row = tenants.setdefault(
            t, {"device_s": {}, "device_total_s": 0.0,
                "kv_block_s": 0.0, "queue_wait_s": 0.0})
        row["queue_wait_s"] = round(row["queue_wait_s"] + s, 6)
    total = sum(by_kind.values())
    goodput = {kind: (round(by_kind.get(kind, 0.0) / total, 6)
                      if total > 0.0 else 0.0) for kind in KINDS}
    measured = p["measured_s"]
    return {
        "tenants": tenants,
        "goodput": goodput,
        "device_time": {
            "measured_s": round(measured, 6),
            "attributed_s": round(p["attributed_s"], 6),
            "conservation_error": round(
                abs(p["attributed_s"] - measured) / max(measured, _EPS), 9),
            "max_dispatch_error": round(p["max_dispatch_error"], 9),
            "dispatches": p["dispatches"],
        },
    }


# ---------------------------------------------------------------------- #
# sensors: the noisy-neighbor spine                                       #
# ---------------------------------------------------------------------- #

class ShareOfTotal:
    """Derived signal: the ``num`` series' newest value over the sum of
    its sibling series' newest values — one tenant's share of the whole
    pool's rate (skipped while the total is 0)."""

    def __init__(self, num: str, siblings: Sequence[str],
                 name: str) -> None:
        self.num = num
        self.siblings = list(siblings)
        self.name = name

    def evaluate(self, store: TimeSeriesStore, now: float) -> None:
        n = store.last(self.num)
        if n is None:
            return
        total = 0.0
        for key in self.siblings:
            latest = store.last(key)
            if latest is not None:
                total += max(latest[1], 0.0)
        if total <= 0.0:
            return
        store.append(self.name, n[0], max(n[1], 0.0) / total)


class NoisyNeighborDetector(Detector):
    """Edge-triggered detector that NAMES its tenant: wraps either a
    fixed threshold (``threshold=`` — deterministic, what the bench
    scenario uses on a share series) or a z-score drift check on the
    tenant's device-seconds rate. On the rising edge it emits a
    ``noisy_neighbor`` event carrying ``tenant=`` on top of the base
    class's ``detector_fired``."""

    def __init__(self, name: str, series: str, *, tenant: str,
                 threshold: Optional[float] = None, z: float = 3.0,
                 baseline: int = 64, min_points: int = 8,
                 severity: str = "degraded") -> None:
        super().__init__(name, series, severity)
        self.tenant = tenant
        self.threshold = (float(threshold) if threshold is not None
                          else None)
        self._z = (None if threshold is not None else ZScoreDetector(
            name + ":z", series, z=z, direction="above",
            baseline=baseline, min_points=min_points, severity=severity))

    def check(self, store: TimeSeriesStore, now: float) -> dict:
        if self.threshold is not None:
            latest = store.last(self.series)
            if latest is None:
                verdict = {"firing": False, "value": None,
                           "threshold": self.threshold}
            else:
                verdict = {"firing": latest[1] > self.threshold,
                           "value": latest[1],
                           "threshold": self.threshold}
        else:
            verdict = self._z.check(store, now)
        verdict["tenant"] = self.tenant
        return verdict

    def evaluate(self, store: TimeSeriesStore, now: float, *,
                 registry=None, events=None) -> dict:
        was = self.firing
        verdict = super().evaluate(store, now, registry=registry,
                                   events=events)
        if events is not None and self.firing and not was:
            fields = {k: v for k, v in verdict.items()
                      if isinstance(v, (int, float, str, bool))}
            fields.pop("tenant", None)
            events.emit("noisy_neighbor", tenant=self.tenant,
                        detector=self.name, series=self.series, **fields)
        return verdict


def standard_tenant_sensors(tenant: str, instance: str, *,
                            tenants: Optional[Sequence[str]] = None,
                            share_threshold: Optional[float] = None,
                            rate_threshold: Optional[float] = None,
                            z: float = 3.0, baseline: int = 64,
                            min_points: int = 8,
                            tag: Optional[str] = None) -> tuple:
    """The per-tenant sensor kit, mirroring
    :func:`~chainermn_tpu.monitor.health.standard_replica_sensors`:
    returns ``(signals, detectors)`` for one tenant on one scheduler
    instance, ready for ``Collector(signals=..., detectors=...)``.

    Signals (when ``tenants`` — the full tenant list — is given): the
    tenant's share of the pool's useful device-seconds rate
    (``tenant_device_share:<tag>``) and of the KV block-seconds rate
    (``tenant_block_share:<tag>``), both derived from the counter
    ``:rate`` series the collector builds automatically.

    The detector watches, in order of preference: the device share
    against ``share_threshold`` (deterministic — the two-tenant bench
    contract), the useful rate against ``rate_threshold``, or z-score
    drift of the useful rate (the open-world default).
    """
    tag = tag if tag is not None else f"{tenant}@{instance}"
    dev_rate = tenant_device_key(instance, tenant, "useful") + ":rate"
    blk_rate = tenant_block_key(instance, tenant) + ":rate"
    share_series = f"tenant_device_share:{tag}"
    signals = []
    if tenants:
        signals.append(ShareOfTotal(
            dev_rate,
            [tenant_device_key(instance, t, "useful") + ":rate"
             for t in tenants],
            name=share_series))
        signals.append(ShareOfTotal(
            blk_rate,
            [tenant_block_key(instance, t) + ":rate" for t in tenants],
            name=f"tenant_block_share:{tag}"))
    if share_threshold is not None and tenants:
        detector = NoisyNeighborDetector(
            f"noisy_neighbor:{tag}", share_series, tenant=tenant,
            threshold=share_threshold)
    elif rate_threshold is not None:
        detector = NoisyNeighborDetector(
            f"noisy_neighbor:{tag}", dev_rate, tenant=tenant,
            threshold=rate_threshold)
    else:
        detector = NoisyNeighborDetector(
            f"noisy_neighbor:{tag}", dev_rate, tenant=tenant, z=z,
            baseline=baseline, min_points=min_points)
    return signals, [detector]


__all__ = [
    "KINDS",
    "UNATTRIBUTED",
    "CostLedger",
    "NoisyNeighborDetector",
    "ShareOfTotal",
    "merge_cost_payloads",
    "standard_tenant_sensors",
    "tenant_block_key",
    "tenant_device_key",
]
