"""Declarative SLOs with multi-window burn-rate evaluation.

An SLO turns a registry series into a *decision signal*: "TTFT p99 must
stay under X ms", "error rate must stay under Y". The classic SRE framing
is the **error budget**: a ``q``-quantile latency objective allows a
``1 - q`` fraction of requests over the threshold; the **burn rate** is
how fast the live traffic is spending that allowance (``burn = observed
bad fraction / allowed bad fraction`` — 1.0 means exactly on budget,
10 means the budget burns 10x too fast). Evaluating it over MULTIPLE
windows (a short one + a long one, both required to burn) keeps a single
slow request from paging while still catching sustained regressions
fast — the standard multi-window multi-burn-rate alert shape.

Inputs come from the process registry: latency objectives read a
histogram's timestamped reservoir (:meth:`~chainermn_tpu.monitor.
registry.Histogram.recent`), error-rate objectives difference counters
between :meth:`SLOEngine.evaluate` calls (the engine keeps its own
bounded snapshot history, so counters don't need timestamps). Each
evaluation publishes ``slo_burn_rate{slo=,window=}`` gauges and a
``slo_compliant{slo=}`` gauge back into the registry — which makes fleet
pooling free: ``monitor.aggregate(comm)`` already averages gauges across
ranks, so rank 0 sees fleet-level burn rates (the admission signal the
future multi-replica router reads).

A breach (every window burning past ``burn_threshold``) emits one
``slo_breach`` flight-recorder event **naming the offending trace ids**
(the tracer's retained slow/errored/deadline-missed traces in the long
window), so an alert joins directly against the causal span trees.

This module must not import ``chainermn_tpu.extensions`` (or jax) at
module level — pinned by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from chainermn_tpu.monitor._state import get_event_log, get_registry


@dataclass
class LatencyObjective:
    """``quantile(metric) < threshold_s``, e.g. TTFT p99 < 200 ms.

    ``metric`` names a seconds-valued registry histogram; every labelled
    instance of that name pools into the objective (a scheduler restart
    changes the ``instance`` label, the SLO shouldn't reset). The allowed
    bad fraction is ``1 - target_quantile``; ``min_samples`` keeps an
    empty window from reporting (burn 0, not NaN)."""

    name: str
    metric: str
    threshold_s: float
    target_quantile: float = 0.99
    windows: tuple = (60.0, 300.0)
    burn_threshold: float = 1.0
    min_samples: int = 1

    def __post_init__(self) -> None:
        if not 0.0 < self.target_quantile < 1.0:
            raise ValueError(
                f"target_quantile must be in (0, 1), got "
                f"{self.target_quantile}")
        if self.threshold_s <= 0:
            raise ValueError(f"threshold_s must be > 0, got "
                             f"{self.threshold_s}")


@dataclass
class ErrorRateObjective:
    """``bad / total < target_rate`` over each window, e.g. errored+shed
    requests under 1% of submissions. ``bad`` / ``total`` name registry
    counters (tuples pool several series; all label sets of a name sum).
    Rates come from counter DELTAS between evaluations, so the engine
    must be evaluated periodically (a scheduler step hook, the HTTP
    scraper, or a test driving ``evaluate(now=...)`` explicitly)."""

    name: str
    bad: tuple
    total: tuple
    target_rate: float = 0.01
    windows: tuple = (60.0, 300.0)
    burn_threshold: float = 1.0
    min_events: int = 1
    _history: deque = field(default_factory=lambda: deque(maxlen=4096),
                            repr=False)

    def __post_init__(self) -> None:
        if isinstance(self.bad, str):
            self.bad = (self.bad,)
        if isinstance(self.total, str):
            self.total = (self.total,)
        if not 0.0 < self.target_rate < 1.0:
            raise ValueError(
                f"target_rate must be in (0, 1), got {self.target_rate}")


class SLOEngine:
    """Evaluate declared objectives against the live registry.

    One engine per process is the normal shape (the HTTP ``/slo``
    endpoint and ``ServingMetrics`` report through the same instance);
    private engines (tests) take their own registry/events/tracer.
    """

    def __init__(self, *, registry=None, events=None, tracer=None) -> None:
        self._registry = registry if registry is not None else get_registry()
        self._events = events if events is not None else get_event_log()
        if tracer is None:
            from chainermn_tpu.monitor.trace import get_tracer

            tracer = get_tracer()
        self._tracer = tracer
        self._lock = threading.Lock()
        self._objectives: list = []
        self._breached: dict[str, bool] = {}   # edge-triggered breach state
        self._last: dict = {}

    def add(self, objective) -> "SLOEngine":
        if not isinstance(objective, (LatencyObjective, ErrorRateObjective)):
            raise TypeError(
                f"expected LatencyObjective or ErrorRateObjective, got "
                f"{type(objective).__name__}")
        with self._lock:
            if any(o.name == objective.name for o in self._objectives):
                raise ValueError(f"objective {objective.name!r} already "
                                 "declared")
            self._objectives.append(objective)
        return self

    @property
    def objectives(self) -> list:
        with self._lock:
            return list(self._objectives)

    # -- registry reads ---------------------------------------------------- #

    def _histograms_named(self, name: str) -> list:
        from chainermn_tpu.monitor.registry import Histogram

        with self._registry._lock:
            insts = list(self._registry._instruments.values())
        return [i for i in insts
                if isinstance(i, Histogram) and i.name == name]

    def _counter_sum(self, names: tuple) -> int:
        from chainermn_tpu.monitor.registry import Counter

        with self._registry._lock:
            insts = list(self._registry._instruments.values())
        return sum(int(i.value) for i in insts
                   if isinstance(i, Counter) and i.name in names)

    # -- evaluation -------------------------------------------------------- #

    def _eval_latency(self, obj: LatencyObjective, now: float) -> dict:
        hists = self._histograms_named(obj.metric)
        allowed = 1.0 - obj.target_quantile
        per_window = {}
        for w in obj.windows:
            samples: list = []
            for h in hists:
                samples.extend(h.recent(w, now=now))
            if len(samples) < obj.min_samples:
                per_window[w] = {"samples": len(samples), "bad_frac": 0.0,
                                 "burn_rate": 0.0}
                continue
            bad = sum(1 for s in samples if s > obj.threshold_s)
            frac = bad / len(samples)
            per_window[w] = {"samples": len(samples),
                             "bad_frac": round(frac, 6),
                             "burn_rate": round(frac / allowed, 4)}
        return per_window

    def _eval_error_rate(self, obj: ErrorRateObjective, now: float) -> dict:
        bad = self._counter_sum(obj.bad)
        total = self._counter_sum(obj.total)
        obj._history.append((now, bad, total))
        per_window = {}
        for w in obj.windows:
            cutoff = now - w
            # the oldest snapshot still inside the window anchors the delta
            anchor = None
            for t, b, n in obj._history:
                if t >= cutoff:
                    anchor = (b, n)
                    break
            if anchor is None:
                anchor = (bad, total)
            d_bad = bad - anchor[0]
            d_total = total - anchor[1]
            if d_total < obj.min_events:
                per_window[w] = {"events": d_total, "bad": d_bad,
                                 "rate": 0.0, "burn_rate": 0.0}
                continue
            rate = d_bad / d_total
            per_window[w] = {"events": d_total, "bad": d_bad,
                             "rate": round(rate, 6),
                             "burn_rate": round(rate / obj.target_rate, 4)}
        return per_window

    def _offending_traces(self, obj, window_s: float,
                          limit: int = 16) -> list[str]:
        """Trace ids the breach should name: retained traces that ended
        inside the window and are slow past the objective's threshold,
        errored, or deadline-missed — the join key into ``/traces``."""
        since = time.perf_counter() - float(window_s)
        ids = []
        threshold = getattr(obj, "threshold_s", None)
        for t in self._tracer.finished(since=since):
            slow = threshold is not None and t.duration_s > threshold
            if slow or t.error is not None or t.deadline_miss:
                ids.append(t.trace_id)
        return ids[-limit:]

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: per-objective per-window burn rates,
        ``compliant`` verdicts, registry gauges updated, and an
        edge-triggered ``slo_breach`` event (+ ``slo_breaches_total``)
        when an objective newly exceeds its burn threshold in EVERY
        window. ``now`` (``time.monotonic()`` scale) is injectable for
        tests."""
        now = time.monotonic() if now is None else float(now)
        report: dict = {}
        for obj in self.objectives:
            if isinstance(obj, LatencyObjective):
                per_window = self._eval_latency(obj, now)
            else:
                per_window = self._eval_error_rate(obj, now)
            burns = [per_window[w]["burn_rate"] for w in obj.windows]
            breached = bool(burns) and all(
                b > obj.burn_threshold for b in burns)
            for w in obj.windows:
                self._registry.gauge(
                    "slo_burn_rate",
                    {"slo": obj.name, "window": f"{w:g}s"},
                ).set(per_window[w]["burn_rate"])
            self._registry.gauge(
                "slo_compliant", {"slo": obj.name}).set(0.0 if breached
                                                        else 1.0)
            entry = {
                "kind": ("latency" if isinstance(obj, LatencyObjective)
                         else "error_rate"),
                "windows": {f"{w:g}s": per_window[w] for w in obj.windows},
                "max_burn_rate": round(max(burns, default=0.0), 4),
                "burn_threshold": obj.burn_threshold,
                "compliant": not breached,
            }
            if isinstance(obj, LatencyObjective):
                entry["threshold_s"] = obj.threshold_s
                entry["target_quantile"] = obj.target_quantile
            else:
                entry["target_rate"] = obj.target_rate
            was = self._breached.get(obj.name, False)
            if breached and not was:
                traces = self._offending_traces(obj, max(obj.windows))
                entry["offending_traces"] = traces
                self._registry.counter(
                    "slo_breaches_total", {"slo": obj.name}).inc()
                self._events.emit(
                    "slo_breach", slo=obj.name,
                    max_burn_rate=entry["max_burn_rate"],
                    windows={f"{w:g}s": per_window[w]["burn_rate"]
                             for w in obj.windows},
                    traces=traces)
            elif breached:
                entry["offending_traces"] = self._offending_traces(
                    obj, max(obj.windows))
            self._breached[obj.name] = breached
            report[obj.name] = entry
        with self._lock:
            self._last = report
        return report

    @property
    def last(self) -> dict:
        """The most recent :meth:`evaluate` result (the ``/slo`` payload
        when the endpoint prefers not to re-evaluate)."""
        with self._lock:
            return dict(self._last)

    # -- fleet pooling ------------------------------------------------------ #

    def aggregate(self, comm) -> dict:
        """Pool burn rates across ranks over the communicator's object
        transport: per objective/window the fleet MEAN (the pooled burn —
        what a router budgets against) and MAX (the worst replica — what
        it routes away from). Every rank returns the same dict."""
        local = {
            name: {w: ent["burn_rate"]
                   for w, ent in entry["windows"].items()}
            for name, entry in self.last.items()
        }
        gathered = comm.allgather_obj(local)
        out: dict = {"ranks": len(gathered)}
        names = {n for g in gathered for n in g}
        for name in sorted(names):
            windows: dict = {}
            for g in gathered:
                for w, b in g.get(name, {}).items():
                    windows.setdefault(w, []).append(float(b))
            out[name] = {
                w: {"mean_burn_rate": round(sum(v) / len(v), 4),
                    "max_burn_rate": round(max(v), 4)}
                for w, v in windows.items()
            }
        return out


_ENGINE: Optional[SLOEngine] = None
_ENGINE_LOCK = threading.Lock()


def get_slo_engine() -> SLOEngine:
    """The process-wide default :class:`SLOEngine` (lazily built; the
    HTTP ``/slo`` endpoint and example flags share it)."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = SLOEngine()
        return _ENGINE


__all__ = [
    "ErrorRateObjective",
    "LatencyObjective",
    "SLOEngine",
    "get_slo_engine",
]
