"""Profiler annotations: one context manager for host AND traced code.

``annotate(name)`` enters both ``jax.profiler.TraceAnnotation`` (host-side
TraceMe — the region shows up on the Python/host rows of an XProf/Perfetto
capture) and ``jax.named_scope`` (trace-time name stack — the region's XLA
ops carry the name in their metadata, so device rows are legible too).
Either half degrades to a no-op when the running JAX lacks it (legacy
releases), and entering them is cheap when no profiler is attached, so the
annotations stay on permanently in the hot paths (train step bodies,
serving prefill/decode, communicator collectives).

Scope names deliberately avoid XLA collective opcode spellings
(``all-reduce`` etc.): names land in HLO ``op_name`` metadata, and
:func:`~chainermn_tpu.extensions.profiling.parse_hlo_collectives` scans raw
HLO text — ``chainermn.allreduce`` can never collide with ``all-reduce(``.
"""

from __future__ import annotations

class _Annotation:
    """Re-entrant-constructible, single-use context manager pair."""

    __slots__ = ("_name", "_tm", "_ns")

    def __init__(self, name: str) -> None:
        self._name = name
        self._tm = None
        self._ns = None

    def __enter__(self) -> "_Annotation":
        # lazy: monitor must stay importable without jax (fleet/deploy
        # ride monitor at module level and are pure host-logic imports);
        # by the time an annotation is *entered*, jax is already loaded
        # by whatever produced the work being annotated
        import jax

        try:
            tm = jax.profiler.TraceAnnotation(self._name)
            tm.__enter__()
            self._tm = tm
        except Exception:
            self._tm = None
        try:
            ns = jax.named_scope(self._name)
            ns.__enter__()
            self._ns = ns
        except Exception:
            self._ns = None
        return self

    def __exit__(self, *exc) -> None:
        if self._ns is not None:
            try:
                self._ns.__exit__(*exc)
            finally:
                self._ns = None
        if self._tm is not None:
            try:
                self._tm.__exit__(*exc)
            finally:
                self._tm = None


def annotate(name: str) -> _Annotation:
    """Name a region for profiling::

        with monitor.annotate("chainermn.decode"):
            ...   # host call OR traced computation

    Inside a trace the enclosed ops get ``name`` in their HLO metadata
    (named_scope); around a host call the region appears on the host
    timeline (TraceAnnotation). No-op fallback on JAX builds lacking
    either API.
    """
    return _Annotation(str(name))


__all__ = ["annotate"]
