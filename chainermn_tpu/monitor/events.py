"""Structured event log + flight recorder.

A bounded in-memory ring of structured events (step start/end,
prefill/decode, slot admit/retire, compile, watchdog arm/fire) that costs
one deque append per event while healthy, and is dumped — last N events as
JSONL plus per-device ``memory_stats()`` — the moment something goes wrong:
:class:`~chainermn_tpu.extensions.profiling.Watchdog` firing, or
``global_except_hook`` tripping. A hang or crash then prints *what the
system was doing*, not just thread stacks (SURVEY.md S5: lost collectives
in the reference are silent).
"""

from __future__ import annotations

import itertools
import json
import sys
import threading
import time
import weakref
from collections import deque
from typing import Optional


def device_memory_lines() -> list[str]:
    """One human line per jax device: the ``memory_stats()`` essentials
    (bytes in use / peak / limit), or a note when the backend exposes none
    (CPU returns ``None``). Never raises — this runs inside crash paths."""
    lines: list[str] = []
    try:
        import jax

        for i, d in enumerate(jax.devices()):
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                lines.append(
                    f"device {i} ({d.device_kind}): memory_stats unavailable")
                continue
            used = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            limit = stats.get("bytes_limit")
            parts = [f"device {i} ({d.device_kind}):"]
            if used is not None:
                parts.append(f"in_use={used / 1e6:.1f}MB")
            if peak is not None:
                parts.append(f"peak={peak / 1e6:.1f}MB")
            if limit is not None:
                parts.append(f"limit={limit / 1e6:.1f}MB")
            lines.append(" ".join(parts))
    except Exception as e:  # jax missing/broken mid-crash: still dump events
        lines.append(f"device memory unavailable: {type(e).__name__}: {e}")
    return lines


class EventLog:
    """Bounded structured event ring.

    :meth:`emit` is the hot-path call: one timestamped dict appended to a
    ``deque(maxlen=capacity)`` under a lock — no I/O, no serialization, so
    it can sit inside serving decode loops and per-step training wrappers.
    :meth:`dump` is the failure-path call: write the tail as JSONL plus
    device memory stats to a sink (stderr by default).
    """

    def __init__(self, capacity: int = 1024) -> None:
        self._ring: deque = deque(maxlen=capacity)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        # per-sink once-guard: {sink -> set of dump keys already written}.
        # Weak keys so test sinks (StringIO) drop out with their tests;
        # sys.stderr persists — which is exactly the sink the guard exists
        # for (one failure must produce ONE dump across Watchdog /
        # global_except_hook / the resilient-trainer boundary).
        self._dump_guard: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary())

    def emit(self, kind: str, **fields) -> None:
        ev = {"i": next(self._seq), "t": round(time.time(), 6),
              "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def tail(self, n: Optional[int] = None) -> list[dict]:
        with self._lock:
            evs = list(self._ring)
        return evs if n is None else evs[-n:]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, file=None, last: int = 64, memory: bool = True,
             once: Optional[str] = None) -> int:
        """Write the flight-recorder tail; returns the number of events
        dumped. Format: a banner, one JSON object per line (oldest first),
        then per-device memory stats — grep-able and machine-parseable.

        ``once``: a failure-episode key — a second guarded dump with the
        same key to the same sink is suppressed (one line notes it), so
        layered failure paths (Watchdog fire -> exception -> excepthook)
        produce exactly one dump. :meth:`reset_dump_guard` re-arms after a
        successful recovery so the NEXT failure dumps again.
        """
        sink = file or sys.stderr
        if once is not None:
            with self._lock:
                try:
                    keys = self._dump_guard.get(sink)
                    if keys is None:
                        keys = set()
                        self._dump_guard[sink] = keys
                except TypeError:      # un-weakref-able sink: never suppress
                    keys = set()
                if once in keys:
                    try:
                        print(
                            "chainermn_tpu.monitor flight recorder: already "
                            f"dumped for {once!r}; suppressing duplicate",
                            file=sink,
                        )
                    except Exception:
                        pass
                    return 0
                keys.add(once)
        evs = self.tail(last)
        print(
            f"chainermn_tpu.monitor flight recorder: last {len(evs)} "
            f"event(s) of {len(self)} retained",
            file=sink,
        )
        for ev in evs:
            try:
                print(json.dumps(ev, default=str), file=sink)
            except Exception:
                print(str(ev), file=sink)
        if memory:
            print("device memory:", file=sink)
            for line in device_memory_lines():
                print(f"  {line}", file=sink)
        print("end flight recorder", file=sink)
        try:
            sink.flush()
        except Exception:
            pass
        return len(evs)

    def reset_dump_guard(self) -> None:
        """Forget every once-key: the failure episode is over (recovery
        succeeded), so a future failure dumps a fresh flight record."""
        with self._lock:
            self._dump_guard = weakref.WeakKeyDictionary()


__all__ = ["EventLog", "device_memory_lines"]
