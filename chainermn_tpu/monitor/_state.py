"""Process-wide default telemetry sinks.

Separate from the package facade so submodules (and the subsystems they
instrument: serving, training, communicators) can reach the singletons
without importing ``chainermn_tpu.monitor``'s ``__init__`` — which may be
mid-initialization when the communicator layer first pulls monitor in.
"""

from __future__ import annotations

from chainermn_tpu.monitor.events import EventLog
from chainermn_tpu.monitor.registry import MetricsRegistry

_REGISTRY = MetricsRegistry()
_EVENTS = EventLog()


def get_registry() -> MetricsRegistry:
    """The process-wide default :class:`MetricsRegistry`."""
    return _REGISTRY


def get_event_log() -> EventLog:
    """The process-wide default :class:`EventLog` (the flight recorder
    Watchdog/global_except_hook dump)."""
    return _EVENTS


__all__ = ["get_registry", "get_event_log"]
