"""Background device prefetch: H2D transfer overlapped with device compute.

The synchronous hot loop pays ``assemble + device_put + step`` per
iteration; the reference hides assembly behind worker processes
(MultiprocessIterator) but still pays the host->device transfer on the
critical path. :class:`DevicePrefetcher` moves BOTH off it: a producer
thread draws batches from any iterator, optionally collates them
(``transform``), ``jax.device_put``s them onto the mesh with the step's
input shardings, and parks them — already device-resident — in a bounded
queue. Steady state, the training thread's per-iteration input cost is a
queue pop.

Contracts:

- **drains cleanly** — :meth:`close` (also the context-manager exit)
  stops the producer, unblocks it if it is waiting on a full queue, and
  joins the thread; abandoning iteration early never leaks a thread;
- **propagates producer exceptions** — an error raised while drawing,
  collating, or transferring a batch re-raises in the consumer's
  ``next()``, not silently on a daemon thread;
- **resume stays bit-exact** — :meth:`state_dict` returns the *wrapped*
  iterator's state positioned to draw the first batch the consumer has
  NOT yet received (batches sitting prefetched in the queue are not
  "consumed"), in the wrapped iterator's own format — so a snapshot taken
  through the prefetcher restores interchangeably onto a bare iterator
  and vice versa.

Telemetry (process registry): ``prefetch_queue_depth{name=}`` gauge,
``prefetch_h2d_seconds`` histogram (transfer time per batch, measured on
the producer thread — i.e. off the critical path), ``prefetch_stall_total``
counter + ``prefetch_stall_seconds`` histogram (consumer arrived at an
empty queue: the producer is the bottleneck), ``prefetch_batches_total``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_registry
from chainermn_tpu.monitor.trace import span as _trace_span

_DONE = "done"
_ERROR = "error"
_BATCH = "batch"


class DevicePrefetcher:
    """Wrap a batch iterator with a device-put-ahead producer thread.

    Parameters
    ----------
    iterator : iterator or iterable
        Yields batches. ``SerialIterator``, the multi-node iterators, a
        ``NativeBatchLoader``, or any generator all work. If it exposes
        ``state_dict``/``load_state_dict`` (and is its own iterator),
        resume is supported — see :meth:`state_dict`.
    depth : int
        How many batches to keep ready (queue bound). ``depth`` batches
        of device memory are pinned in addition to the one being stepped.
    sharding : optional
        Passed to ``jax.device_put`` (a ``Sharding`` applied to every
        leaf, or a pytree of shardings matching the batch). ``None``
        skips the transfer — host-side prefetch only.
    transform : callable, optional
        ``transform(batch) -> batch`` run on the producer thread before
        the transfer (collation: list-of-records -> arrays).
    snapshot : bool
        Capture ``iterator.state_dict()`` after every draw so
        :meth:`state_dict` is exact mid-epoch. Costs one state copy per
        batch (O(dataset) for ``SerialIterator``'s order array) — turn
        off for huge datasets when resume granularity of "wherever the
        wrapped iterator was" is enough. Default: on when the wrapped
        iterator supports it.
    """

    def __init__(self, iterator, *, depth: int = 2, sharding=None,
                 transform: Optional[Callable] = None,
                 snapshot: Optional[bool] = None,
                 name: str = "prefetch") -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        # epoch metadata may live on the iterABLE (NativeBatchLoader sets
        # epoch/is_new_epoch on itself while its generator yields), so keep
        # the source object for attribute capture
        self._src = iterator
        self._it = iterator if hasattr(iterator, "__next__") else iter(iterator)
        self._depth = int(depth)
        self._sharding = sharding
        self._transform = transform
        self._name = name
        self._stateful = (hasattr(self._it, "state_dict")
                          and hasattr(self._it, "load_state_dict"))
        self._snapshot = self._stateful if snapshot is None else bool(snapshot)
        if self._snapshot and not self._stateful:
            raise TypeError(
                "snapshot=True needs the wrapped iterator to expose "
                "state_dict()/load_state_dict()")
        # state positioned to draw the next UNDELIVERED batch
        self._resume_state = self._it.state_dict() if self._snapshot else None
        self.epoch = getattr(self._src, "epoch", 0)
        self.is_new_epoch = getattr(self._src, "is_new_epoch", False)

        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._finished = False

        reg = get_registry()
        labels = {"name": name}
        self._g_depth = reg.gauge("prefetch_queue_depth", labels)
        self._h_h2d = reg.histogram("prefetch_h2d_seconds", labels, unit="s")
        self._c_stall = reg.counter("prefetch_stall_total", labels)
        self._h_stall = reg.histogram("prefetch_stall_seconds", labels,
                                      unit="s")
        self._c_batches = reg.counter("prefetch_batches_total", labels)

    # -- producer -------------------------------------------------------- #

    def _offer(self, item) -> bool:
        """Blocking put that stays interruptible by :meth:`close`."""
        while not self._stop.is_set():
            # interleaving point: the fuzzer stretches the gap between
            # the stop check and the put — the close()/producer race
            sanitizer.sync_point("prefetch:offer")
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._offer((_DONE, None, None, None))
                    return
                state = self._it.state_dict() if self._snapshot else None
                meta = (getattr(self._src, "epoch", 0),
                        getattr(self._src, "is_new_epoch", False))
                if self._transform is not None:
                    batch = self._transform(batch)
                if self._sharding is not None:
                    import jax

                    t0 = time.perf_counter()
                    batch = jax.device_put(batch, self._sharding)
                    # force the transfer to finish HERE, on the producer's
                    # timeline — a lazy put would resolve on the consumer's
                    # first use, i.e. back on the critical path
                    jax.block_until_ready(batch)
                    self._h_h2d.observe(time.perf_counter() - t0)
                if not self._offer((_BATCH, batch, state, meta)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            self._offer((_ERROR, e, None, None))

    def _ensure_started(self) -> None:
        if self._thread is None and not self._finished:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._produce, name=f"prefetch-{self._name}",
                daemon=True)
            self._thread.start()

    # -- consumer protocol ----------------------------------------------- #

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        if self._finished:
            raise StopIteration
        self._ensure_started()
        # interleaving point: empty-check vs producer-put race window
        sanitizer.sync_point("prefetch:next")
        if self._q.empty():
            # the producer is behind: the input pipeline, not the step, is
            # the bottleneck right now — count it, time the wait, and put
            # the stall on the ambient train-step trace (if one is open)
            self._c_stall.inc()
            t0 = time.perf_counter()
            with _trace_span("prefetch_stall"):
                item = self._q.get()
            self._h_stall.observe(time.perf_counter() - t0)
        else:
            item = self._q.get()
        self._g_depth.set(self._q.qsize())
        kind, payload, state, meta = item
        if kind == _DONE:
            self._finished = True
            self._join()
            raise StopIteration
        if kind == _ERROR:
            self._finished = True
            self._join()
            raise payload
        if self._snapshot:
            self._resume_state = state
        self.epoch, self.is_new_epoch = meta
        self._c_batches.inc()
        return payload

    next = __next__

    # -- lifecycle ------------------------------------------------------- #

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def _join(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # unblock a producer waiting on a full queue...
            self._drain()
            t.join(timeout=5.0)
            self._thread = None
        # ...and drain AGAIN: the freed slot can admit the producer's
        # already-in-flight put before it re-checks the stop flag — a stale
        # batch that must never survive into a restarted iteration
        self._drain()
        self._g_depth.set(0)

    def close(self) -> None:
        """Stop and join the producer; safe to call repeatedly. Prefetched
        batches are discarded — iterating again after ``close`` without a
        ``load_state_dict`` would silently skip them, so the prefetcher
        stays stopped until repositioned."""
        self._join()
        self._finished = True

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # best effort; close() is the real contract
        try:
            self._stop.set()
        except Exception:
            pass

    # -- checkpointing ---------------------------------------------------- #

    def state_dict(self) -> dict:
        """The wrapped iterator's state, positioned to draw the first batch
        the consumer has not yet received — prefetched-but-undelivered
        batches are NOT consumed. Interchangeable with the wrapped
        iterator's own ``state_dict`` format."""
        if not self._snapshot:
            raise TypeError(
                "state_dict() needs snapshot=True and a wrapped iterator "
                "with state_dict()/load_state_dict()")
        return self._resume_state

    def load_state_dict(self, state: dict) -> None:
        """Reposition the wrapped iterator; discards every prefetched
        batch (they were drawn past the restore point)."""
        if not self._stateful:
            raise TypeError(
                "load_state_dict() needs a wrapped iterator with "
                "state_dict()/load_state_dict()")
        self._join()
        self._q = queue.Queue(maxsize=self._depth)  # belt + braces vs stale
        self._it.load_state_dict(state)
        self._resume_state = self._it.state_dict() if self._snapshot else None
        self.epoch = getattr(self._src, "epoch", 0)
        self.is_new_epoch = getattr(self._src, "is_new_epoch", False)
        self._finished = False


__all__ = ["DevicePrefetcher"]
