"""``chainermn_tpu.dataflow`` — the async hot-loop layer.

ChainerMN's core lesson is that scaling dies on the host: the accelerator
step is fast and everything serialized around it — data feeding, loss
fetches, snapshot writes — becomes the wall (PERF.md: per-step blocked
timing costs ~80 ms of host RTT vs ~52 ms queued on the same step). The
jitted steps already donate buffers; this package takes the HOST loop
around them off the critical path, in three pieces:

- :class:`DevicePrefetcher` — batches drawn, collated, and
  ``device_put`` onto the mesh by a producer thread, ``depth`` ahead:
  H2D transfer overlaps device compute instead of following it.
- :class:`LossWindow` + :func:`device_fetch` — dispatch-ahead stepping:
  losses stay on device and are fetched batched every ``window`` steps
  (one round trip closes the whole window), bounding in-flight dispatch;
  ``device_fetch`` is the trustworthy completion barrier (PERF.md's
  relay-ack hazard) shared with ``bench.py``'s timing methodology.
- ``MultiNodeCheckpointer.save_async`` (``extensions.checkpoint``) —
  ``device_get`` on the training thread (the consistency point), then
  serialize + CRC footer + atomic rename + GC on a writer thread.

Wired end to end by :func:`chainermn_tpu.training.fit` and
``resilience.resilient_fit(async_save=True)``; proven by
``bench.py --mode pipeline`` (pipelined wall/step ~= max(step, loader)
instead of step + loader).
"""

from chainermn_tpu.dataflow.dispatch import LossWindow, device_fetch
from chainermn_tpu.dataflow.prefetch import DevicePrefetcher

__all__ = ["DevicePrefetcher", "LossWindow", "device_fetch"]
