"""Dispatch-ahead stepping: keep losses on device, fetch batched every K.

PERF.md's measurement note is also a hot-loop rule: a per-step
``float(loss)`` costs a full device->host round trip on the critical path
(~80 ms of RTT per step through the axon tunnel — 133 ms blocked vs 52 ms
queued for the same ResNet-50 step), while JAX's async dispatch is happy
to run several steps ahead. :class:`LossWindow` is the loop-side half of
that bargain: ``push`` enqueues the on-device loss of each step and
returns immediately; once ``window`` losses are pending they are fetched
in ONE host round trip, which doubles as the bounded in-flight window —
the fetch of step ``i-K+1..i`` cannot resolve before those steps complete,
so dispatch never runs more than ``window`` steps past completion (an
unbounded run-ahead queues device work and host memory without limit).

:func:`device_fetch` is the other half, extracted from ``bench.py``'s
methodology (PERF.md "relay-ack hazard"): ``jax.block_until_ready`` can
return on a relay's acknowledgement before the device finishes producing
the buffer, so every timing window — and every "is this step done"
barrier — must close with a device->host VALUE fetch, which cannot
resolve early. Use it anywhere a trustworthy completion barrier is
needed; it is what :class:`LossWindow` closes its fetches with.

Telemetry (process registry): ``loss_fetch_total{loop=}`` (fetch EVENTS —
the per-step-host-sync guard test pins this at ``ceil(steps/window)``,
not ``steps``), ``loss_fetch_seconds`` histogram, ``dispatch_lag_steps``
histogram (how many steps were in flight when a fetch closed — the
dispatch-vs-complete lag), ``dispatch_inflight{loop=}`` gauge.
"""

from __future__ import annotations

from collections import deque
from time import perf_counter
from typing import Callable, Optional

from chainermn_tpu.monitor._state import get_registry
from chainermn_tpu.monitor.trace import span as _trace_span


def device_fetch(values):
    """Fetch device value(s) to host — the trustworthy completion barrier.

    Unlike ``jax.block_until_ready``, a value fetch cannot resolve before
    the device has actually produced the bytes (PERF.md: through the axon
    relay, ``block_until_ready`` acked 50 ResNet steps in 87 ms on a chip
    whose FLOP peak says that's impossible). Accepts any pytree of arrays;
    returns host (numpy) values.
    """
    import jax

    return jax.device_get(values)


class LossWindow:
    """Bounded in-flight window of on-device per-step losses.

    ``push(i, loss)`` is O(1) host work until the window fills; then all
    pending losses are fetched in one device round trip (amortized
    ``1/window`` syncs per step). ``drain()`` fetches the remainder and
    returns every loss, in step order, as floats.

    ``on_fetch(step_index, value)`` (optional) is called for each loss as
    its fetch completes — logging callbacks see values ``<= window-1``
    steps late, which is the price of keeping the loop unblocked.
    """

    def __init__(self, window: int = 8, *, name: str = "train",
                 on_fetch: Optional[Callable[[int, float], None]] = None
                 ) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._window = int(window)
        self._on_fetch = on_fetch
        self._pending: deque = deque()
        self._losses: list[float] = []
        reg = get_registry()
        labels = {"loop": name}
        self._c_fetches = reg.counter("loss_fetch_total", labels)
        self._h_fetch = reg.histogram("loss_fetch_seconds", labels, unit="s")
        self._h_lag = reg.histogram("dispatch_lag_steps", labels)
        self._g_inflight = reg.gauge("dispatch_inflight", labels)

    def push(self, step: int, loss) -> bool:
        """Enqueue step ``step``'s on-device loss; fetches (blocking once
        per ``window`` pushes) when the in-flight bound is reached.
        Returns True when this push closed a fetch — the caller's signal
        that the (rare) blocking host round trip happened here."""
        self._pending.append((step, loss))
        self._g_inflight.set(len(self._pending))
        if len(self._pending) >= self._window:
            self._fetch_pending()
            return True
        return False

    def _fetch_pending(self) -> None:
        if not self._pending:
            return
        steps = [s for s, _ in self._pending]
        vals = [v for _, v in self._pending]
        self._pending.clear()
        self._h_lag.observe(len(vals))
        t0 = perf_counter()
        # ONE round trip closes `len(vals)` steps; the ambient span puts
        # the blocking fetch on the current train-step trace (no-op when
        # no trace is ambient)
        with _trace_span("loss_fetch", n=len(vals)):
            host = device_fetch(vals)
        self._h_fetch.observe(perf_counter() - t0)
        self._c_fetches.inc()
        self._g_inflight.set(0)
        for s, v in zip(steps, host):
            v = float(v)
            self._losses.append(v)
            if self._on_fetch is not None:
                self._on_fetch(s, v)

    def drain(self) -> list[float]:
        """Fetch whatever is still in flight; returns ALL losses in step
        order. The loop's closing barrier — after ``drain`` every pushed
        step has verifiably completed on device."""
        self._fetch_pending()
        return list(self._losses)

    @property
    def losses(self) -> list[float]:
        """Losses fetched so far (excludes in-flight steps)."""
        return list(self._losses)

    @property
    def inflight(self) -> int:
        return len(self._pending)


__all__ = ["LossWindow", "device_fetch"]
