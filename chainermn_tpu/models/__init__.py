from chainermn_tpu.models.mlp import MLP
from chainermn_tpu.models.resnet import (
    AlexNet,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from chainermn_tpu.models.transformer import (
    TransformerBlock,
    TransformerLM,
    generate,
    init_kv_caches,
    init_paged_kv_caches,
)
from chainermn_tpu.models.vision import GoogLeNet, InceptionBlock, VGG16

__all__ = [
    "MLP",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "AlexNet",
    "GoogLeNet",
    "InceptionBlock",
    "VGG16",
    "TransformerBlock",
    "TransformerLM",
    "generate",
    "init_kv_caches",
    "init_paged_kv_caches",
]
