"""GoogLeNet (Inception v1) and VGG16 — the rest of the reference's
ImageNet example model zoo.

Parity target: ``[U] examples/imagenet/models/`` (SURVEY.md S2.15 —
unverified cite: the reference ships resnet50, alex, googlenet example
models). Fresh flax implementations, TPU conventions throughout: NHWC,
bfloat16 compute with float32 params, logits head in float32.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class InceptionBlock(nn.Module):
    """Four-branch Inception v1 block: 1x1 / 1x1->3x3 / 1x1->5x5 /
    maxpool->1x1, concatenated on the channel axis."""

    b1: int          # 1x1 branch channels
    b3_reduce: int   # 3x3 branch bottleneck
    b3: int
    b5_reduce: int   # 5x5 branch bottleneck
    b5: int
    pool_proj: int
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dt = self.compute_dtype
        conv = lambda f, k, name: nn.Conv(f, k, padding="SAME", dtype=dt, name=name)
        y1 = nn.relu(conv(self.b1, (1, 1), "b1")(x))
        y3 = nn.relu(conv(self.b3_reduce, (1, 1), "b3_reduce")(x))
        y3 = nn.relu(conv(self.b3, (3, 3), "b3")(y3))
        y5 = nn.relu(conv(self.b5_reduce, (1, 1), "b5_reduce")(x))
        y5 = nn.relu(conv(self.b5, (5, 5), "b5")(y5))
        yp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        yp = nn.relu(conv(self.pool_proj, (1, 1), "pool_proj")(yp))
        return jnp.concatenate([y1, y3, y5, yp], axis=-1)


# (b1, b3_reduce, b3, b5_reduce, b5, pool_proj) per block, grouped by stage
_INCEPTION_CFG = [
    [(64, 96, 128, 16, 32, 32), (128, 128, 192, 32, 96, 64)],            # 3a-3b
    [(192, 96, 208, 16, 48, 64), (160, 112, 224, 24, 64, 64),            # 4a-4e
     (128, 128, 256, 24, 64, 64), (112, 144, 288, 32, 64, 64),
     (256, 160, 320, 32, 128, 128)],
    [(256, 160, 320, 32, 128, 128), (384, 192, 384, 48, 128, 128)],      # 5a-5b
]


class GoogLeNet(nn.Module):
    """Inception v1 main tower (the era's auxiliary classifiers are a
    training-schedule artifact, superseded by BN; omitted like modern
    reimplementations do)."""

    num_classes: int = 1000
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no normalization layers in the v1 tower
        dt = self.compute_dtype
        x = x.astype(dt)
        x = nn.relu(nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME",
                            dtype=dt, name="stem1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.Conv(64, (1, 1), dtype=dt, name="stem2_reduce")(x))
        x = nn.relu(nn.Conv(192, (3, 3), padding="SAME", dtype=dt,
                            name="stem2")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, blocks in enumerate(_INCEPTION_CFG):
            if stage > 0:
                x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for cfg in blocks:
                x = InceptionBlock(*cfg, compute_dtype=dt)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


class VGG16(nn.Module):
    """VGG-16 (configuration D)."""

    num_classes: int = 1000
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        dt = self.compute_dtype
        x = x.astype(dt)
        for stage, (filters, reps) in enumerate(
            [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        ):
            for r in range(reps):
                x = nn.relu(nn.Conv(filters, (3, 3), padding="SAME", dtype=dt,
                                    name=f"conv{stage + 1}_{r + 1}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=dt)(x))
        x = nn.relu(nn.Dense(4096, dtype=dt)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
