"""MLP — the reference's MNIST workload model.

Parity target: the 784-[units]-[units]-10 MLP in
``[U] examples/mnist/train_mnist.py`` (SURVEY.md S2.15 — unverified cite).
TPU notes: compute in bfloat16 by default (params stay f32; casts fuse into
the matmuls on the MXU), gelu instead of the reference era's relu is NOT used
— relu kept for workload parity.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.compute_dtype)
        x = nn.relu(nn.Dense(self.n_units, dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Dense(self.n_units, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.n_out, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)  # logits in f32 for a stable softmax
