"""ResNet family — the reference's ImageNet benchmark workload.

Parity target: ``[U] examples/imagenet/models/resnet50.py`` (SURVEY.md S2.15
— unverified cite; the reference also ships alex/googlenet example models).
This is a fresh flax implementation tuned for TPU:

- NHWC layout (TPU-native), bfloat16 compute / float32 params & BN stats:
  casts fuse into the convs on the MXU, BN accumulates in f32;
- ``norm`` is an injected factory, so multi-node sync-BN is
  ``functools.partial(MultiNodeBatchNormalization, communicator=comm)``
  instead of a post-hoc module walk (the walker in links/ still exists for
  field-declared BN, matching the reference's ``create_mnbn_model``);
- v1.5 downsampling (stride on the 3x3, not the 1x1) — the variant every
  modern ImageNet ResNet-50 baseline means.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="downsample",
            )(x)
            residual = self.norm(name="downsample_norm")(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = nn.relu(self.norm()(y))
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="downsample",
            )(x)
            residual = self.norm(name="downsample_norm")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    compute_dtype: jnp.dtype = jnp.bfloat16
    norm: Callable | None = None  # factory; None -> plain BatchNorm
    # "conv7": the classic 7x7/2 stem. "space_to_depth": rearrange the input
    # 2x2 -> 4x channels first and use a 4x4/1 conv — the MXU-friendly stem
    # (3 input channels starve the 128-wide systolic array; 12 channels with
    # a denser kernel do the same receptive-field work at far higher
    # utilization; the standard TPU ResNet trick from MLPerf submissions).
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.compute_dtype, padding="SAME"
        )
        if self.norm is not None:
            norm = functools.partial(self.norm, use_running_average=not train)
        else:
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train,
                momentum=0.9, epsilon=1e-5, dtype=self.compute_dtype,
            )
        x = x.astype(self.compute_dtype)
        if self.stem == "space_to_depth":
            n, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(f"space_to_depth stem needs even H/W, got {(h, w)}")
            # NHWC 2x2 space-to-depth: (N, H/2, W/2, 4C)
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
            # 4x4/1 on the half-res input covers the 7x7/2 stem's receptive
            # field (an 8x8 window at original resolution, stride 2)
            x = conv(self.width, (4, 4), strides=(1, 1), name="stem_conv")(x)
        elif self.stem == "conv7":
            x = conv(self.width, (7, 7), strides=(2, 2), name="stem_conv")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = nn.relu(norm(name="stem_norm")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                x = self.block(
                    filters=self.width * 2**i,
                    strides=2 if i > 0 and j == 0 else 1,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3], block=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3], block=BottleneckBlock)


class AlexNet(nn.Module):
    """Parity with the reference's examples/imagenet ``alex`` model (small,
    era-appropriate; useful as a cheap smoke workload)."""

    num_classes: int = 1000
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = self.compute_dtype
        x = x.astype(dt)
        x = nn.relu(nn.Conv(64, (11, 11), strides=(4, 4), dtype=dt)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(192, (5, 5), dtype=dt)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), dtype=dt)(x))
        x = nn.relu(nn.Conv(256, (3, 3), dtype=dt)(x))
        x = nn.relu(nn.Conv(256, (3, 3), dtype=dt)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=dt)(x))
        x = nn.relu(nn.Dense(4096, dtype=dt)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
