"""Transformer LM — the long-context flagship family.

No counterpart in the reference (it predates attention; SURVEY.md S2.16
marks SP/CP absent) — this is the TPU-first extension workload that
exercises sequence parallelism end to end. Design notes:

- layout ``[batch, seq, heads, head_dim]``; params f32, compute bf16 by
  default (casts fuse into the MXU matmuls);
- attention is pluggable (``'full' | 'ring' | 'zigzag' | 'ulysses' |
  'flash'`` from :mod:`chainermn_tpu.parallel.sequence`) so the same module
  runs single-chip or sequence-sharded inside ``comm.shard_map`` with the
  sequence axis in the batch ``PartitionSpec``;
- static shapes, ``nn.scan``-free explicit layer stack (layer count is a
  Python constant — XLA sees a straight-line program it can pipeline).
"""

from __future__ import annotations

from typing import Optional

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.parallel.moe import ExpertParallelMLP
from chainermn_tpu.parallel.sequence import sequence_parallel_attention


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    attention: str = "full"
    sequence_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    # moe_experts > 0 replaces this block's dense FFN with an expert-parallel
    # routed MLP over ``moe_axis`` (see parallel.moe); the block THEN returns
    # ``(x, aux_loss)`` instead of ``x`` — dense blocks keep the original
    # single-array contract so existing callers are unaffected.
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    # 'ep' = shard_map ExpertParallelMLP (explicit all_to_all; needs
    # moe_axis bound); 'gshard' = einsum-dispatch GShardMoE for plain-jit
    # GSPMD execution (expert stacks shardable at rest; see parallel/gspmd)
    moe_impl: str = "ep"
    # tensor_axis set -> Megatron-style block: head-sharded attention +
    # column/row FFN from parallel.tensor, one psum each. Train with the
    # global-objective pattern (tensor.py docstring), NOT the pcast/varying
    # gradient pattern of the dense blocks.
    tensor_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, pos_offset=0, kv_cache=None):
        dt = self.compute_dtype
        d_head = self.d_model // self.n_heads
        if kv_cache is not None:
            if self.sequence_axis is not None:
                raise ValueError(
                    "kv_cache decoding does not support sequence-sharded "
                    "blocks — rebuild with sequence_axis=None for inference"
                )
            if self.moe_experts and self.moe_impl != "gshard":
                raise ValueError(
                    "kv_cache decoding supports MoE only via "
                    "moe_impl='gshard' (plain-jit dispatch); the shard_map "
                    "'ep' implementation needs an axis context the decode "
                    "loop does not bind"
                )

        h = nn.LayerNorm(dtype=dt)(x)
        if self.tensor_axis is not None:
            if self.moe_experts:
                # guard here too (not only in TransformerLM): the TP branch
                # would otherwise silently train a dense FFN instead of the
                # experts AND return a bare array where the MoE contract
                # promises (x, aux_loss)
                raise ValueError(
                    "tensor_axis and moe_experts are mutually exclusive "
                    "on a TransformerBlock"
                )
            from chainermn_tpu.parallel.tensor import (
                TensorParallelAttention,
                TensorParallelMLP,
            )

            attn_out = TensorParallelAttention(
                d_model=self.d_model, n_heads=self.n_heads,
                axis_name=self.tensor_axis, causal=True,
                attention=self.attention, sequence_axis=self.sequence_axis,
                compute_dtype=dt, name="attn",
            )(h, pos_offset=pos_offset, kv_cache=kv_cache)
            if kv_cache is not None:
                attn_out, new_cache = attn_out
            x = x + attn_out
            h = nn.LayerNorm(dtype=dt)(x)
            x = x + TensorParallelMLP(
                d_model=self.d_model, d_ff=self.d_ff,
                axis_name=self.tensor_axis, compute_dtype=dt, name="mlp",
            )(h)
            return (x, new_cache) if kv_cache is not None else x

        qkv = nn.DenseGeneral((3, self.n_heads, d_head), dtype=dt, name="qkv")(h)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if kv_cache is not None:
            from chainermn_tpu.parallel.sequence import update_cache_and_attend

            o, new_cache = update_cache_and_attend(kv_cache, q, k, v,
                                                   pos_offset)
        else:
            attn_fn = sequence_parallel_attention(
                self.attention, self.sequence_axis, causal=True
            )
            o = attn_fn(q, k, v)
        x = x + nn.DenseGeneral(self.d_model, axis=(-2, -1), dtype=dt, name="proj")(o)

        h = nn.LayerNorm(dtype=dt)(x)
        if self.moe_experts:
            if self.moe_impl not in ("ep", "gshard"):
                raise ValueError(
                    f"moe_impl must be 'ep' or 'gshard', got "
                    f"{self.moe_impl!r}"
                )
            if self.moe_impl == "gshard":
                from chainermn_tpu.parallel.moe import GShardMoE

                y, aux = GShardMoE(
                    n_experts=self.moe_experts, d_model=self.d_model,
                    d_ff=self.d_ff,
                    capacity_factor=self.moe_capacity_factor,
                    top_k=self.moe_top_k,
                    compute_dtype=dt, name="moe",
                )(h)
            else:
                y, aux = ExpertParallelMLP(
                    n_experts=self.moe_experts, d_model=self.d_model,
                    d_ff=self.d_ff, axis_name=self.moe_axis,
                    capacity_factor=self.moe_capacity_factor,
                    top_k=self.moe_top_k,
                    compute_dtype=dt, name="moe",
                )(h)
            if kv_cache is not None:
                # decode: the cache replaces the aux loss in the contract
                # (inference adds no balance objective)
                return x + y, new_cache
            return x + y, aux
        h = nn.Dense(self.d_ff, dtype=dt)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, dtype=dt)(h)
        return (x, new_cache) if kv_cache is not None else x


class TransformerLM(nn.Module):
    """Decoder-only LM. ``__call__(tokens[B, T_local], pos_offset)`` ->
    logits ``[B, T_local, vocab]``; when sequence-sharded, ``pos_offset`` is
    each shard's global position base (pass ``axis_index * T_local`` inside
    the traced step) — EXCEPT under ``attention='zigzag'``, whose shards are
    not contiguous: pass the full ``[T_local]`` position vector from
    :func:`~chainermn_tpu.parallel.sequence.zigzag_positions` instead
    (``training._shard_positions`` picks the right form automatically)."""

    vocab_size: int
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: Optional[int] = None
    max_len: int = 65536
    attention: str = "full"
    sequence_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    # MoE: every ``moe_every``-th block routes its FFN over ``moe_axis``
    # experts (0 = dense everywhere). Train with return_aux=True and add
    # the aux loss (jit_lm_train_step does this automatically).
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1  # 1 = Switch routing, 2 = GShard top-2
    # 'ep': shard_map ExpertParallelMLP over moe_axis (explicit all_to_all).
    # 'gshard': einsum-dispatch GShardMoE for the plain-jit GSPMD step
    # (parallel/gspmd) — expert stacks shard at rest, no moe_axis needed.
    moe_impl: str = "ep"
    # Megatron-style tensor parallelism: heads + FFN width sharded over this
    # mesh axis in every block (embeddings and lm_head stay replicated).
    # Train with the global-objective pattern (parallel/tensor.py docstring).
    tensor_axis: Optional[str] = None
    # With tensor_axis: shard the LM head over the vocab too. __call__ then
    # returns LOCAL logits [B, T, vocab/n] (rank r's contiguous vocab slice)
    # — full [B, T, vocab] logits are never materialized. Train against
    # parallel.tensor.vocab_parallel_cross_entropy (jit_lm_train_step does
    # this automatically); for inference, all_gather the last axis.
    vocab_parallel_head: bool = False
    # Rematerialize each block's forward in the backward pass
    # (jax.checkpoint via nn.remat): stored-for-backward activations drop
    # from ~12 tensors/block to the block BOUNDARY only, trading ~1/3 more
    # forward FLOPs for O(n_layers * B*T*d) less HBM — the standard TPU
    # memory lever for long context / large token batches (e.g. the
    # 220M-param bench model at T=2048 B=32 stores ~18 GB without remat:
    # past a 16 GB v5e chip; with it, well inside). Training only —
    # kv_caches decode has no backward and ignores it.
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_aux: bool = False,
                 kv_caches=None, return_hidden: bool = False):
        if self.tensor_axis is not None and self.moe_experts:
            raise ValueError(
                "tensor_axis and moe_experts are mutually exclusive: the MoE "
                "blocks' expert axis and the TP axis would need a combined "
                "gradient pattern this model does not define"
            )
        if self.vocab_parallel_head and self.tensor_axis is None:
            raise ValueError("vocab_parallel_head needs tensor_axis")
        if kv_caches is not None:
            if self.sequence_axis is not None:
                raise ValueError(
                    "kv_caches decoding does not support sequence-sharded "
                    "models — rebuild with sequence_axis=None for inference"
                )
            if self.moe_experts and self.moe_impl != "gshard":
                raise ValueError(
                    "kv_caches decoding supports MoE only via "
                    "moe_impl='gshard' — rebuild the model with "
                    "moe_impl='gshard' for inference (same params: the "
                    "expert stacks are identical)"
                )
        d_ff = self.d_ff or 4 * self.d_model
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        # pos_offset: scalar base (contiguous shard), a [T_local] vector of
        # explicit global positions (zigzag layout — each shard holds one
        # early and one late chunk, so its positions are not contiguous),
        # OR a [B, T] matrix of per-SEQUENCE positions (continuous-batching
        # decode: every cache slot sits at its own depth, so one call
        # advances all slots with per-row position bases).
        if jnp.ndim(pos_offset) == 0:
            pos = pos_offset + jnp.arange(tokens.shape[1])
        else:
            pos = pos_offset
        pe = nn.Embed(self.max_len, self.d_model,
                      dtype=self.compute_dtype, name="pos_embed")(pos)
        x = x + (pe if jnp.ndim(pos_offset) == 2 else pe[None])
        # blocks only consume positions on the cache path, where each batch
        # row needs its scalar base: column 0 of the per-sequence matrix
        # (decode steps are contiguous within one call)
        block_pos = pos_offset[:, 0] if jnp.ndim(pos_offset) == 2 else pos_offset
        aux_total = jnp.float32(0.0)
        new_caches = []
        # nn.remat wraps the block's apply in jax.checkpoint; decode
        # (kv_caches) has no backward to save for, so skip the wrapper and
        # its prevent_cse pessimization there.
        block_cls = (nn.remat(TransformerBlock)
                     if self.remat and kv_caches is None else TransformerBlock)
        for i in range(self.n_layers):
            is_moe = self.moe_experts and (i % self.moe_every == self.moe_every - 1)
            block = block_cls(
                self.d_model, self.n_heads, d_ff,
                attention=self.attention, sequence_axis=self.sequence_axis,
                compute_dtype=self.compute_dtype,
                moe_experts=self.moe_experts if is_moe else 0,
                moe_axis=self.moe_axis,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_top_k=self.moe_top_k,
                moe_impl=self.moe_impl,
                tensor_axis=self.tensor_axis,
                name=f"block_{i}",
            )
            if kv_caches is not None:
                x, c = block(x, block_pos, kv_cache=kv_caches[i])
                new_caches.append(c)
                continue
            out = block(x, block_pos)
            x, aux = out if is_moe else (out, 0.0)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        if return_hidden:
            # pre-head hidden states for a fused/chunked head+loss (see
            # ops.losses.chunked_softmax_cross_entropy): the [B, T, vocab]
            # f32 logits are the train step's largest tensor pair and this
            # path never builds them
            if self.vocab_parallel_head:
                raise ValueError(
                    "return_hidden composes with the replicated lm_head "
                    "(the fused CE applies it itself); the vocab-parallel "
                    "head already avoids full logits — use "
                    "vocab_parallel_cross_entropy instead"
                )
            if kv_caches is not None:
                raise ValueError("return_hidden is a training-loss path; "
                                 "decode wants logits")
            return (x, aux_total) if return_aux else x
        if self.vocab_parallel_head:
            from chainermn_tpu.parallel.tensor import ColumnParallelDense

            logits = ColumnParallelDense(
                self.vocab_size, self.tensor_axis,
                compute_dtype=self.compute_dtype, name="lm_head",
            )(x)
        else:
            logits = nn.Dense(self.vocab_size, dtype=self.compute_dtype,
                              name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if kv_caches is not None:
            return logits, new_caches
        if return_aux:
            return logits, aux_total
        return logits


def init_kv_caches(model: TransformerLM, batch: int, cache_len: int,
                   *, local_heads: Optional[int] = None):
    """Zeroed per-layer KV cache buffers for :meth:`TransformerLM.__call__`'s
    ``kv_caches`` argument: a list of ``{'k','v'}`` dicts shaped
    ``[batch, cache_len, heads, d_head]`` in the model's compute dtype.
    Tensor-parallel decode (inside ``shard_map``) passes
    ``local_heads=n_heads // tp_size`` for the per-rank buffers."""
    h = local_heads or model.n_heads
    dh = model.d_model // model.n_heads
    z = lambda: jnp.zeros((batch, cache_len, h, dh), model.compute_dtype)
    return [{"k": z(), "v": z()} for _ in range(model.n_layers)]


def init_paged_kv_caches(model: TransformerLM, n_blocks: int,
                         block_size: int, *,
                         local_heads: Optional[int] = None,
                         quant: str = "none"):
    """Zeroed per-layer **paged** KV block stores: a list of ``{'k','v'}``
    dicts shaped ``[n_blocks, block_size, heads, d_head]`` — one pool of
    fixed-size token blocks shared by every sequence, addressed through a
    ``[B, max_blocks]`` block table the caller threads into each layer
    dict as its ``'table'`` entry (see
    :func:`~chainermn_tpu.parallel.sequence.paged_update_cache_and_attend`).
    ``quant='int8'`` stores int8 rows plus per-row-per-head f32
    ``'k_scale'``/``'v_scale'`` arrays (``x ≈ x_q * scale`` — ~2x less KV
    memory per resident token; dequantized inside the attention gather).
    Tensor-parallel decode passes ``local_heads=n_heads // tp_size``."""
    if quant not in ("none", "int8"):
        raise ValueError(f"quant must be 'none' or 'int8', got {quant!r}")
    h = local_heads or model.n_heads
    dh = model.d_model // model.n_heads
    dt = jnp.int8 if quant == "int8" else model.compute_dtype

    def layer():
        d = {"k": jnp.zeros((n_blocks, block_size, h, dh), dt),
             "v": jnp.zeros((n_blocks, block_size, h, dh), dt)}
        if quant == "int8":
            d["k_scale"] = jnp.zeros((n_blocks, block_size, h), jnp.float32)
            d["v_scale"] = jnp.zeros((n_blocks, block_size, h), jnp.float32)
        return d

    return [layer() for _ in range(model.n_layers)]


def generate(
    model: TransformerLM,
    params,
    prompt,
    n_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng=None,
    use_cache: bool = True,
    comm=None,
    eos_id: Optional[int] = None,
):
    """Autoregressive decoding for :class:`TransformerLM` (inference utility
    beyond the reference, which has no generation loop; completes the LM
    family's user surface).

    ``prompt [B, T0]`` ints; returns ``[B, T0 + n_tokens]``. ``temperature=0``
    is greedy (deterministic); otherwise softmax sampling at the given
    temperature with ``rng``, optionally truncated to the ``top_k`` most
    probable tokens and/or the smallest set whose cumulative probability
    reaches ``top_p`` (nucleus sampling; both filters compose, top-k
    first). Compiled per (model, shapes, sampler config) — repeat calls
    with the same shapes reuse the compile.

    ``use_cache=True`` (default): one full prefill over the prompt fills a
    static ``[B, T0+n_tokens]`` KV cache per layer, then each step runs ONE
    token through the model against the cache — O(T*d) per token. The
    greedy token sequence is identical to the cacheless path (pinned in
    tests). ``use_cache=False`` keeps the round-3 re-forward-the-buffer
    loop (O(T^2) attention per token) as the independent reference.

    Tensor-parallel models (``tensor_axis``, incl. ``vocab_parallel_head``):
    pass ``comm=`` (the communicator whose mesh axis the model was built
    on) — the whole decode loop then runs inside its ``shard_map`` with
    per-rank local-head caches; a vocab-parallel head's local logits are
    ``all_gather``\\ ed (one ``[B, vocab]`` row per step) for sampling.

    MoE models decode with ``moe_impl='gshard'`` (plain-jit einsum
    dispatch; an ``'ep'``-trained model rebuilds as gshard on the SAME
    params — the expert stacks are identical). Use the cached path: the
    cacheless reference routes the zero-padded buffer through the gate,
    so with a tight ``capacity_factor`` padding competes with real tokens
    for expert capacity and the two paths can diverge (a warning fires).
    Sequence-sharded models still need a dense rebuild for inference.

    GSPMD at-rest layouts decode as-is: the decode loop is plain jit, so
    params placed by :func:`~chainermn_tpu.parallel.gspmd.megatron_shard`
    run under the partitioner, which inserts the gathers the Megatron
    layout needs (pinned by ``test_generate_with_megatron_layout``).

    ``eos_id``: early-stop token. Once a sequence samples it, every later
    position in that row is written as pad (0) instead of the sampled
    token — the row stops contributing changed tokens while the batch
    keeps its static shape (pure ``jnp.where`` masking, no recompile, no
    shape change). The decode loop still runs ``n_tokens`` steps (finished
    rows feed pad through the model), so cached/cacheless/TP parity is
    preserved; per-request wall-clock retirement on EOS is the serving
    engine's job (:mod:`chainermn_tpu.serving`), whose slot-retirement
    contract depends on exactly this masking.
    """
    if model.sequence_axis is not None:
        raise ValueError(
            "generate() does not support sequence-sharded models: rebuild "
            "with sequence_axis=None (attention='full') for inference"
        )
    if model.moe_experts and model.moe_impl != "gshard":
        raise ValueError(
            "generate() supports MoE only via moe_impl='gshard' — rebuild "
            "the model with moe_impl='gshard' for inference (same params)"
        )
    if temperature and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if (top_k or top_p < 1.0) and not temperature:
        raise ValueError(
            "top_k/top_p filter the sampling distribution; with "
            "temperature=0 (greedy) they have no effect — pass a "
            "temperature > 0"
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if not 0 <= top_k <= model.vocab_size:
        raise ValueError(
            f"top_k must be in [0, vocab_size={model.vocab_size}], got "
            f"{top_k} (0 disables the filter)"
        )
    if eos_id is not None:
        eos_id = int(eos_id)  # normalize for the compiled-fn cache key
        if not 0 <= eos_id < model.vocab_size:
            raise ValueError(
                f"eos_id must be in [0, vocab_size={model.vocab_size}), "
                f"got {eos_id}"
            )
    if model.moe_experts and not use_cache:
        import warnings

        warnings.warn(
            "cacheless decode of an MoE model routes the zero-padded "
            "buffer positions through the gate, so padding competes for "
            "expert capacity: tokens can differ from the cached path "
            "(which routes only real tokens) unless capacity_factor is "
            "ample. Prefer use_cache=True for MoE decoding.",
            stacklevel=2,
        )
    b, t0 = prompt.shape
    if t0 + n_tokens > model.max_len:
        raise ValueError(
            f"{t0 + n_tokens} tokens exceed max_len={model.max_len}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if model.tensor_axis is not None:
        if comm is None or not use_cache:
            raise ValueError(
                "tensor-parallel generate() needs comm= and use_cache=True "
                "(the decode loop runs inside the communicator's shard_map)"
            )
        run = _generate_tp_fn(model, int(n_tokens), float(temperature),
                              int(top_k), float(top_p), b, int(t0),
                              jnp.dtype(prompt.dtype).name, comm, eos_id)
        return run(params, prompt, rng)
    fn = _generate_cached_fn if use_cache else _generate_fn
    run = fn(model, int(n_tokens), float(temperature), int(top_k),
             float(top_p), b, int(t0), jnp.dtype(prompt.dtype).name, eos_id)
    return run(params, prompt, rng)


def _sampler(temperature, top_k=0, top_p=1.0):
    """(logits [B, V], key) -> (token [B], key); the split sequence is
    identical between the cached and cacheless paths so sampled outputs
    match too (given equal logits).

    Filters compose in the standard order: temperature scaling, then top-k
    truncation, then nucleus (top-p) truncation of what remains. Top-p
    always keeps at least the most probable token (the mask keeps entries
    whose cumulative probability BEFORE them is < p)."""

    def sample(lg, key):
        key, sub = jax.random.split(key)
        if not temperature:
            return jnp.argmax(lg, axis=-1), key
        lg = lg / temperature
        if top_k:
            kth = lax.top_k(lg, top_k)[0][..., -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        if top_p < 1.0:
            srt = jnp.sort(lg, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                             keepdims=True)
            lg = jnp.where(lg < cutoff, -jnp.inf, lg)
        return jax.random.categorical(sub, lg, axis=-1), key

    return sample


def _eos_tracker(eos_id, b):
    """(init_done, mask_fn) for EOS early-stop: ``init_done(first)`` flags
    rows whose FIRST generated token is EOS; ``mask_fn(done, nxt)`` returns
    ``(write, new_done)`` — pad (0) for already-done rows, and the done set
    grown by rows that just sampled EOS. With ``eos_id=None`` both are
    identity/always-false, compiling to nothing."""
    if eos_id is None:
        return (lambda first: jnp.zeros((b,), bool),
                lambda done, nxt: (nxt, done))

    def mask(done, nxt):
        return jnp.where(done, jnp.zeros_like(nxt), nxt), done | (nxt == eos_id)

    return (lambda first: first == eos_id), mask


@functools.lru_cache(maxsize=32)
def _generate_cached_fn(model, n_tokens, temperature, top_k, top_p, b, t0,
                        dtype_name, eos_id=None):
    """KV-cached decode: one prefill over the prompt, then one token per
    step against the static cache. Compiled per (model, shape, sampler)
    key. NOTE the lru_cache retains compiled programs closed over param
    SHAPES only (params are arguments), but each entry still holds a
    full decode executable — bounded by maxsize."""
    total = t0 + n_tokens
    dtype = jnp.dtype(dtype_name)
    sample = _sampler(temperature, top_k, top_p)
    init_done, eos_mask = _eos_tracker(eos_id, b)

    @jax.jit
    def run(params, prompt, rng):
        caches = init_kv_caches(model, b, total)
        buf = jnp.zeros((b, total), dtype).at[:, :t0].set(prompt)
        logits, caches = model.apply(params, prompt, 0, kv_caches=caches)
        nxt, key = sample(logits[:, -1], rng)
        buf = buf.at[:, t0].set(nxt.astype(dtype))
        done = init_done(nxt)

        def step(carry, i):
            buf, caches, key, done = carry
            tok = lax.dynamic_slice_in_dim(buf, i, 1, axis=1)
            lg, caches = model.apply(params, tok, i, kv_caches=caches)
            nxt, key = sample(lg[:, 0], key)
            write, done = eos_mask(done, nxt)
            buf = lax.dynamic_update_slice(
                buf, write[:, None].astype(dtype), (0, i + 1))
            return (buf, caches, key, done), None

        (buf, _, _, _), _ = lax.scan(
            step, (buf, caches, key, done), jnp.arange(t0, total - 1))
        return buf

    return run


@functools.lru_cache(maxsize=8)
def _generate_tp_fn(model, n_tokens, temperature, top_k, top_p, b, t0,
                    dtype_name, comm, eos_id=None):
    """Tensor-parallel cached decode: the same loop as
    :func:`_generate_cached_fn` traced INSIDE ``comm.shard_map`` — per-rank
    caches hold the rank's local heads, and a vocab-parallel head's local
    logits are all_gather'ed (one [B, vocab] row per step) before sampling.
    Keyed on the communicator by identity — reuse the same comm object to
    reuse the compile."""
    from jax.sharding import PartitionSpec as P

    total = t0 + n_tokens
    dtype = jnp.dtype(dtype_name)
    sample = _sampler(temperature, top_k, top_p)
    axis = model.tensor_axis
    n_tp = comm.mesh.shape[axis]
    if model.n_heads % n_tp:
        raise ValueError(
            f"n_heads {model.n_heads} not divisible by tensor-axis size {n_tp}"
        )
    local_h = model.n_heads // n_tp
    init_done, eos_mask = _eos_tracker(eos_id, b)

    def body(params, prompt, rng):
        def last_logits(tokens, offset, caches):
            """Logits at the LAST input position, [B, vocab] — sliced
            before the vocab all_gather so prefill ships one row per batch
            element, not [B, T0, vocab]."""
            lg, caches = model.apply(params, tokens, offset,
                                     kv_caches=caches)
            lg = lg[:, -1]
            if model.vocab_parallel_head:
                lg = lax.all_gather(lg, axis, axis=-1, tiled=True)
            return lg, caches

        caches = init_kv_caches(model, b, total, local_heads=local_h)
        buf = jnp.zeros((b, total), dtype).at[:, :t0].set(prompt)
        logits, caches = last_logits(prompt, 0, caches)
        nxt, key = sample(logits, rng)
        buf = buf.at[:, t0].set(nxt.astype(dtype))
        done = init_done(nxt)

        def step(carry, i):
            buf, caches, key, done = carry
            tok = lax.dynamic_slice_in_dim(buf, i, 1, axis=1)
            lg, caches = last_logits(tok, i, caches)
            nxt, key = sample(lg, key)
            write, done = eos_mask(done, nxt)
            buf = lax.dynamic_update_slice(
                buf, write[:, None].astype(dtype), (0, i + 1))
            return (buf, caches, key, done), None

        (buf, _, _, _), _ = lax.scan(
            step, (buf, caches, key, done), jnp.arange(t0, total - 1))
        return buf

    return jax.jit(comm.shard_map(
        body, in_specs=(P(), P(), P()), out_specs=P(), check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def _generate_fn(model, n_tokens, temperature, top_k, top_p, b, t0,
                 dtype_name, eos_id=None):
    """The cacheless reference decode (round-3 behavior): re-runs the full
    forward over the whole buffer per token — O(T^2) attention x T tokens.
    Kept as the independent correctness reference for the cached path.
    One compiled decode program per (model, shape, sampler) key —
    flax modules are frozen/hashable, so they key an lru_cache directly."""
    total = t0 + n_tokens
    dtype = jnp.dtype(dtype_name)
    sample = _sampler(temperature, top_k, top_p)
    _, eos_mask = _eos_tracker(eos_id, b)

    @jax.jit
    def run(params, prompt, rng):
        buf = jnp.zeros((b, total), dtype).at[:, :t0].set(prompt)
        done = jnp.zeros((b,), bool)  # every token is sampled inside the scan

        def step(carry, i):
            buf, key, done = carry
            logits = model.apply(params, buf)      # [B, total, V]
            # the token at position i is predicted from the logits at i-1
            nxt_logits = lax.dynamic_slice_in_dim(logits, i - 1, 1, axis=1)[:, 0]
            nxt, key = sample(nxt_logits, key)
            write, done = eos_mask(done, nxt)
            buf = buf.at[:, i].set(write.astype(buf.dtype))
            return (buf, key, done), None

        (out, _, _), _ = lax.scan(step, (buf, rng, done), jnp.arange(t0, total))
        return out

    return run
