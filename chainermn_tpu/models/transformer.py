"""Transformer LM — the long-context flagship family.

No counterpart in the reference (it predates attention; SURVEY.md S2.16
marks SP/CP absent) — this is the TPU-first extension workload that
exercises sequence parallelism end to end. Design notes:

- layout ``[batch, seq, heads, head_dim]``; params f32, compute bf16 by
  default (casts fuse into the MXU matmuls);
- attention is pluggable (``'full' | 'ring' | 'zigzag' | 'ulysses' |
  'flash'`` from :mod:`chainermn_tpu.parallel.sequence`) so the same module
  runs single-chip or sequence-sharded inside ``comm.shard_map`` with the
  sequence axis in the batch ``PartitionSpec``;
- static shapes, ``nn.scan``-free explicit layer stack (layer count is a
  Python constant — XLA sees a straight-line program it can pipeline).
"""

from __future__ import annotations

from typing import Optional

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.parallel.moe import ExpertParallelMLP
from chainermn_tpu.parallel.sequence import sequence_parallel_attention


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    attention: str = "full"
    sequence_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    # moe_experts > 0 replaces this block's dense FFN with an expert-parallel
    # routed MLP over ``moe_axis`` (see parallel.moe); the block THEN returns
    # ``(x, aux_loss)`` instead of ``x`` — dense blocks keep the original
    # single-array contract so existing callers are unaffected.
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    # tensor_axis set -> Megatron-style block: head-sharded attention +
    # column/row FFN from parallel.tensor, one psum each. Train with the
    # global-objective pattern (tensor.py docstring), NOT the pcast/varying
    # gradient pattern of the dense blocks.
    tensor_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, pos_offset=0):
        dt = self.compute_dtype
        d_head = self.d_model // self.n_heads

        h = nn.LayerNorm(dtype=dt)(x)
        if self.tensor_axis is not None:
            if self.moe_experts:
                # guard here too (not only in TransformerLM): the TP branch
                # would otherwise silently train a dense FFN instead of the
                # experts AND return a bare array where the MoE contract
                # promises (x, aux_loss)
                raise ValueError(
                    "tensor_axis and moe_experts are mutually exclusive "
                    "on a TransformerBlock"
                )
            from chainermn_tpu.parallel.tensor import (
                TensorParallelAttention,
                TensorParallelMLP,
            )

            x = x + TensorParallelAttention(
                d_model=self.d_model, n_heads=self.n_heads,
                axis_name=self.tensor_axis, causal=True,
                attention=self.attention, sequence_axis=self.sequence_axis,
                compute_dtype=dt, name="attn",
            )(h)
            h = nn.LayerNorm(dtype=dt)(x)
            return x + TensorParallelMLP(
                d_model=self.d_model, d_ff=self.d_ff,
                axis_name=self.tensor_axis, compute_dtype=dt, name="mlp",
            )(h)

        attn_fn = sequence_parallel_attention(
            self.attention, self.sequence_axis, causal=True
        )
        qkv = nn.DenseGeneral((3, self.n_heads, d_head), dtype=dt, name="qkv")(h)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        o = attn_fn(q, k, v)
        x = x + nn.DenseGeneral(self.d_model, axis=(-2, -1), dtype=dt, name="proj")(o)

        h = nn.LayerNorm(dtype=dt)(x)
        if self.moe_experts:
            y, aux = ExpertParallelMLP(
                n_experts=self.moe_experts, d_model=self.d_model,
                d_ff=self.d_ff, axis_name=self.moe_axis,
                capacity_factor=self.moe_capacity_factor,
                compute_dtype=dt, name="moe",
            )(h)
            return x + y, aux
        h = nn.Dense(self.d_ff, dtype=dt)(h)
        h = nn.gelu(h)
        return x + nn.Dense(self.d_model, dtype=dt)(h)


class TransformerLM(nn.Module):
    """Decoder-only LM. ``__call__(tokens[B, T_local], pos_offset)`` ->
    logits ``[B, T_local, vocab]``; when sequence-sharded, ``pos_offset`` is
    each shard's global position base (pass ``axis_index * T_local`` inside
    the traced step) — EXCEPT under ``attention='zigzag'``, whose shards are
    not contiguous: pass the full ``[T_local]`` position vector from
    :func:`~chainermn_tpu.parallel.sequence.zigzag_positions` instead
    (``training._shard_positions`` picks the right form automatically)."""

    vocab_size: int
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: Optional[int] = None
    max_len: int = 65536
    attention: str = "full"
    sequence_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    # MoE: every ``moe_every``-th block routes its FFN over ``moe_axis``
    # experts (0 = dense everywhere). Train with return_aux=True and add
    # the aux loss (jit_lm_train_step does this automatically).
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    # Megatron-style tensor parallelism: heads + FFN width sharded over this
    # mesh axis in every block (embeddings and lm_head stay replicated).
    # Train with the global-objective pattern (parallel/tensor.py docstring).
    tensor_axis: Optional[str] = None
    # With tensor_axis: shard the LM head over the vocab too. __call__ then
    # returns LOCAL logits [B, T, vocab/n] (rank r's contiguous vocab slice)
    # — full [B, T, vocab] logits are never materialized. Train against
    # parallel.tensor.vocab_parallel_cross_entropy (jit_lm_train_step does
    # this automatically); for inference, all_gather the last axis.
    vocab_parallel_head: bool = False

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_aux: bool = False):
        if self.tensor_axis is not None and self.moe_experts:
            raise ValueError(
                "tensor_axis and moe_experts are mutually exclusive: the MoE "
                "blocks' expert axis and the TP axis would need a combined "
                "gradient pattern this model does not define"
            )
        if self.vocab_parallel_head and self.tensor_axis is None:
            raise ValueError("vocab_parallel_head needs tensor_axis")
        d_ff = self.d_ff or 4 * self.d_model
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        # pos_offset: scalar base (contiguous shard) OR a [T_local] vector of
        # explicit global positions (zigzag layout — each shard holds one
        # early and one late chunk, so its positions are not contiguous)
        if jnp.ndim(pos_offset) == 0:
            pos = pos_offset + jnp.arange(tokens.shape[1])
        else:
            pos = pos_offset
        x = x + nn.Embed(self.max_len, self.d_model,
                         dtype=self.compute_dtype, name="pos_embed")(pos)[None]
        aux_total = jnp.float32(0.0)
        for i in range(self.n_layers):
            is_moe = self.moe_experts and (i % self.moe_every == self.moe_every - 1)
            out = TransformerBlock(
                self.d_model, self.n_heads, d_ff,
                attention=self.attention, sequence_axis=self.sequence_axis,
                compute_dtype=self.compute_dtype,
                moe_experts=self.moe_experts if is_moe else 0,
                moe_axis=self.moe_axis,
                moe_capacity_factor=self.moe_capacity_factor,
                tensor_axis=self.tensor_axis,
                name=f"block_{i}",
            )(x)
            x, aux = out if is_moe else (out, 0.0)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        if self.vocab_parallel_head:
            from chainermn_tpu.parallel.tensor import ColumnParallelDense

            logits = ColumnParallelDense(
                self.vocab_size, self.tensor_axis,
                compute_dtype=self.compute_dtype, name="lm_head",
            )(x)
        else:
            logits = nn.Dense(self.vocab_size, dtype=self.compute_dtype,
                              name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if return_aux:
            return logits, aux_total
        return logits


def generate(
    model: TransformerLM,
    params,
    prompt,
    n_tokens: int,
    *,
    temperature: float = 0.0,
    rng=None,
):
    """Autoregressive decoding for :class:`TransformerLM` (inference utility
    beyond the reference, which has no generation loop; completes the LM
    family's user surface).

    ``prompt [B, T0]`` ints; returns ``[B, T0 + n_tokens]``. ``temperature=0``
    is greedy (deterministic); otherwise softmax sampling at the given
    temperature with ``rng``. The decode loop is a jitted ``lax.scan`` over a
    fixed ``T0 + n_tokens`` buffer, cached per (model, shapes, temperature) —
    repeat calls with the same shapes reuse the compile. Each step re-runs
    the full forward on the buffer (no KV cache: simple, correct, static
    shapes); causal attention makes positions past the current length
    irrelevant to the sampled token. Single-device / replicated-params only:
    the parallel training layouts (tensor_axis, sequence_axis, moe_axis)
    trace collectives that need a mesh context — rebuild a plain model for
    inference, or run inside an equivalent shard_map.
    """
    if (model.tensor_axis is not None or model.sequence_axis is not None
            or model.moe_experts):
        raise ValueError(
            "generate() runs outside a mesh: rebuild the model without "
            "tensor_axis/sequence_axis/moe_experts (attention='full') "
            "for inference"
        )
    if temperature and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    b, t0 = prompt.shape
    if t0 + n_tokens > model.max_len:
        raise ValueError(
            f"{t0 + n_tokens} tokens exceed max_len={model.max_len}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    run = _generate_fn(model, int(n_tokens), float(temperature), b, int(t0),
                       jnp.dtype(prompt.dtype).name)
    return run(params, prompt, rng)


@functools.lru_cache(maxsize=32)
def _generate_fn(model, n_tokens, temperature, b, t0, dtype_name):
    """One compiled decode program per (model, shape, temperature) key —
    flax modules are frozen/hashable, so they key an lru_cache directly."""
    total = t0 + n_tokens
    dtype = jnp.dtype(dtype_name)

    @jax.jit
    def run(params, prompt, rng):
        buf = jnp.zeros((b, total), dtype).at[:, :t0].set(prompt)

        def step(carry, i):
            buf, key = carry
            logits = model.apply(params, buf)      # [B, total, V]
            # the token at position i is predicted from the logits at i-1
            nxt_logits = lax.dynamic_slice_in_dim(logits, i - 1, 1, axis=1)[:, 0]
            key, sub = jax.random.split(key)
            if temperature:
                nxt = jax.random.categorical(
                    sub, nxt_logits / temperature, axis=-1
                )
            else:
                nxt = jnp.argmax(nxt_logits, axis=-1)
            buf = buf.at[:, i].set(nxt.astype(buf.dtype))
            return (buf, key), None

        (out, _), _ = lax.scan(step, (buf, rng), jnp.arange(t0, total))
        return out

    return run
