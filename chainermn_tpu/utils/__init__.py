"""Small host-side utilities (no reference counterpart; the reference leans
on mpi4py/chainer for these)."""

from __future__ import annotations

import os


def apply_env_platform() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment, in-process.

    Some containers register a PJRT plugin from ``sitecustomize`` at
    interpreter startup and force their platform regardless of the env var.
    Calling this before the first backend touch makes ``JAX_PLATFORMS=cpu
    python examples/...`` (the emulated multi-device workflow) reliable.
    No-op when the variable is unset or the backend is already initialized.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already up; the env var did its job or it's too late


def ensure_batch_fits(dataset, global_batch: int, size: int = 1) -> None:
    """Fail fast when the global batch exceeds the dataset: every batch would
    be a ragged tail (which training loops skip, matching the reference's
    drop-last behavior) and zero steps would run — a silent no-op otherwise.

    ``size`` is the device count when the global batch was computed as
    per-device batch x devices (used only for the error message).
    """
    if global_batch > len(dataset):
        how = f" (= per-device batch x {size} devices)" if size > 1 else ""
        raise SystemExit(
            f"global batch {global_batch}{how} exceeds the "
            f"{len(dataset)}-sample dataset: every batch would be a ragged "
            "tail and zero training steps would run"
        )


__all__ = ["apply_env_platform", "ensure_batch_fits"]
