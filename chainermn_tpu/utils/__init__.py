"""Small host-side utilities (no reference counterpart; the reference leans
on mpi4py/chainer for these)."""

from __future__ import annotations

import os


def apply_env_platform() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment, in-process.

    Some containers register a PJRT plugin from ``sitecustomize`` at
    interpreter startup and force their platform regardless of the env var.
    Calling this before the first backend touch makes ``JAX_PLATFORMS=cpu
    python examples/...`` (the emulated multi-device workflow) reliable.
    No-op when the variable is unset or the backend is already initialized.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already up; the env var did its job or it's too late


__all__ = ["apply_env_platform"]
