"""Small host-side utilities (no reference counterpart; the reference leans
on mpi4py/chainer for these)."""

from __future__ import annotations

import os


def apply_env_platform() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment, in-process.

    Some containers register a PJRT plugin from ``sitecustomize`` at
    interpreter startup and force their platform regardless of the env var.
    Calling this before the first backend touch makes ``JAX_PLATFORMS=cpu
    python examples/...`` (the emulated multi-device workflow) reliable.
    No-op when the variable is unset or the backend is already initialized.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backend already up; the env var did its job or it's too late


def axis_size(axis_name):
    """``lax.axis_size`` across JAX generations: legacy 0.4.x lacks it —
    ``psum(1, axis)`` is the classic equivalent (and raises the same
    ``NameError`` outside a bound axis context, which callers rely on to
    detect "not inside shard_map")."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pcast_varying(x, axes):
    """``lax.pcast(x, axes, to='varying')`` across JAX generations.

    New JAX tracks per-value varying manner (vma) inside ``shard_map`` and
    needs the explicit cast wherever a replicated value enters a per-rank
    computation whose gradients must STAY per-rank (training.py's grad
    pattern, the pipeline scan carry). Legacy 0.4.x has no vma — and the
    framework runs its legacy shard_maps with ``check_rep=False`` (see
    ``mesh_communicator._shard_map``), where every value is per-rank by
    default — so the cast is the identity there.
    """
    import jax

    if hasattr(jax.lax, "pcast"):
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        return jax.lax.pcast(x, axes, to="varying")
    return x


def ensure_batch_fits(dataset, global_batch: int, size: int = 1) -> None:
    """Fail fast when the global batch exceeds the dataset: every batch would
    be a ragged tail (which training loops skip, matching the reference's
    drop-last behavior) and zero steps would run — a silent no-op otherwise.

    ``size`` is the device count when the global batch was computed as
    per-device batch x devices (used only for the error message).
    """
    if global_batch > len(dataset):
        how = f" (= per-device batch x {size} devices)" if size > 1 else ""
        raise SystemExit(
            f"global batch {global_batch}{how} exceeds the "
            f"{len(dataset)}-sample dataset: every batch would be a ragged "
            "tail and zero training steps would run"
        )


__all__ = ["apply_env_platform", "axis_size", "ensure_batch_fits",
           "pcast_varying"]
