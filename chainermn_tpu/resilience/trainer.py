"""Step-level exception boundary with checkpoint-restore recovery.

The reference's recovery story is *fail-and-restart*: the job dies, the
scheduler relaunches it, ``maybe_load`` resumes from the newest common
snapshot (SURVEY.md S2.14). :func:`resilient_fit` closes the loop
*inside* one launch as well: every training step runs inside an
exception boundary that, on failure, dumps the monitor flight recorder
(once per failure — the dump guard is shared with ``Watchdog`` and
``global_except_hook`` so layered failure paths never stutter duplicate
dumps), restores the newest common :class:`~chainermn_tpu.extensions.
checkpoint.MultiNodeCheckpointer` snapshot, and replays from there under
a bounded restore budget. Cross-launch resume falls out of the same
path: a fresh process calling :func:`resilient_fit` over the same
snapshot directory continues where the dead one stopped.

Bit-exact resume contract: a snapshot carries the full replay state —
the user ``state`` pytree (put your PRNG keys IN it; they round-trip
through the pickle like any leaf) plus the iterator's
``state_dict()`` — so the post-resume loss trajectory is identical to an
uninterrupted run. Iteration ``k``'s snapshot holds the state *after*
``k`` steps with the iterator positioned to draw batch ``k``; restore
sets the loop index back to ``k`` and the replayed steps recompute the
exact same math (``step_fn`` must be deterministic given ``(state,
batch)`` — jitted steps on a fixed backend are).

Buffer-donation note: the boundary never reuses the in-flight ``state``
after a failure (it always restores from disk), so ``step_fn`` built
with donated buffers is safe — a failed call may have consumed its
inputs, and the restore path does not care.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.trace import get_tracer
from chainermn_tpu.resilience.cutpoints import TRAINER_STEP
from chainermn_tpu.resilience.faults import inject
from chainermn_tpu.resilience.retry import RetryPolicy


class ResilientTrainer:
    """Drive ``step_fn`` for ``n_steps`` with crash recovery.

    Parameters
    ----------
    step_fn : callable
        ``step_fn(state, batch) -> state`` — pure step over an arbitrary
        ``state`` pytree (params, opt state, PRNG keys, host scalars).
    checkpointer : MultiNodeCheckpointer
        Owns snapshot naming, GC, checksum verification, and the
        cross-rank newest-common-iteration agreement.
    save_every : int
        Snapshot cadence in steps (a snapshot is also taken at iteration
        0 — before any batch — so a failure before the first periodic
        save still has a restore point; and at ``n_steps``).
    max_restores : int
        Recovery budget; the failure that exhausts it re-raises.
    retry : RetryPolicy, optional
        Wrapped around checkpoint save/load I/O (host-transient faults
        get absorbed before they count as a training failure). Default: 3
        attempts.
    dump_on_failure : bool
        Dump the flight recorder (once per failure episode) to stderr at
        the boundary.
    restore_hook : callable, optional
        ``restore_hook(state) -> state`` applied to every snapshot-loaded
        state (resume and recovery alike) before stepping. Snapshots hold
        host arrays (``jax.device_get``); a jitted ``step_fn`` whose math
        depends on input placement (sharded params/opt state on a mesh)
        needs them ``device_put`` back to the original shardings to keep
        the resumed trajectory bit-exact.
    async_save : bool
        Snapshot via ``checkpointer.save_async``: the loop blocks only on
        the ``device_get`` (the consistency point) while serialization +
        disk write + GC run on the checkpointer's writer thread. The
        snapshot CONTENT is identical to the sync path, so resume stays
        bit-exact. Recovery never races a pending write (``maybe_load``
        joins first), and :meth:`fit` closes with a ``wait_async`` so the
        final snapshot is durable — a writer failure raises there. With
        async saves the trainer-level ``retry`` only covers enqueue-time
        faults; give write-retry budget to the CHECKPOINTER
        (``MultiNodeCheckpointer(retry=...)``), which applies it on the
        writer thread.
    """

    def __init__(self, step_fn: Callable, checkpointer, *,
                 save_every: int = 10, max_restores: int = 3,
                 retry: Optional[RetryPolicy] = None,
                 dump_on_failure: bool = True,
                 restore_hook: Optional[Callable] = None,
                 async_save: bool = False) -> None:
        if save_every < 1:
            raise ValueError(f"save_every must be >= 1, got {save_every}")
        self.step_fn = step_fn
        self.checkpointer = checkpointer
        self.save_every = int(save_every)
        self.max_restores = int(max_restores)
        self.retry = retry if retry is not None else RetryPolicy(3)
        self.dump_on_failure = dump_on_failure
        self.restore_hook = restore_hook
        self.async_save = bool(async_save)
        if self.async_save and not hasattr(checkpointer, "save_async"):
            raise TypeError(
                f"async_save=True needs a checkpointer with save_async(); "
                f"{type(checkpointer).__name__} has none")
        reg = get_registry()
        self._c_failures = reg.counter("trainer_failures_total")
        self._c_restores = reg.counter("trainer_restores_total")
        self._h_mttr = reg.histogram("trainer_mttr_seconds", unit="s")
        self._events = get_event_log()
        self._tracer = get_tracer()
        # one trace per failure EPISODE (first failure -> first completed
        # post-restore step): its root span IS the MTTR interval, its
        # children attribute the recovery (flight dump, snapshot load,
        # replay). Error-marked, so sampling never drops it.
        self._episode = None

    # -- checkpoint plumbing --------------------------------------------- #

    def _save(self, state, iterator, iteration: int) -> None:
        # ambient span: with async_save the enqueue (device_get) is the
        # only critical-path cost and the step trace shows exactly it
        with self._tracer.span("checkpoint_enqueue", iteration=iteration,
                               asynchronous=self.async_save):
            snap = {"state": state, "iterator": iterator.state_dict()}
            save = (self.checkpointer.save_async if self.async_save
                    else self.checkpointer.save)
            self.retry.call(save, snap, iteration, op="checkpoint.save")
        self._events.emit("trainer_snapshot", iteration=iteration,
                          asynchronous=self.async_save)

    def _load(self):
        return self.retry.call(self.checkpointer.maybe_load,
                               op="checkpoint.load")

    def _restore_state(self, state):
        return state if self.restore_hook is None else \
            self.restore_hook(state)

    def _episode_label(self) -> dict:
        if self._episode is not None and self._episode.enabled:
            return {"trace": self._episode.trace_id}
        return {}

    def _finish_episode(self, **labels) -> None:
        if self._episode is not None:
            self._episode.finish(**labels)
            self._episode = None

    # -- the loop -------------------------------------------------------- #

    def fit(self, state, iterator, n_steps: int, *,
            on_step: Optional[Callable] = None) -> tuple:
        """Run to ``n_steps`` total iterations (resuming included);
        returns ``(state, report)`` where ``report`` carries
        ``resumed_from`` / ``failures`` / ``restores`` / per-recovery
        ``mttr_s`` (failure to first completed post-restore step) and the
        checkpointer's save/load timing stats."""
        loaded, start = self._load()
        if loaded is not None:
            state = self._restore_state(loaded["state"])
            iterator.load_state_dict(loaded["iterator"])
            if start:
                self._events.emit("trainer_resume", iteration=start)
        else:
            # iteration-0 restore point: initial state, untouched iterator
            self._save(state, iterator, 0)
        resumed_from = start
        failures = restores = 0
        mttr: list = []
        t_fail: Optional[float] = None
        i = start
        while i < n_steps:
            # per-step span tree (ambient): prefetch-wait, dispatch, and
            # — on saving steps — the checkpoint enqueue, same taxonomy
            # as training.fit; a failed step's trace is error-marked so
            # sampling keeps it
            with self._tracer.trace("train_step", kind="train", step=i,
                                    loop="resilient") as step_tr:
                try:
                    inject(TRAINER_STEP, step=i)
                    with self._tracer.span("prefetch_wait"):
                        batch = next(iterator)
                    with self._tracer.span("dispatch"):
                        state = self.step_fn(state, batch)
                except Exception as e:  # noqa: BLE001 — recovery boundary
                    step_tr.mark_error(type(e).__name__)
                    failures += 1
                    self._c_failures.inc()
                    if t_fail is None:
                        # first failure of the episode: open the MTTR
                        # trace (root = failure -> first recovered step)
                        t_fail = time.perf_counter()
                        self._episode = self._tracer.trace(
                            "failure_episode", kind="resilience", step=i,
                            error=type(e).__name__)
                        self._episode.mark_error(type(e).__name__)
                    ep = self._episode
                    self._events.emit("trainer_failure", step=i,
                                      error=type(e).__name__,
                                      detail=str(e)[:200],
                                      **self._episode_label())
                    if self.dump_on_failure:
                        with ep.span("flight_dump"):
                            get_event_log().dump(file=sys.stderr,
                                                 once="failure")
                    if restores >= self.max_restores:
                        self._events.emit("trainer_giving_up", step=i,
                                          restores=restores,
                                          **self._episode_label())
                        self._finish_episode(gave_up=True)
                        raise
                    with ep.span("restore", attempt=restores + 1):
                        loaded, it_r = self._load()
                        if loaded is None:
                            # no snapshot anywhere: nothing to restore
                            self._finish_episode(gave_up=True)
                            raise
                        state = self._restore_state(loaded["state"])
                        iterator.load_state_dict(loaded["iterator"])
                    i = it_r
                    restores += 1
                    self._c_restores.inc()
                    self._events.emit("trainer_restore", iteration=it_r,
                                      restores=restores,
                                      **self._episode_label())
                    get_event_log().reset_dump_guard()  # next dump is new
                    continue
                if t_fail is not None:
                    dt = time.perf_counter() - t_fail
                    mttr.append(dt)
                    self._h_mttr.observe(dt)
                    self._events.emit("trainer_recovered", step=i,
                                      mttr_s=round(dt, 6),
                                      **self._episode_label())
                    # the episode's root span closes HERE: its duration
                    # IS the MTTR (failure -> first completed step)
                    self._finish_episode(mttr_s=round(dt, 6),
                                         recovered_step=i)
                    t_fail = None
                if on_step is not None:
                    on_step(i, state)
                i += 1
                if i % self.save_every == 0 or i == n_steps:
                    self._save(state, iterator, i)
        if self.async_save:
            # end-of-run barrier: the final snapshot must be durable (and
            # any writer failure loud) before the run reports success
            self.checkpointer.wait_async()
        report = {
            "steps": int(n_steps),
            "resumed_from": int(resumed_from),
            "failures": int(failures),
            "restores": int(restores),
            "mttr_s": mttr,
            "checkpoint_stats": self.checkpointer.get_stats(),
        }
        return state, report


def resilient_fit(step_fn: Callable, state, iterator, n_steps: int,
                  checkpointer, *, on_step: Optional[Callable] = None,
                  **kwargs) -> tuple:
    """One-call form of :class:`ResilientTrainer` (see its docstring):
    ``state, report = resilient_fit(step, state, it, N, ckpt)``."""
    trainer = ResilientTrainer(step_fn, checkpointer, **kwargs)
    return trainer.fit(state, iterator, n_steps, on_step=on_step)


__all__ = ["ResilientTrainer", "resilient_fit"]
