"""Bounded retry with exponential backoff for host-side transient ops.

Device-side failures restart whole programs (that is ``resilient_fit`` /
the serving engine restart); *host*-side operations — checkpoint
save/load, objstore transfers, prefill admission — fail transiently
(slow disk, a dropped TCP frame, an injected fault) and deserve a second
attempt before the heavyweight recovery machinery engages. This policy
is deliberately boring: bounded attempts, exponential backoff with an
optional **deterministic** jitter (seeded — replayable under test and
chaos runs, unlike ``random.random()`` jitter), a ``retry_on`` exception
filter, and registry/event telemetry for every retry and every
exhaustion (``retries_total{op}`` / ``retries_exhausted_total{op}``).

Usage::

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.05)
    result = policy.call(ckpt_write, blob, op="checkpoint.save")
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from chainermn_tpu.monitor._state import get_event_log, get_registry


class RetryPolicy:
    """Retry a callable up to ``max_attempts`` times.

    Backoff for attempt ``k`` (1-based; the delay slept *after* attempt
    ``k`` fails) is ``min(max_delay_s, base_delay_s * multiplier**(k-1))``
    scaled by ``1 + jitter * u`` with ``u ~ U[0, 1)`` from a seeded RNG —
    ``jitter=0`` disables it; ``seed=None`` makes it nondeterministic
    (production de-synchronization; keep the default seed in tests).
    Exceptions outside ``retry_on`` propagate immediately: a shape error
    is not a transient.
    """

    def __init__(self, max_attempts: int = 3, *, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, seed: Optional[int] = 0,
                 retry_on: tuple = (Exception,)) -> None:
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retry_on = retry_on
        self._rng = np.random.RandomState(seed)

    def delay_s(self, attempt: int) -> float:
        """Backoff after failed attempt ``attempt`` (1-based). Draws from
        the policy's RNG when jitter is on (one draw per call)."""
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * float(self._rng.rand())
        return d

    def call(self, fn: Callable, *args, op: str = "op", **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying per the policy. The final
        failure re-raises the last exception unchanged (callers keep their
        except clauses); every sleep and give-up is event-logged under
        ``op``."""
        events = get_event_log()
        registry = get_registry()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                if attempt >= self.max_attempts:
                    registry.counter(
                        "retries_exhausted_total", {"op": op}).inc()
                    events.emit("retry_exhausted", op=op, attempts=attempt,
                                error=type(e).__name__)
                    raise
                d = self.delay_s(attempt)
                registry.counter("retries_total", {"op": op}).inc()
                events.emit("retry", op=op, attempt=attempt,
                            delay_s=round(d, 6), error=type(e).__name__)
                time.sleep(d)

    def wrap(self, fn: Callable, op: str = "op") -> Callable:
        """``fn`` with the policy baked in (drop-in replacement)."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, op=op, **kwargs)

        return wrapped


__all__ = ["RetryPolicy"]
