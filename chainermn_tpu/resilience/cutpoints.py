"""Canonical fault-injection cut-point catalog.

Every ``inject()`` / ``torn_fraction()`` site names its cut-point with a
constant from this module — never a bare string literal. That makes the
set of places a chaos test can break the system a *closed, greppable
surface*: graftlint's consistency checker fails the build when a
call-site uses a string that is not here, when a constant here has no
call-site, when a point is not referenced by any test, or when the
README table drifts.

Naming convention: ``subsystem.site`` (lowercase, dot-separated —
enforced statically). Dynamic families (one point per collective op)
are built through the helper functions below and declared in
``DYNAMIC_PREFIXES``.

This module is import-light on purpose (stdlib only, no siblings);
fleet/deploy call-sites still import it *lazily*, because reaching any
``chainermn_tpu.resilience`` submodule executes the package
``__init__`` and with it the jax-heavy trainer stack.
"""

from __future__ import annotations

# -- checkpointing -------------------------------------------------------- #
CHECKPOINT_SAVE = "checkpoint.save"
CHECKPOINT_WRITE = "checkpoint.write"
CHECKPOINT_LOAD = "checkpoint.load"
SHARDED_CHECKPOINT_SAVE = "sharded_checkpoint.save"
SHARDED_CHECKPOINT_LOAD = "sharded_checkpoint.load"

# -- training ------------------------------------------------------------- #
TRAINER_STEP = "trainer.step"
DATALOADER_ASSEMBLE = "dataloader.assemble"
OBJSTORE_PUT = "objstore.put"
OBJSTORE_GET = "objstore.get"

# -- collectives ---------------------------------------------------------- #
COMM_ALLGATHER_OBJ = "comm.allgather_obj"

# -- serving -------------------------------------------------------------- #
SERVING_PREFILL = "serving.prefill"
SERVING_PREFILL_BATCH = "serving.prefill_batch"
SERVING_ADMIT_FAIR = "serving.admit_fair"
SERVING_DECODE = "serving.decode"
SERVING_KV_APPEND = "serving.kv_append"
SERVING_PREFIX_COPY = "serving.prefix_copy"
SERVING_SPEC_VERIFY = "serving.spec_verify"
SERVING_CHUNK_PREFILL = "serving.chunk_prefill"

# -- fleet / deploy ------------------------------------------------------- #
FLEET_ROUTE = "fleet.route"
FLEET_REPLICA = "fleet.replica"
FLEET_BREAKER = "fleet.breaker"
FLEET_MIGRATE = "fleet.migrate"
FLEET_SHARE = "fleet.share"
FLEET_REBALANCE = "fleet.rebalance"
DEPLOY_PUBLISH = "deploy.publish"
DEPLOY_RESHARD = "deploy.reshard"

# families of points minted at runtime (``comm.<collective-op>``); a
# resolved point matching one of these prefixes is catalog-sanctioned
DYNAMIC_PREFIXES = ("comm.",)


def comm_point(op: str) -> str:
    """Cut-point for one collective op (``comm.allreduce`` ...)."""
    return f"comm.{op}"


ALL_CUTPOINTS = (
    CHECKPOINT_SAVE,
    CHECKPOINT_WRITE,
    CHECKPOINT_LOAD,
    SHARDED_CHECKPOINT_SAVE,
    SHARDED_CHECKPOINT_LOAD,
    TRAINER_STEP,
    DATALOADER_ASSEMBLE,
    OBJSTORE_PUT,
    OBJSTORE_GET,
    COMM_ALLGATHER_OBJ,
    SERVING_PREFILL,
    SERVING_PREFILL_BATCH,
    SERVING_ADMIT_FAIR,
    SERVING_DECODE,
    SERVING_KV_APPEND,
    SERVING_PREFIX_COPY,
    SERVING_SPEC_VERIFY,
    SERVING_CHUNK_PREFILL,
    FLEET_ROUTE,
    FLEET_REPLICA,
    FLEET_BREAKER,
    FLEET_MIGRATE,
    FLEET_SHARE,
    FLEET_REBALANCE,
    DEPLOY_PUBLISH,
    DEPLOY_RESHARD,
)

__all__ = [
    "ALL_CUTPOINTS",
    "CHECKPOINT_LOAD",
    "CHECKPOINT_SAVE",
    "CHECKPOINT_WRITE",
    "COMM_ALLGATHER_OBJ",
    "DATALOADER_ASSEMBLE",
    "DEPLOY_PUBLISH",
    "DEPLOY_RESHARD",
    "DYNAMIC_PREFIXES",
    "FLEET_BREAKER",
    "FLEET_MIGRATE",
    "FLEET_REBALANCE",
    "FLEET_REPLICA",
    "FLEET_ROUTE",
    "FLEET_SHARE",
    "OBJSTORE_GET",
    "OBJSTORE_PUT",
    "SERVING_ADMIT_FAIR",
    "SERVING_CHUNK_PREFILL",
    "SERVING_DECODE",
    "SERVING_KV_APPEND",
    "SERVING_PREFILL",
    "SERVING_PREFILL_BATCH",
    "SERVING_PREFIX_COPY",
    "SERVING_SPEC_VERIFY",
    "SHARDED_CHECKPOINT_LOAD",
    "SHARDED_CHECKPOINT_SAVE",
    "TRAINER_STEP",
    "comm_point",
]
