"""``chainermn_tpu.resilience`` — failure as a first-class, tested scenario.

The monitor subsystem (PR 2) made the system *observable*; this package
makes it *survivable*, in three layers that compose with it:

- **Fault injection** (:mod:`~chainermn_tpu.resilience.faults`): a
  deterministic, seedable :class:`FaultInjector` over named cut-points
  threaded through the framework's host-side boundaries — eager
  ``MeshCommunicator`` collectives, ``ServingEngine`` device calls,
  checkpoint I/O, the native dataloader/objstore paths. Every injected
  fault (raise / delay / hang / torn-write) emits flight-recorder events
  and registry counters, so chaos runs are diagnosed with the exact
  tooling production failures are.
- **Bounded retry** (:class:`RetryPolicy`): exponential backoff with
  deterministic jitter around host-transient ops (checkpoint save/load,
  objstore transfers, prefill admission).
- **Auto-resume training** (:func:`resilient_fit` /
  :class:`ResilientTrainer`): a step-level exception boundary that dumps
  the flight recorder (idempotently — shared dump guard with ``Watchdog``
  and ``global_except_hook``), restores the newest common
  ``MultiNodeCheckpointer`` snapshot (state + iterator + any PRNG keys in
  the state pytree), and replays bit-exactly under a restore budget.

Serving-side graceful degradation (bounded admission queue, per-request
deadlines, the terminal ``ERRORED`` state, warm engine restart) lives in
:mod:`chainermn_tpu.serving` and consumes these primitives.
"""

from chainermn_tpu.resilience.faults import (
    FaultInjector,
    InjectedFault,
    get_injector,
    inject,
    torn_fraction,
)
from chainermn_tpu.resilience.retry import RetryPolicy
from chainermn_tpu.resilience.trainer import ResilientTrainer, resilient_fit

__all__ = [
    "FaultInjector",
    "InjectedFault",
    "ResilientTrainer",
    "RetryPolicy",
    "get_injector",
    "inject",
    "resilient_fit",
    "torn_fraction",
]
