"""Deterministic, seedable fault injection with named cut-points.

The reference's fault-tolerance story is fail-and-restart via per-rank
snapshots (SURVEY.md S2.14) — but nothing in it, or in this repo before
this module, ever *exercises* a failure. This is the missing half: the
framework's host-side boundaries carry named cut-points
(``inject("serving.decode")``, ``inject("checkpoint.write")``, ...) that
are free no-ops until a :class:`FaultInjector` is installed, at which
point armed faults fire deterministically (``after``/``times``) or with a
seeded probability (``p`` — reproducible chaos), emitting an
``fault_injected`` event into the flight recorder and incrementing
``faults_injected_total{point,kind}`` in the process registry so every
injected failure is observable through the same telemetry as the real
thing.

Fault kinds:

- ``raise`` — raise :class:`InjectedFault` (or a caller-supplied
  exception) at the cut-point: a crashed device call, a failed write;
- ``delay`` — sleep ``delay_s``: a transient stall (slow disk, jittery
  interconnect) that retries/deadlines must absorb;
- ``hang`` — block for ``hang_s`` (interruptible via
  :meth:`FaultInjector.release`): the lost-collective wedge the Watchdog
  exists to turn into a loud abort;
- ``torn_write`` — silently truncate a write to ``frac`` of its bytes
  (consulted by write-shaped cut-points through :func:`torn_fraction`):
  the data-loss case only a checksum catches.

Cut-points in the framework (the injection surface):

==========================  ==================================================
point                       where it fires
==========================  ==================================================
``comm.<op>``               eager ``MeshCommunicator`` collectives (allreduce,
                            bcast, allgather, ...), before the device program
``comm.allgather_obj``      host object-channel gather (checkpoint agreement)
``serving.prefill``         ``ServingEngine.prefill`` (single-request
                            admission), inside the watchdog window (a
                            hang here trips hang detection)
``serving.prefill_batch``   ``ServingEngine.admit_batch`` — before the
                            batched bucket-prefill device call, so a
                            raise is contained to the admitting group
``serving.prefix_copy``     prefix-cache block copies (``op='fetch'`` on
                            a hit, ``op='insert'`` after admission;
                            ``op='share'`` in paged mode, where a hit is
                            a table reference instead of a copy)
``serving.kv_append``       ``ServingEngine.append_block`` — the paged
                            engine's lazy block allocation when a slot
                            crosses a block boundary mid-decode; a raise
                            is contained by preempting+requeueing ONLY
                            that slot's request (no restart)
``serving.decode``          ``ServingEngine.decode_step``, same window
``fleet.route``             ``FleetRouter``'s routing decision — a raise
                            degrades placement to the lowest-id accepting
                            replica (the request still lands, on the
                            fallback) instead of losing the submission
``fleet.replica``           each ``EngineReplica`` drive-loop iteration —
                            a raise models a worker death and exercises
                            the whole supervisor path: fail in-flight,
                            drain QUEUED, warm-restart or quarantine,
                            re-route to healthy replicas
``trainer.step``            each ``resilient_fit`` iteration, inside its
                            exception boundary
``checkpoint.save``         ``MultiNodeCheckpointer.save`` before any I/O
``checkpoint.write``        mid-write of the snapshot tmp file (``raise``
                            leaves a torn ``.tmp``; ``torn_write`` corrupts
                            the renamed target so only the checksum catches)
``checkpoint.load``         ``MultiNodeCheckpointer.maybe_load``
``dataloader.assemble``     ``NativeBatchLoader`` batch assembly
``objstore.put/get``        native objstore sidecar transfers
==========================  ==================================================

Usage::

    inj = FaultInjector(seed=0)
    inj.arm("serving.decode", kind="raise", after=3, times=1)
    with inj:                      # installs process-globally
        ... drive the system; the 4th decode_step raises InjectedFault ...
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from chainermn_tpu.monitor._state import get_event_log, get_registry


class InjectedFault(RuntimeError):
    """The exception an armed ``kind='raise'`` fault throws at its
    cut-point (tests and retry policies match on this type)."""

    def __init__(self, point: str, message: Optional[str] = None) -> None:
        super().__init__(message or f"injected fault at {point!r}")
        self.point = point


_KINDS = ("raise", "delay", "hang", "torn_write")


@dataclass
class _Fault:
    point: str
    kind: str
    after: int = 0            # hits to let pass before becoming eligible
    times: Optional[int] = 1  # max firings (None: every eligible hit)
    p: float = 1.0            # per-hit firing probability once eligible
    delay_s: float = 0.05
    hang_s: float = 3600.0
    frac: float = 0.5         # torn_write: fraction of bytes kept
    exc: Optional[BaseException] = None
    hits: int = 0
    fired: int = 0


class FaultInjector:
    """Armable set of faults over the framework's named cut-points.

    Deterministic by construction: eligibility is hit-counted per fault
    (``after``/``times``) and the probabilistic path (``p < 1``) draws
    from one seeded ``RandomState``, so a chaos run replays exactly under
    the same seed and call sequence. Install process-globally with
    :meth:`install`/:meth:`uninstall` or as a context manager; when no
    injector is installed every cut-point is a cheap no-op.
    """

    def __init__(self, seed: Optional[int] = 0) -> None:
        self._rng = np.random.RandomState(seed)
        self._faults: list[_Fault] = []
        self._lock = threading.Lock()
        self._released = threading.Event()
        self.fired_log: list[tuple[str, str]] = []   # (point, kind) history

    # -- configuration --------------------------------------------------- #

    def arm(self, point: str, kind: str = "raise", **kw) -> _Fault:
        """Arm one fault at ``point``. Keywords per kind: ``after``,
        ``times``, ``p`` (all), ``delay_s`` (delay), ``hang_s`` (hang),
        ``frac`` (torn_write), ``exc`` (raise)."""
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        fault = _Fault(point=point, kind=kind, **kw)
        with self._lock:
            self._faults.append(fault)
        return fault

    def clear(self, point: Optional[str] = None) -> None:
        with self._lock:
            self._faults = [
                f for f in self._faults
                if point is not None and f.point != point
            ]

    # -- installation ---------------------------------------------------- #

    def install(self) -> "FaultInjector":
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.release()
        self.uninstall()

    def release(self) -> None:
        """Unblock any in-flight ``hang`` fault (tests; emergency stop)."""
        self._released.set()

    # -- firing ---------------------------------------------------------- #

    def _match(self, point: str, kinds) -> Optional[_Fault]:
        with self._lock:
            for f in self._faults:
                if f.point != point or f.kind not in kinds:
                    continue
                f.hits += 1
                if f.hits <= f.after:
                    continue
                if f.times is not None and f.fired >= f.times:
                    continue
                if f.p < 1.0 and self._rng.rand() >= f.p:
                    continue
                f.fired += 1
                self.fired_log.append((point, f.kind))
                return f
        return None

    def _record(self, f: _Fault, ctx: dict) -> None:
        get_registry().counter(
            "faults_injected_total", {"point": f.point, "kind": f.kind}
        ).inc()
        get_event_log().emit("fault_injected", point=f.point, fault=f.kind,
                             **ctx)

    def fire(self, point: str, **ctx) -> None:
        """Consult the armed faults for ``point`` and act (the body of
        :func:`inject`). ``torn_write`` faults never fire here — they are
        consulted by write-shaped cut-points via :func:`torn_fraction`."""
        f = self._match(point, ("raise", "delay", "hang"))
        if f is None:
            return
        self._record(f, ctx)
        if f.kind == "raise":
            raise f.exc if f.exc is not None else InjectedFault(point)
        if f.kind == "delay":
            time.sleep(f.delay_s)
            return
        # hang: block in short interruptible slices so tests (and the
        # emergency release()) can cut it short; a Watchdog sees one
        # continuous stall either way
        deadline = time.monotonic() + f.hang_s
        while time.monotonic() < deadline:
            if self._released.wait(min(0.05, max(0.0,
                                                 deadline - time.monotonic()))):
                return

    def torn_fraction(self, point: str, **ctx) -> Optional[float]:
        """Fraction of bytes a write at ``point`` should keep, or ``None``
        when no ``torn_write`` fault fires."""
        f = self._match(point, ("torn_write",))
        if f is None:
            return None
        self._record(f, ctx)
        return f.frac


_ACTIVE: Optional[FaultInjector] = None


def get_injector() -> Optional[FaultInjector]:
    """The process-globally installed injector, or None."""
    return _ACTIVE


def inject(point: str, **ctx) -> None:
    """The cut-point call sprinkled through the framework: a no-op unless
    an injector is installed AND has an eligible fault armed at ``point``.
    ``ctx`` fields ride into the ``fault_injected`` event."""
    inj = _ACTIVE
    if inj is None:
        return
    inj.fire(point, **ctx)


def torn_fraction(point: str, **ctx) -> Optional[float]:
    """Write-shaped cut-points ask how much of their payload to actually
    write; None (the overwhelmingly common answer) means all of it."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.torn_fraction(point, **ctx)


__all__ = [
    "FaultInjector",
    "InjectedFault",
    "get_injector",
    "inject",
    "torn_fraction",
]
