"""chainermn_tpu — TPU-native distributed training framework.

A ground-up rebuild of ChainerMN's capability set (reference:
``gshuichi/chainermn``; see SURVEY.md) designed for TPU hardware: collectives
are XLA ops over a ``jax.sharding.Mesh`` (ICI), object traffic rides the
process-space side channel (DCN), and the training step is one fused jitted
program. Facade parity: ``[U] chainermn/__init__.py`` (unverified cite).
"""

from chainermn_tpu import functions
from chainermn_tpu.datasets import (
    create_empty_dataset,
    scatter_dataset,
    scatter_index,
)
from chainermn_tpu.evaluators import create_multi_node_evaluator
from chainermn_tpu.extensions import (
    AllreducePersistent,
    ObservationAggregator,
    create_multi_node_checkpointer,
)
from chainermn_tpu.global_except_hook import add_hook as add_global_except_hook
from chainermn_tpu import dataflow
from chainermn_tpu import fleet
from chainermn_tpu import monitor
from chainermn_tpu import resilience
from chainermn_tpu.iterators import (
    SerialIterator,
    create_multi_node_iterator,
    create_synchronized_iterator,
)
from chainermn_tpu.links import (
    MultiNodeBatchNormalization,
    MultiNodeChainList,
    create_mnbn_model,
)
from chainermn_tpu.optimizers import (
    clip_by_global_norm_sharded,
    create_multi_node_optimizer,
    create_zero_optimizer,
)
from chainermn_tpu.communicators import (
    CommunicatorBase,
    FlatCommunicator,
    HierarchicalCommunicator,
    MeshCommunicator,
    NaiveCommunicator,
    SingleNodeCommunicator,
    TpuCommunicator,
    TwoDimensionalCommunicator,
    create_communicator,
)

__version__ = "0.1.0"

__all__ = [
    "CommunicatorBase",
    "MeshCommunicator",
    "NaiveCommunicator",
    "FlatCommunicator",
    "TpuCommunicator",
    "HierarchicalCommunicator",
    "TwoDimensionalCommunicator",
    "SingleNodeCommunicator",
    "create_communicator",
    "create_multi_node_optimizer",
    "create_zero_optimizer",
    "clip_by_global_norm_sharded",
    "create_multi_node_evaluator",
    "MultiNodeChainList",
    "MultiNodeBatchNormalization",
    "create_mnbn_model",
    "scatter_dataset",
    "scatter_index",
    "create_empty_dataset",
    "SerialIterator",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
    "AllreducePersistent",
    "ObservationAggregator",
    "create_multi_node_checkpointer",
    "add_global_except_hook",
    "dataflow",
    "fleet",
    "functions",
    "monitor",
    "resilience",
    "__version__",
]
