#!/usr/bin/env python
"""Continuous-batching LM serving demo — the traffic-facing counterpart of
``train_lm.py``.

Builds a small TransformerLM, stands up the in-process serving stack
(:mod:`chainermn_tpu.serving`: slot-pool KV-cache engine + FCFS scheduler +
background client thread), and pushes a burst of ragged random prompts
through it: some blocking, one streamed token-by-token. Prints the serving
metrics (TTFT/TPOT percentiles, tokens/s, slot occupancy) at the end.

And the continuous-telemetry demo (ISSUE 15): ``--health`` runs a
background collector sampling every registry instrument into ring-buffer
time series at ``--ts-cadence``, scores each replica
healthy/degraded/critical through the standard detector set (TTFT p99
drift, queue-depth threshold, decode-stall deadman), prints the verdict
at the end, and — with ``--http-port`` — serves live ``/timeseries`` and
``/health`` JSON it then scrapes back over the real socket.

Also the telemetry demo: the burst runs inside a
:func:`chainermn_tpu.monitor.annotate` profiler scope (capture with
``jax.profiler.trace`` and the span shows up named in XProf/Perfetto),
``--watchdog SECONDS`` arms the engine's hang watchdog (a wedged
collective dumps the flight recorder + thread stacks instead of hanging
the client), and ``--prometheus`` prints the process-wide
:func:`chainermn_tpu.monitor.exposition` text — the same series a
Prometheus scraper would pull.

And the graceful-degradation demo: ``--max-queue N`` bounds the admission
queue (overflow submissions are rejected with ``QueueFullError`` —
backpressure at the submitter) and ``--deadline SECONDS`` sheds requests
still queued past their deadline (``wait()`` raises
``DeadlineExceededError`` instead of blocking on work that will never
start). See README "Fault tolerance".

And the admission fast path (PR 5): ``--prefill-buckets`` pads prompts to
a small ladder of bucketed lengths instead of one ``--prefill-len``,
``--prefill-batch N`` admits up to N same-bucket requests per compiled
prefill call, and ``--prefix-blocks N`` turns on ref-counted prefix KV
reuse — with ``--shared-prefix M`` every burst prompt shares an M-token
system prompt, so admissions prefill only their ragged tails (the prefix
stats print at the end: hit rate, evictions, store occupancy).

And the paged KV store (PR 7): ``--paged-kv`` runs decode on one shared
block store with per-slot block tables — slots stop reserving worst-case
``cache_len`` regions, so the same device memory serves 4x+ more
concurrent requests, prefix hits become zero-copy shared table entries,
and ``--kv-quant int8`` halves resident KV bytes again; ``--kv-blocks``
caps the pool (admission then defers to the queue, and a dry pool
preempts+requeues the newest request instead of failing it).

And the serving fleet (ISSUE 8): ``--replicas N`` runs N engine replicas
(each with its own warmup'd programs, slot pool, and prefix store) behind
a ``FleetRouter`` — prefix-affinity + occupancy-aware routing
(``--no-affinity`` for pure least-loaded), a global ``--max-queue`` shed
at the fleet edge, and replica-level failover; the fleet report (replica
states, affinity hit rate, fleet-pooled TTFT percentiles) prints at the
end, and ``--verify-parity`` checks the first few outputs token-for-token
against solo ``generate()``.

And speculative decode (PR 12): ``--speculate ngram`` drafts k tokens per
round from the request's own prefix (prompt-lookup n-grams — no second
model) and verifies them in ONE target call, committing every leading
match plus the free correction token; ``--speculate draft`` drafts with a
small TransformerLM instead. Greedy-only (``--temperature 0``) and paged
(``--paged-kv``): accepted tokens commit straight into shared block-store
blocks, rejected rows roll back. ``--spec-k`` sets the draft window; the
accept rate and proposed/accepted totals print at the end.

And the weight lifecycle (ISSUE 10): ``--reshard-from <dir>`` restores
the serving params from a ``ShardedCheckpointer`` snapshot directory
through ``deploy.elastic_restore`` — a snapshot saved while training at
one mesh shape / TP degree serves at another (the manifest's save-time
geometry drives the fused-qkv layout permutation); pair with
``train_lm.py --snapshot-to`` for the train→reshard→serve chain, or with
``train_lm.py --publish-to engine`` for the online hot-swap variant.

And the closed-loop control plane (ISSUE 16): ``--autoscale`` runs a
background :class:`~chainermn_tpu.fleet.control.FleetController` over
the fleet — sustained queue pressure spawns replicas (up to
``--max-replicas``), sustained idleness retires them (down to
``--min-replicas``), and ``--canary`` then demonstrates an SLO-guarded
canary deploy end to end: bumped weights swap onto ONE replica, bake,
and promote fleet-wide (or auto-rollback on regression), with the
controller's decision ring and version history printed at the end and
served live at ``/control`` with ``--http-port``.

And overload robustness (ISSUE 18): ``--priority mixed`` labels every
other burst request ``batch`` (``batch`` runs only when the interactive
queue is drained, and is preempted FIRST when the KV pool runs dry),
``--tenant-weights "tenant0=4,tenant1=1"`` turns on weighted
deficit-round-robin admission over the ``--tenants`` labels (weights
shrink automatically for tenants over their measured device-second
share), and ``--brownout N`` arms the degradation ladder up to level N —
sustained interactive backlog steps pause-batch -> single-token decode ->
max-new cap -> shed-lowest-weight-tenant, each step edge-logged and fully
reversible once the queue drains; the episode (levels hit, steps, final
level) prints at the end next to the per-tenant cost table.

And chunked prefill + disaggregated tiers (ISSUE 19): ``--chunk-tokens
N`` (with ``--paged-kv``) prefills long prompts N tokens per scheduler
step interleaved with decode — resident streams stop stalling for whole
long prefills; ``--prefill-replicas P --decode-replicas D`` splits the
fleet into tiers: new requests prefill on the first P replicas, then
their KV blocks migrate host-bounce to a decode replica (same token
stream, rng and position ride along; a failed migration just decodes in
place). The migration counters print with the fleet report.

And fleet-wide KV reuse (ISSUE 20): ``--share-prefixes`` (paged fleet,
affinity on) turns an affinity MISS on a prompt whose prefix another
replica holds into a prefix hit — the holder exports the cached blocks
once through the fused migration gather, a host-side payload LRU serves
every later adopter, and the routed replica imports them before the
request admits, prefilling only the uncached suffix; ``--rebalance``
probes mid-stream decode rebalancing — while the burst is in flight the
router migrates one live decode from the busiest replica to the least
loaded, and the victim finishes token-exactly on its new home. The
share/rebalance counters and payload-cache stats print with the fleet
report.

Run (CPU mesh; any accelerator works the same)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --requests 16 --slots 4 --prometheus

    # two replicas behind the prefix-affinity router:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --replicas 2 --shared-prefix 4 \
        --prefix-blocks 16 --prefix-block-size 2 --verify-parity

    # shared-system-prompt traffic through the prefix-cached fast path:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --shared-prefix 12 \
        --prefill-buckets 4,16 --prefill-batch 4 --prefix-blocks 32 \
        --prefix-block-size 2

    # tensor-parallel decode through the same scheduler:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --tensor-parallel

    # speculative decode on the paged store (prompt-lookup drafting):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --paged-kv --temperature 0 \
        --speculate ngram --spec-k 4

    # closed-loop autoscaling + a canary deploy through the controller:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --autoscale --min-replicas 1 \
        --max-replicas 3 --slots 1 --requests 24 --canary

    # chunked prefill + disaggregated prefill/decode tiers:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --paged-kv --chunk-tokens 4 \
        --prefill-replicas 1 --decode-replicas 1 --verify-parity

    # fleet-wide KV reuse: cross-replica prefix sharing + a mid-stream
    # decode-rebalance probe:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/serve_lm.py --replicas 2 --paged-kv \
        --kv-block-size 2 --shared-prefix 12 --share-prefixes \
        --rebalance --verify-parity
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform

apply_env_platform()
from chainermn_tpu import monitor  # noqa: E402
from chainermn_tpu.models import TransformerLM  # noqa: E402
from chainermn_tpu.serving import (  # noqa: E402
    QueueFullError,
    ServingClient,
    ServingEngine,
)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=4,
                    help="cache slots = max concurrent decodes")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prefill-len", type=int, default=16,
                    help="prompts are padded to this length (one compile)")
    ap.add_argument("--prefill-buckets", default="",
                    help="comma-separated padded-length ladder (e.g. "
                         "'4,16'): each admission runs the smallest "
                         "bucket covering its (suffix) length — less "
                         "padding waste for one extra compile per bucket "
                         "(empty: single prefill-len bucket)")
    ap.add_argument("--prefill-batch", type=int, default=1,
                    help="admit up to this many same-bucket requests per "
                         "prefill device call (batched admission)")
    ap.add_argument("--prefix-blocks", type=int, default=0,
                    help="enable ref-counted prefix KV reuse with this "
                         "many device store blocks: requests sharing a "
                         "cached prompt prefix prefill only their suffix "
                         "(0: off)")
    ap.add_argument("--prefix-block-size", type=int, default=4,
                    help="tokens per prefix-cache block (matches are "
                         "multiples of this)")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="give every burst prompt a shared system-prompt "
                         "prefix of this many tokens — the workload "
                         "prefix caching exists for (0: fully ragged)")
    ap.add_argument("--paged-kv", action="store_true",
                    help="paged KV decode: one shared block store under "
                         "every slot (block-table indexed), admission by "
                         "free blocks instead of worst-case slot "
                         "regions — 4x+ more concurrent requests at the "
                         "same device KV memory; prefix hits become "
                         "zero-copy shared table entries")
    ap.add_argument("--kv-blocks", type=int, default=0,
                    help="paged: total store blocks incl. the scratch "
                         "block (0: dense-equivalent capacity, "
                         "slots x ceil(cache_len/block))")
    ap.add_argument("--kv-block-size", type=int, default=16,
                    help="paged: tokens per KV block")
    ap.add_argument("--kv-quant", choices=("none", "int8"), default="none",
                    help="paged: int8-quantize resident blocks (per-row "
                         "per-head scales, ~2x less KV memory; small "
                         "tested logit perturbation)")
    ap.add_argument("--speculate", choices=("off", "ngram", "draft"),
                    default="off",
                    help="speculative decode on the paged store: draft k "
                         "tokens per round (ngram: prompt-lookup from the "
                         "request's own prefix, no second model; draft: a "
                         "small draft TransformerLM), verify them in ONE "
                         "target call, commit every leading match + the "
                         "correction token. Needs --paged-kv and "
                         "--temperature 0 (greedy-only)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative draft window: tokens proposed per "
                         "verify round")
    ap.add_argument("--chunk-tokens", type=int, default=0,
                    help="chunked prefill (ISSUE 19, needs --paged-kv): "
                         "prefill long prompts this many tokens per "
                         "scheduler step, interleaved with decode of "
                         "resident slots, instead of one monolithic "
                         "bucket call (0: off)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="run this many engine replicas behind the fleet "
                         "router (1: the plain single-engine client)")
    ap.add_argument("--prefill-replicas", type=int, default=0,
                    help="disaggregated tiers (ISSUE 19, needs "
                         "--paged-kv and --decode-replicas): the first P "
                         "replicas take every new request's prefill; on "
                         "completion the KV blocks migrate host-bounce "
                         "to a decode-tier replica (0: symmetric fleet)")
    ap.add_argument("--decode-replicas", type=int, default=0,
                    help="disaggregated tiers: replicas that only take "
                         "migrated-in decode work (give with "
                         "--prefill-replicas; the fleet size is P+D)")
    ap.add_argument("--share-prefixes", action="store_true",
                    help="cross-replica prefix sharing (ISSUE 20, needs "
                         "a --paged-kv fleet with affinity): an affinity "
                         "miss on a prompt whose prefix another replica "
                         "holds exports those blocks through the fused "
                         "migration path (cached host-side, LRU) and "
                         "imports them into the routed replica BEFORE "
                         "admission — only the uncached suffix prefills")
    ap.add_argument("--rebalance", action="store_true",
                    help="mid-stream decode rebalancing probe (ISSUE 20, "
                         "needs a --paged-kv fleet): while the burst is "
                         "in flight, migrate one live decode from the "
                         "busiest replica to the least loaded — the "
                         "victim finishes token-exactly on its new home")
    ap.add_argument("--affinity", dest="affinity", action="store_true",
                    default=True,
                    help="prefix-affinity routing (default): requests "
                         "sharing a cached prefix go to the replica whose "
                         "trie holds it, within the load-imbalance bound")
    ap.add_argument("--no-affinity", dest="affinity", action="store_false",
                    help="pure occupancy-aware least-loaded routing")
    ap.add_argument("--autoscale", action="store_true",
                    help="closed-loop fleet control (ISSUE 16): a "
                         "background FleetController scales the fleet "
                         "between --min-replicas and --max-replicas on "
                         "sustained queue pressure / idleness (implies "
                         "fleet mode and the --health telemetry wiring)")
    ap.add_argument("--min-replicas", type=int, default=1,
                    help="autoscale floor (also the starting fleet size "
                         "when --autoscale is given without --replicas)")
    ap.add_argument("--max-replicas", type=int, default=3,
                    help="autoscale ceiling")
    ap.add_argument("--canary", action="store_true",
                    help="after the burst, deploy bumped weights through "
                         "the controller's canary path: one replica "
                         "takes them, bakes for --canary-bake seconds "
                         "against the fleet health/SLO baseline, then "
                         "promotes fleet-wide (or auto-rollbacks on "
                         "regression); needs --autoscale")
    ap.add_argument("--canary-bake", type=float, default=1.0,
                    help="canary bake window in seconds (--canary)")
    ap.add_argument("--reshard-from", default="",
                    help="restore the serving params from a "
                         "ShardedCheckpointer snapshot directory through "
                         "deploy.elastic_restore: the manifest's "
                         "save-time TP degree is resharded onto THIS "
                         "run's layout (dense or --tensor-parallel at "
                         "any degree), so a training snapshot serves "
                         "directly — see train_lm.py --snapshot-to")
    ap.add_argument("--verify-parity", action="store_true",
                    help="after the burst, check the first few completed "
                         "requests token-for-token against solo "
                         "generate() with the same rng")
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--eos-id", type=int, default=1,
                    help="token retiring a request early (-1: disabled)")
    ap.add_argument("--tensor-parallel", action="store_true",
                    help="shard heads over the mesh; decode runs inside "
                         "the communicator's shard_map")
    ap.add_argument("--watchdog", type=float, default=0.0,
                    help="arm the engine hang watchdog: a decode step "
                         "exceeding this many seconds dumps the flight "
                         "recorder + thread stacks and aborts (0: off)")
    ap.add_argument("--max-queue", type=int, default=0,
                    help="bounded admission queue: submissions beyond this "
                         "many queued requests are rejected with "
                         "QueueFullError — backpressure instead of "
                         "unbounded queueing (0: unbounded)")
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="per-request deadline in seconds: work still "
                         "queued past it is shed (terminal ERRORED, "
                         "wait() raises DeadlineExceededError) instead of "
                         "occupying a slot too late to matter (0: off)")
    ap.add_argument("--prometheus", action="store_true",
                    help="print the Prometheus text exposition of the "
                         "process metrics registry at the end")
    ap.add_argument("--trace", type=int, default=1, metavar="N",
                    help="trace every Nth request (span tree: queue -> "
                         "admit -> prefill -> decode -> retire; "
                         "shed/errored requests are always kept). "
                         "0 disables tracing")
    ap.add_argument("--trace-out", default="",
                    help="write retained traces as Chrome trace-event "
                         "JSON to this path — load it in "
                         "chrome://tracing or ui.perfetto.dev")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="declare a TTFT p99 SLO at this many ms and "
                         "print the multi-window burn-rate evaluation "
                         "at the end (0: no SLO)")
    ap.add_argument("--http-port", type=int, default=-1,
                    help="serve the monitor scrape endpoints (/metrics "
                         "/traces /slo /events) on this port for the "
                         "duration of the burst (0: ephemeral; -1: off)")
    ap.add_argument("--health", action="store_true",
                    help="continuous telemetry (ISSUE 15): a background "
                         "collector samples every registry instrument "
                         "into ring-buffer time series, the standard "
                         "detector set (TTFT drift, queue threshold, "
                         "decode-stall deadman) scores each replica "
                         "healthy/degraded/critical, and the verdict "
                         "prints at the end; with --http-port the "
                         "/timeseries and /health endpoints serve live "
                         "JSON")
    ap.add_argument("--ts-cadence", type=float, default=0.05,
                    help="collector sampling cadence in seconds "
                         "(--health)")
    ap.add_argument("--tenants", type=int, default=1,
                    help="cost accounting (ISSUE 17): label the burst's "
                         "requests with this many synthetic tenants "
                         "(round-robin) and print the per-tenant cost "
                         "table — device seconds by kind, KV "
                         "block-seconds, queue wait — plus the fleet "
                         "goodput breakdown at the end; with "
                         "--http-port the /costs endpoint serves the "
                         "same JSON live (1: everything bills to "
                         "'default')")
    ap.add_argument("--priority", choices=("interactive", "batch", "mixed"),
                    default="interactive",
                    help="admission class for the burst's requests "
                         "(ISSUE 18): 'batch' marks them all "
                         "best-effort (admitted only when the "
                         "interactive queue is drained, preempted first "
                         "when KV runs dry), 'mixed' alternates the two "
                         "classes request by request")
    ap.add_argument("--tenant-weights", default="",
                    help="weighted-fair tenant admission (ISSUE 18): "
                         "comma-separated 'name=weight' pairs over the "
                         "--tenants labels (e.g. 'tenant0=4,tenant1=1') "
                         "— admission runs deficit-round-robin over "
                         "per-tenant token budgets, and a tenant over "
                         "its measured device-second share has its "
                         "effective weight shrunk (empty: FIFO within "
                         "each class)")
    ap.add_argument("--brownout", type=int, default=0,
                    help="arm the brownout degradation ladder up to "
                         "this level (1: pause batch, 2: +single-token "
                         "decode, 3: +max-new cap, 4: +shed lowest-"
                         "weight tenant); sustained interactive backlog "
                         "steps up, a drained queue steps back down, "
                         "and the episode prints at the end (0: off)")
    args = ap.parse_args()

    comm = chainermn_tpu.create_communicator("tpu") if args.tensor_parallel \
        else None
    model = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, max_len=args.prefill_len + args.max_new,
        tensor_axis=comm.axis_name if comm else None,
    )
    rng = np.random.RandomState(0)
    init_tok = jnp.zeros((1, args.prefill_len), jnp.int32)
    if comm is not None:
        from jax.sharding import PartitionSpec as P

        params = jax.jit(comm.shard_map(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            in_specs=P(), out_specs=P(),
        ))(init_tok)
    else:
        params = model.init(jax.random.PRNGKey(0), init_tok)

    if args.reshard_from:
        # elastic restore (ISSUE 10): the fresh-init params are only the
        # restore TEMPLATE (structure + target shardings); a snapshot
        # saved on a different mesh shape or TP degree is gathered,
        # qkv-permuted per the manifest, and re-sliced onto this layout
        from chainermn_tpu.deploy import elastic_restore
        from chainermn_tpu.extensions.sharded_checkpoint import (
            ShardedCheckpointer,
        )

        with ShardedCheckpointer(args.reshard_from) as cp:
            mf = cp.manifest() or {}
            restored, step = elastic_restore(
                cp, {"params": params}, comm=comm, model=model)
        if restored is None:
            raise SystemExit(
                f"--reshard-from {args.reshard_from}: no snapshot found")
        params = restored["params"]
        print(f"resharded snapshot step {step}: save-time tp_degree="
              f"{mf.get('tp_degree', 1)} -> serving tp_degree="
              f"{comm.size if comm else 1}")

    buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
               if args.prefill_buckets else None)
    paged_kw = {}
    if args.paged_kv:
        paged_kw = dict(paged=True, kv_blocks=args.kv_blocks or None,
                        kv_block_size=args.kv_block_size,
                        kv_quant=args.kv_quant)
        if args.prefix_blocks:
            raise SystemExit("--paged-kv unifies the prefix cache onto the "
                             "shared block store; drop --prefix-blocks and "
                             "size it with --kv-blocks/--kv-block-size")
    spec_cfg = None
    if args.speculate != "off":
        from chainermn_tpu.serving import SpeculativeConfig

        if not args.paged_kv:
            raise SystemExit("--speculate commits accepted tokens into "
                             "shared block-store blocks; add --paged-kv")
        if args.temperature != 0.0:
            raise SystemExit("--speculate verifies drafts against the "
                             "greedy argmax; pass --temperature 0")
        if args.speculate == "draft":
            draft_model = TransformerLM(
                vocab_size=args.vocab, d_model=max(16, args.d_model // 2),
                n_heads=max(1, args.heads // 2), n_layers=1,
                max_len=args.prefill_len + args.max_new,
            )
            draft_params = draft_model.init(jax.random.PRNGKey(2),
                                            init_tok)
            spec_cfg = SpeculativeConfig(k=args.spec_k, drafter="draft",
                                         draft_model=draft_model,
                                         draft_params=draft_params)
        else:
            spec_cfg = SpeculativeConfig(k=args.spec_k)
    engine_kw = dict(
        speculative=spec_cfg,
        n_slots=args.slots, prefill_len=args.prefill_len,
        prefill_buckets=buckets, prefill_batch=args.prefill_batch,
        prefix_cache_blocks=args.prefix_blocks,
        prefix_block_size=args.prefix_block_size,
        temperature=args.temperature, comm=comm,
        watchdog=args.watchdog or None, **paged_kw,
    )
    if args.canary and not args.autoscale:
        raise SystemExit("--canary deploys through the controller; add "
                         "--autoscale")
    # overload robustness (ISSUE 18): weighted-fair admission + the
    # brownout ladder ride the same scheduler kwargs in both the
    # single-engine client and every fleet replica
    fair_kw = {}
    if args.tenant_weights:
        weights = {}
        for pair in args.tenant_weights.split(","):
            name, _, w = pair.partition("=")
            if not w:
                raise SystemExit(f"--tenant-weights: '{pair}' is not "
                                 "name=weight")
            weights[name.strip()] = float(w)
        fair_kw = dict(fair=True, tenant_weights=weights)
    brownout_policy = None
    if args.brownout:
        from chainermn_tpu.serving.fairness import BrownoutPolicy

        brownout_policy = BrownoutPolicy(
            max_level=args.brownout, queue_high=float(args.slots),
            up_after_s=0.05, down_after_s=0.2, cooldown_s=0.1)
        fair_kw["brownout"] = brownout_policy
    if args.chunk_tokens and not args.paged_kv:
        raise SystemExit("--chunk-tokens stages chunks on the shared "
                         "block store; add --paged-kv")
    tiered = bool(args.prefill_replicas or args.decode_replicas)
    if tiered:
        if not (args.prefill_replicas and args.decode_replicas):
            raise SystemExit("disaggregated tiers need BOTH "
                             "--prefill-replicas and --decode-replicas")
        if not args.paged_kv:
            raise SystemExit("KV migration moves block-store rows; the "
                             "tiers need --paged-kv")
        if args.autoscale:
            raise SystemExit("--autoscale resizes a symmetric fleet; "
                             "static tiers don't mix with it")
    fleet_mode = args.replicas > 1 or args.autoscale or tiered
    if args.share_prefixes or args.rebalance:
        if not args.paged_kv:
            raise SystemExit("--share-prefixes/--rebalance move "
                             "block-store rows; add --paged-kv")
        if not fleet_mode:
            raise SystemExit("--share-prefixes/--rebalance need a fleet; "
                             "add --replicas 2 (or more)")
        if args.share_prefixes and not args.affinity:
            raise SystemExit("--share-prefixes finds holders through the "
                             "affinity trie; drop --no-affinity")
    n_start = (args.prefill_replicas + args.decode_replicas if tiered
               else max(args.replicas, args.min_replicas)
               if args.autoscale else args.replicas)
    eos = None if args.eos_id < 0 else args.eos_id
    if fleet_mode:
        from chainermn_tpu.fleet import FleetRouter

        engines = [ServingEngine(model, params, **engine_kw)
                   for _ in range(n_start)]
        engine = engines[0]
        tier_kw = dict(prefill_replicas=args.prefill_replicas,
                       decode_replicas=args.decode_replicas) if tiered \
            else {}
        front = FleetRouter(engines, eos_id=eos, affinity=args.affinity,
                            max_queue=args.max_queue or None,
                            default_deadline_s=args.deadline or None,
                            chunk_tokens_per_step=args.chunk_tokens
                            or None,
                            share_prefixes=args.share_prefixes,
                            **tier_kw, **fair_kw)
        front.wait_ready(600)   # every replica warm, off the burst clock
    else:
        engine = ServingEngine(model, params, **engine_kw)
        engine.warmup()   # every bucket + decode compile once, off the burst
        front = ServingClient(engine, eos_id=eos,
                              max_queue=args.max_queue or None,
                              default_deadline_s=args.deadline or None,
                              chunk_tokens_per_step=args.chunk_tokens
                              or None, **fair_kw)

    collector = None
    if args.health or args.autoscale:
        from chainermn_tpu.monitor.health import (
            HealthMonitor,
            fleet_health,
            standard_replica_sensors,
        )
        from chainermn_tpu.monitor.timeseries import Collector

        if fleet_mode:
            # per-replica sensors + lifecycle probes + routing penalty,
            # wired in one call
            collector = fleet_health(front, cadence_s=args.ts_cadence,
                                     stall_timeout_s=30.0)
        else:
            collector = Collector(cadence_s=args.ts_cadence)
            inst = front.metrics.instance
            sigs, dets = standard_replica_sensors(
                inst, stall_timeout_s=30.0, tag="0")
            for sg in sigs:
                collector.add_signal(sg)
            for dt in dets:
                collector.add_detector(dt)
            health_mon = HealthMonitor(store=collector.store)
            health_mon.watch("0", detectors=dets)
            collector.attach_health(health_mon)
            front.metrics.attach_health(
                lambda m=health_mon: m.score_json("0"))
        collector.start()

    controller = None
    if args.autoscale:
        from chainermn_tpu.fleet import (
            AutoscalePolicy,
            CanaryPolicy,
            FleetController,
        )

        controller = FleetController(
            front, collector,
            engine_factory=lambda: ServingEngine(model, params,
                                                 **engine_kw),
            autoscale=AutoscalePolicy(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                queue_high=1.0, idle_low=0.25, up_after_s=0.2,
                down_after_s=1.0, cooldown_s=0.3),
            canary=CanaryPolicy(bake_s=args.canary_bake),
            cadence_s=0.05, sensor_kw=dict(stall_timeout_s=30.0))
        controller.start()

    monitor.get_tracer().configure(sample=args.trace)
    slo_engine = None
    if args.slo_ttft_ms:
        slo_engine = monitor.SLOEngine()
        slo_engine.add(monitor.LatencyObjective(
            "ttft_p99", "serving_ttft_seconds",
            threshold_s=args.slo_ttft_ms / 1e3, windows=(30.0, 120.0)))
    server = None
    if args.http_port >= 0:
        server = monitor.http.serve(
            port=args.http_port, slo=slo_engine,
            fleet=front if fleet_mode else None,
            timeseries=collector,
            health=collector.health if collector is not None else None,
            controller=controller,
            costs=None if fleet_mode else front.metrics.costs)
        print(f"monitor endpoints at {server.url} "
              "(/metrics /traces /slo /events /fleet /timeseries "
              "/health /control /costs)")
    shared = (rng.randint(2, args.vocab, args.shared_prefix)
              .astype(np.int32) if args.shared_prefix else
              np.zeros((0,), np.int32))
    t0 = time.time()
    rejected = shed_or_failed = 0
    parity_jobs = []
    with monitor.annotate("chainermn.serve_lm_burst"), front as client:
        # one streaming request: tokens arrive as they are decoded
        tail_max = max(1, args.prefill_len - len(shared))
        stream_toks: list[int] = []
        streamed = client.submit(
            np.concatenate([shared,
                            rng.randint(2, args.vocab, min(5, tail_max))
                            .astype(np.int32)]),
            args.max_new,
            rng=jax.random.PRNGKey(1), stream_cb=stream_toks.append)
        # a burst of blocking requests with ragged prompt (tail) lengths;
        # with --shared-prefix they all share a system prompt, so a
        # prefix-cached engine prefills only the ragged tails. With
        # --max-queue the bounded queue may bounce some (backpressure is
        # the submitter's signal — a real client would retry later)
        handles = []
        tenants = [f"tenant{j}" for j in range(max(args.tenants, 1))] \
            if args.tenants > 1 else ["default"]
        for i in range(args.requests - 1):
            prompt = np.concatenate([shared, rng.randint(
                2, args.vocab, rng.randint(1, tail_max + 1))
                .astype(np.int32)])
            n_new = int(rng.randint(1, args.max_new + 1))
            key = jax.random.PRNGKey(100 + i)
            prio = ("batch" if args.priority == "batch"
                    or (args.priority == "mixed" and i % 2 == 1)
                    else "interactive")
            try:
                h = client.submit(prompt, n_new, rng=key,
                                  tenant=tenants[i % len(tenants)],
                                  priority=prio)
                handles.append(h)
                parity_jobs.append((h, prompt, n_new, key))
            except QueueFullError:
                rejected += 1
        rebalanced = None
        if args.rebalance:
            # the probe: pick the busiest replica while the burst is in
            # flight and ask the router to move one live decode off it
            # (a False just means nothing was mid-decode to move — the
            # demo burst may drain faster than the handshake)
            snaps = [r.snapshot() for r in client.replicas]
            busy = [s for s in snaps if s.active_slots > 0]
            src = max(busy or snaps,
                      key=lambda s: s.active_slots).replica_id
            ticket = client.rebalance_decode(src)
            rebalanced = (bool(ticket.wait(30))
                          if ticket is not None else False)
        for h in handles + [streamed]:
            try:
                h.wait(timeout=600)
            except Exception as e:  # shed past --deadline, or engine-failed
                shed_or_failed += 1
                print(f"request {h.id}: {type(e).__name__}: {e}")
        if controller is not None and args.canary:
            # the canary path end to end: bumped weights onto ONE
            # replica, bake against the fleet baseline, promote (or
            # auto-rollback) — driven entirely by the background loop
            new_params = jax.tree_util.tree_map(
                lambda a: a + jnp.asarray(0.01, a.dtype), params)
            controller.deploy(new_params, step=1)
            deadline = time.time() + 120
            outcome = None
            while time.time() < deadline:
                crep = controller.report()
                outcome = (crep["canary"] or {}).get("last_outcome")
                if outcome is not None and crep["phase"] == "idle":
                    break
                time.sleep(0.05)
            assert outcome is not None, "canary deploy never resolved"
            print(f"canary deploy: {outcome['action']} "
                  f"(replica {outcome['replica']}, "
                  f"version {outcome.get('version')})")
        if controller is not None:
            crep = controller.report()
            cur = crep["versions"]["current"]
            print(f"controller: capacity={crep['capacity']} "
                  f"target={crep['target_replicas']} "
                  f"scale_ups={crep['autoscale']['scale_ups']} "
                  f"scale_downs={crep['autoscale']['scale_downs']}")
            for d in crep["decisions"]:
                print(f"  decision: {d}")
            print(f"weights: version={cur['version']} ({cur['source']}) "
                  f"history={[(h['version'], h['source']) for h in crep['versions']['history']]}")
            controller.stop()
        if fleet_mode:
            fleet_rep = client.fleet_report()
            pooled_ttft = fleet_rep["pooled"]["histograms"].get(
                "serving_ttft_seconds", {})
            report = {
                "fleet_requests_total": fleet_rep["requests_total"],
                "fleet_reroutes_total": fleet_rep["reroutes_total"],
                "fleet_shed_total": fleet_rep["shed_total"],
                "fleet_capacity": fleet_rep["capacity"],
                "affinity_hit_rate": fleet_rep["affinity"]["hit_rate"],
                "ttft_p50_s": pooled_ttft.get("p50_s"),
                "ttft_p99_s": pooled_ttft.get("p99_s"),
                "tokens_generated": fleet_rep["pooled"]["counters"].get(
                    "serving_tokens_total", 0),
            }
            cost_rep = fleet_rep.get("costs")
        else:
            report = client.metrics.report()
            # printed as its own table below, not as one mega-line
            cost_rep = report.pop("costs", None)

    print(f"streamed request: {len(stream_toks)} tokens "
          f"(first few: {stream_toks[:8]})")
    done = sum(1 for h in handles if h.state.value == "done") \
        + (streamed.state.value == "done")
    print(f"{done}/{args.requests} requests served in "
          f"{time.time() - t0:.2f}s through {args.slots} slots "
          f"({rejected} rejected at admission, {shed_or_failed} "
          "shed/failed)")
    for k, v in sorted(report.items()):
        print(f"  {k}: {v}")
    if cost_rep:
        # the tenant bill: who consumed the device, and how much of the
        # measured time did useful work (the goodput breakdown)
        dt = cost_rep["device_time"]
        gp = cost_rep["goodput"]
        print(f"cost accounting: measured={dt['measured_s']}s "
              f"attributed={dt['attributed_s']}s over "
              f"{dt['dispatches']} dispatches "
              f"(conservation_error={dt['conservation_error']})")
        print("  goodput: " + ", ".join(
            f"{k}={v}" for k, v in gp.items()))
        for tenant, row in sorted(cost_rep["tenants"].items()):
            print(f"  tenant {tenant}: device={row['device_total_s']}s "
                  f"{row['device_s']} kv_block_s={row['kv_block_s']} "
                  f"queue_wait_s={row['queue_wait_s']}")
    if brownout_policy is not None:
        bj = brownout_policy.to_json()
        print(f"brownout episode: steps={bj['steps']} "
              f"final_level={bj['level']} ({bj['action']}) "
              f"last_reason={bj['last_reason']}")
    if args.verify_parity:
        from chainermn_tpu.models import generate as solo_generate

        checked = 0
        for h, prompt, n_new, key in parity_jobs:
            if h.state.value != "done" or checked >= 3:
                continue
            ref = np.asarray(solo_generate(
                model, params, jnp.asarray(prompt)[None], n_new,
                temperature=args.temperature, rng=key, eos_id=eos,
                comm=comm)[0])
            out = h.output
            assert np.array_equal(out, ref[:len(out)]), (
                f"request {h.id} diverged from solo generate()")
            checked += 1
        print(f"parity vs solo generate: OK ({checked} requests)")
    if fleet_mode:
        for r in front.replicas:
            print(f"replica {r.replica_id}: state={r.state.value} "
                  f"served={r.metrics.requests_completed} "
                  f"executables={r.engine.compile_counts_detailed()} "
                  "(zero recompiles after warmup)")
        print("fleet: " + ", ".join(
            f"{k}={v}" for k, v in fleet_rep["affinity"].items()))
        if args.share_prefixes or args.rebalance:
            kr = fleet_rep["kv_reuse"]
            pc = kr.get("payload_cache") or {}
            print(f"kv reuse: share_enabled={kr['share_enabled']} "
                  f"shares={kr['shares']} rebalances={kr['rebalances']} "
                  f"payload_cache_hits={pc.get('hits', 0)} "
                  f"payload_cache_entries={pc.get('entries', 0)} "
                  f"payload_cache_imports={pc.get('imports', 0)}")
        if args.rebalance:
            print(f"rebalance probe: moved={rebalanced}")
        if fleet_rep.get("tiers"):
            from chainermn_tpu.monitor._state import get_registry

            mig = sum(v for k, v in
                      get_registry().snapshot()["counters"].items()
                      if k.startswith("kv_migrations_total"))
            print(f"tiers: prefill={fleet_rep['tiers']['prefill']} "
                  f"decode={fleet_rep['tiers']['decode']} "
                  f"kv_migrations_total={mig}")
    else:
        if engine.prefix_enabled:
            print("prefix cache: " + ", ".join(
                f"{k}={v}" for k, v in engine.prefix_stats().items()))
        if engine.paged:
            print("paged KV: " + ", ".join(
                f"{k}={v}" for k, v in engine.kv_stats().items()))
        if engine.spec_enabled:
            print("speculative: " + ", ".join(
                f"{k}={v}" for k, v in engine.spec_stats().items()))
        print(f"engine executables: {engine.compile_counts_detailed()} "
              "(zero recompiles after warmup)")
    if slo_engine is not None:
        import json

        ev = slo_engine.evaluate()
        for name, entry in ev.items():
            print(f"SLO {name}: compliant={entry['compliant']} "
                  f"max_burn_rate={entry['max_burn_rate']} "
                  f"windows={json.dumps(entry['windows'])}")
    if args.trace_out:
        tracer = monitor.get_tracer()
        n = len(tracer.finished())
        tracer.export_chrome(args.trace_out)
        print(f"wrote {n} trace(s) to {args.trace_out} "
              "(load in chrome://tracing or ui.perfetto.dev)")
    if collector is not None:
        collector.stop()
        hm = collector.health
        hrep = hm.report() if hm is not None else {}
        print(f"health: worst={hrep.get('worst')} over "
              f"{hrep.get('n_watched', 0)} replica(s), "
              f"{len(collector.store.names())} series, "
              f"{collector.ticks} ticks")
        for key, score in sorted(hrep.get("replicas", {}).items()):
            print(f"  replica {key}: {score['state']} "
                  f"(contributing: {score['contributing'] or 'none'})")
        if server is not None:
            # scrape our own endpoints over the real socket — the same
            # JSON any external prober would see
            import json as _json
            from urllib.request import urlopen

            with urlopen(f"{server.url}/health", timeout=10) as r:
                scraped = _json.loads(r.read())
            with urlopen(f"{server.url}/timeseries?last=8",
                         timeout=10) as r:
                ts_scraped = _json.loads(r.read())
            print(f"scraped /health: worst={scraped.get('worst')}; "
                  f"/timeseries: {ts_scraped.get('n_series', 0)} series")
    if server is not None:
        import json as _json
        from urllib.request import urlopen

        with urlopen(f"{server.url}/costs", timeout=10) as r:
            cost_scraped = _json.loads(r.read())
        if cost_scraped:
            print(f"scraped /costs: {len(cost_scraped['tenants'])} "
                  "tenant(s), conservation_error="
                  f"{cost_scraped['device_time']['conservation_error']}")
    if server is not None:
        server.close()
    if args.prometheus:
        print("\n# process metrics registry (Prometheus exposition)")
        print(monitor.exposition(), end="")


if __name__ == "__main__":
    main()
