#!/usr/bin/env python
"""Language-model training — the long-context / MoE extension workload.

No counterpart in the reference (it predates attention; SURVEY.md S2.16
marks SP/CP/EP absent) — this script is the user-facing entry to the
TPU-first extensions: sequence-parallel ring/Ulysses attention
(``--seq-parallel``), Pallas flash attention (``--attention flash``), and
expert-parallel MoE blocks (``--moe-experts N``).

Synthetic data: a deterministic k-th order Markov character stream — real
next-token structure (loss can drop well below uniform) with zero I/O.

Run (2+ emulated devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/train_lm.py --iterations 30 --moe-experts 8
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm/train_lm.py --iterations 30 --seq-parallel \
        --attention ring --seq-len 256
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform

apply_env_platform()
from chainermn_tpu import monitor  # noqa: E402
from chainermn_tpu.models import TransformerLM  # noqa: E402
from chainermn_tpu.training import jit_lm_train_step  # noqa: E402


def _dump_traces(args) -> None:
    """``--trace-out``: export whatever span trees the run retained
    (training.fit and the resilient trainer trace every step through the
    default tracer) as a Perfetto-loadable Chrome trace file."""
    if not getattr(args, "trace_out", ""):
        return
    tracer = monitor.get_tracer()
    n = len(tracer.finished())
    tracer.export_chrome(args.trace_out)
    print(f"wrote {n} trace(s) to {args.trace_out} "
          "(load in chrome://tracing or ui.perfetto.dev)")


def markov_stream(n_tokens: int, vocab: int, order: int = 2, seed: int = 0):
    """Deterministic k-th order Markov chain over ``vocab`` symbols."""
    rng = np.random.RandomState(seed)
    table = rng.randint(0, vocab, (vocab,) * order)
    out = np.zeros(n_tokens, np.int32)
    out[:order] = rng.randint(0, vocab, order)
    for i in range(order, n_tokens):
        ctx = tuple(out[i - order : i])
        # mostly-deterministic transitions with a little noise
        out[i] = table[ctx] if rng.rand() < 0.9 else rng.randint(0, vocab)
    return out


def _stream_data(args):
    """(tokens, targets, n_seq) arrays from the Markov stream — shared by
    every mode's data prep."""
    stream = markov_stream(args.n_tokens, args.vocab)
    n_seq = (len(stream) - 1) // args.seq_len
    toks = stream[: n_seq * args.seq_len].reshape(n_seq, args.seq_len)
    tgts = stream[1 : n_seq * args.seq_len + 1].reshape(n_seq, args.seq_len)
    return toks, tgts, n_seq


def _serve_samples(args, comm, model, params, tokens_all):
    """Training-to-serving in one script: push ``--serve-samples``
    continuations of the trained model through the serving fast path
    (bucketed batched prefill + ref-counted prefix KV reuse,
    :mod:`chainermn_tpu.serving`). All prompts share the stream's opening
    context, so after the first admission every later one hits the prefix
    cache and prefills only its ragged tail — the shared-system-prompt
    traffic shape the cache exists for. Skipped for sharded-model modes
    (rebuild without sequence/tensor sharding to serve; see
    ``serve_lm.py``)."""
    if comm.rank != 0:
        return
    if args.seq_parallel or args.tensor_parallel:
        print("serve-samples: skipped (sequence/tensor-sharded training "
              "model; rebuild dense for inference — see serve_lm.py)")
        return
    from chainermn_tpu.serving import ServingClient, ServingEngine

    infer = (model.clone(moe_impl="gshard") if model.moe_experts
             else model)
    params = jax.device_get(params)           # host copy: plain-jit serve
    ctx_len = min(args.seq_len // 2, 24)
    ctx = np.asarray(tokens_all[0][:ctx_len], np.int32)
    tail_src = np.asarray(tokens_all[1], np.int32)
    bucket_small = 8
    prefill_len = ctx_len + bucket_small
    engine = ServingEngine(
        infer, params, n_slots=4,
        prefill_buckets=(bucket_small, prefill_len), prefill_batch=4,
        prefix_cache_blocks=32, prefix_block_size=4,
        cache_len=prefill_len + 16)
    engine.warmup()
    n = args.serve_samples
    print(f"serving {n} shared-context continuations "
          f"(ctx={ctx_len} tokens, prefix-cached, bucketed prefill):")
    with ServingClient(engine) as client:
        reqs = [client.submit(
            np.concatenate([ctx, tail_src[: 1 + i % bucket_small]]), 12,
            rng=jax.random.PRNGKey(i)) for i in range(n)]
        for i, req in enumerate(reqs):
            req.wait(timeout=300)
            print(f"  sample {i}: ...{[int(t) for t in req.output[-8:]]}")
    stats = engine.prefix_stats()
    print(f"prefix cache: hit_rate={stats['hit_rate']} "
          f"hits={stats['hits']} inserted_blocks="
          f"{stats['inserted_blocks']}; executables="
          f"{engine.compile_counts_detailed()} (zero recompiles)")


class _OnlinePublisher:
    """``--publish-to engine``: the online train→serve loop (ISSUE 10).

    A live serving engine (initial weights) plus its background client
    thread come up BEFORE training starts; every ``--publish-every``
    iterations the freshly trained params hot-swap in through the deploy
    version fence — the client thread drains the fence, which is what
    makes the blocking ``publish`` from the training loop safe — a
    continuation samples at the new version, and training continues.
    The jit cache is asserted unchanged across every swap at close."""

    def __init__(self, args, model, params, tokens_all) -> None:
        from chainermn_tpu.deploy import WeightPublisher
        from chainermn_tpu.serving import ServingClient, ServingEngine

        infer = (model.clone(moe_impl="gshard") if model.moe_experts
                 else model)
        ctx_len = min(args.seq_len // 2, 16)
        self._ctx = np.asarray(tokens_all[0][:ctx_len], np.int32)
        self.every = args.publish_every or max(1, args.iterations // 2)
        self._engine = ServingEngine(
            infer, jax.device_get(params), n_slots=2,
            prefill_len=ctx_len, cache_len=ctx_len + 16)
        self._engine.warmup()
        self._client = ServingClient(self._engine)
        self._pub = WeightPublisher(self._engine, self._client.scheduler)
        self._counts = dict(self._engine.compile_counts_detailed())
        self._sample("serving v0 (initial weights)")

    def _sample(self, label: str) -> None:
        out = self._client.generate(
            self._ctx, 12,
            rng=jax.random.PRNGKey(self._engine.weight_version),
            timeout=300)
        print(f"{label}: ...{[int(t) for t in out[-8:]]}")

    def publish(self, it: int, params) -> None:
        # host copy, like --serve-samples: the engine runs plain-jit
        # uncommitted leaves and the publisher re-places to match them
        v = self._pub.publish(jax.device_get(params), step=it,
                              timeout=120.0)
        self._sample(f"published v{v} at iter {it}")

    def close(self) -> None:
        assert dict(self._engine.compile_counts_detailed()) == self._counts
        self._client.close()
        print(f"publish-to engine: weight_version="
              f"{self._engine.weight_version}, zero recompiles across "
              "swaps")


def _save_snapshot(args, comm, model, params) -> None:
    """``--snapshot-to``: step-stamped sharded snapshot of the trained
    params with the resharding manifest (mesh shape, TP degree, head
    geometry) — what ``serve_lm.py --reshard-from`` consumes, on any
    mesh shape or TP degree."""
    from chainermn_tpu.deploy import snapshot_meta
    from chainermn_tpu.extensions.sharded_checkpoint import (
        ShardedCheckpointer,
    )

    meta = snapshot_meta(comm=comm, model=model)
    with ShardedCheckpointer(args.snapshot_to) as cp:
        cp.save(args.iterations, {"params": params}, meta=meta)
    if comm.rank == 0:
        print(f"snapshot -> {args.snapshot_to} (step {args.iterations}, "
              f"tp_degree={meta.get('tp_degree', 1)})")


def _drop_suffix(acc) -> str:
    """Footer fragment for the aggregated MoE drop telemetry ('' when the
    run had no MoE steps) — shared by every mode's final log line."""
    s = acc.summary()
    if not s["steps"]:
        return ""
    return (f"  moe_drop mean {s['moe_drop_frac_mean']:.1%} "
            f"max {s['moe_drop_frac_max']:.1%}")


def _sequential_train_loop(args, comm, step, params, opt_state,
                           toks, tgts, n_seq, batch):
    """The shared strided train/telemetry loop for the pipeline and gspmd
    modes (no shuffling): one place for the compile-time exclusion, tok/s
    logging, MoE drop aggregation, and the final footer. Steps may return
    3-tuples (pipeline) or the uniform 4-tuple (gspmd)."""
    from chainermn_tpu.parallel import MoeStatsAccumulator

    t0, seen, first, loss = time.time(), 0, None, None
    acc = MoeStatsAccumulator()
    for it in range(1, args.iterations + 1):
        i = (it * batch) % max(1, n_seq - batch)
        out = step(
            params, opt_state, jnp.asarray(toks[i : i + batch]),
            jnp.asarray(tgts[i : i + batch]))
        params, opt_state, loss = out[:3]
        acc.update(out[3] if len(out) > 3 else {})
        if it == 1:
            jax.block_until_ready(loss)
            first = float(loss)
            t0, seen = time.time(), 0
            if comm.rank == 0:
                print(f"compiled; first loss {first:.3f}")
        seen += batch * args.seq_len
        if it % 20 == 0 and comm.rank == 0:
            print(f"iter {it:4d}  loss {float(loss):.3f}  "
                  f"{seen / (time.time() - t0):.0f} tok/s")
    if comm.rank == 0 and loss is not None:
        print(f"done: loss {first:.3f} -> {float(loss):.3f}"
              f"{_drop_suffix(acc)}")
    return params, opt_state


def run_gspmd(args, comm) -> None:
    """Megatron weights-at-rest: the DENSE TransformerLM under plain jit,
    params + optimizer state sharded ~1/n per device (parallel.gspmd);
    MoE uses the gshard einsum-dispatch twin."""
    from chainermn_tpu.parallel import (
        gspmd_lm_train_step,
        megatron_opt_shard,
        megatron_shard,
    )

    model = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers,
        max_len=args.max_len or max(args.seq_len, 512),
        attention=args.attention,  # 'full' or 'flash' (guarded in main)
        moe_experts=args.moe_experts, moe_impl="gshard",
        moe_top_k=args.moe_top_k,
        remat=args.remat,
        compute_dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    toks, tgts, n_seq = _stream_data(args)
    batch = args.batchsize
    if n_seq < batch:
        raise SystemExit(f"need >= {batch} sequences, have {n_seq}")

    params = megatron_shard(
        model.init(jax.random.PRNGKey(0), jnp.asarray(toks[:1])), comm)
    optimizer = optax.adam(args.lr)
    opt_state = megatron_opt_shard(
        optimizer, jax.jit(optimizer.init)(params), params, comm)
    step = gspmd_lm_train_step(model, optimizer, comm)

    def frac(tree):
        tot = loc = 0
        for _, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if hasattr(leaf, "sharding") and leaf.shape:
                tot += leaf.size
                loc += int(np.prod(leaf.sharding.shard_shape(leaf.shape)))
        return loc / max(tot, 1)

    if comm.rank == 0:
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        print(f"{n_params / 1e6:.2f}M params  gspmd megatron layout  "
              f"per-device fraction: params {frac(params):.3f}, "
              f"opt {frac(opt_state):.3f} (1/n = {1 / comm.size:.3f})")
    _sequential_train_loop(args, comm, step, params, opt_state,
                           toks, tgts, n_seq, batch)


def run_pipeline(args, comm) -> None:
    """Pipeline-parallel LM: n_stages = mesh size, one causal transformer
    block resident per rank, stage params stacked P(axis); the GPipe
    fill-drain schedule microbatches each step (ops.pipeline)."""
    from chainermn_tpu.ops import (
        init_pipeline_lm,
        jit_pp_lm_train_step,
        make_pipeline_lm,
        pp_lm_opt_init,
    )

    n_stages = comm.size
    mods = make_pipeline_lm(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_stages=n_stages, max_len=args.max_len or max(args.seq_len, 512),
        compute_dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    toks, tgts, n_seq = _stream_data(args)
    batch = args.batchsize * args.microbatches
    if n_seq < batch:
        raise SystemExit(f"need >= {batch} sequences, have {n_seq}")

    params = init_pipeline_lm(
        mods, jax.random.PRNGKey(0), jnp.asarray(toks[:1]), n_stages)
    optimizer = optax.adam(args.lr)
    opt_state = pp_lm_opt_init(optimizer, params)
    step = jit_pp_lm_train_step(mods, optimizer, comm,
                                n_microbatches=args.microbatches)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    bubble = (n_stages - 1) / (args.microbatches + n_stages - 1)
    if comm.rank == 0:
        print(f"{n_params / 1e6:.2f}M params  pipeline stages={n_stages} "
              f"microbatches={args.microbatches} "
              f"(bubble fraction {bubble:.1%})")
    _sequential_train_loop(args, comm, step, params, opt_state,
                           toks, tgts, n_seq, batch)


def run_resilient(args, comm, step, params, opt_state,
                  tokens_all, targets_all, n_seq, batch) -> None:
    """``--resume``: the same jitted step driven by
    :func:`chainermn_tpu.resilience.resilient_fit` — periodic snapshots of
    (params, optimizer state, iterator position), a step-level exception
    boundary that restores the newest intact snapshot on failure, and
    cross-launch resume: rerunning the command continues from where the
    last launch stopped, on the same loss trajectory."""
    import chainermn_tpu.resilience as resilience

    ckpt = chainermn_tpu.create_multi_node_checkpointer(
        "train_lm", comm, path=args.checkpoint_dir)
    # drop the ragged tail (as the non-resume loop's generator does): the
    # sharded step needs every batch exactly `batch` rows
    it = chainermn_tpu.SerialIterator(
        list(range(n_seq - n_seq % batch)), batch_size=batch,
        shuffle=True, seed=1)

    def step_fn(state, sel):
        sel = np.asarray(sel)
        p, o, loss, _ = step(state["params"], state["opt_state"],
                             jnp.asarray(tokens_all[sel]),
                             jnp.asarray(targets_all[sel]))
        return {"params": p, "opt_state": o, "loss": float(loss)}

    def restore_hook(state):
        # snapshots hold host arrays; put them back with the step's
        # (replicated) shardings so the resumed trajectory is bit-exact
        return {
            "params": jax.device_put(state["params"],
                                     comm.named_sharding()),
            "opt_state": jax.device_put(state["opt_state"],
                                        comm.named_sharding()),
            "loss": state["loss"],
        }

    def on_step(i, state):
        if (i + 1) % 20 == 0 and comm.rank == 0:
            print(f"iter {i + 1:4d}  loss {state['loss']:.3f}")

    injector = None
    if args.inject_fault:
        injector = resilience.FaultInjector(seed=0)
        injector.arm("trainer.step", kind="raise",
                     after=args.inject_fault, times=1)
        injector.install()
    try:
        state, report = resilience.resilient_fit(
            step_fn, {"params": params, "opt_state": opt_state,
                      "loss": None},
            it, args.iterations, ckpt, save_every=args.save_every,
            restore_hook=restore_hook, on_step=on_step,
            async_save=args.async_save)
    finally:
        if injector is not None:
            injector.uninstall()
    if comm.rank == 0:
        mttr = (f"  mttr {report['mttr_s'][0] * 1e3:.0f}ms"
                if report["mttr_s"] else "")
        print(f"done: loss {state['loss']:.3f}  resumed_from "
              f"{report['resumed_from']}  failures {report['failures']}  "
              f"restores {report['restores']}{mttr}")


def main() -> None:
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: LM")
    parser.add_argument("--vocab", type=int, default=64)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--batchsize", "-b", type=int, default=4,
                        help="per-rank batch (DP mode) / global batch (SP mode)")
    parser.add_argument("--iterations", type=int, default=100)
    parser.add_argument("--attention", default="full",
                        choices=["full", "ring", "ring_flash", "zigzag",
                                 "zigzag_flash", "ulysses", "ulysses_flash",
                                 "flash"])
    parser.add_argument("--seq-parallel", action="store_true",
                        help="shard the SEQUENCE axis over the mesh "
                             "(context parallelism); needs ring/zigzag/"
                             "ulysses (zigzag data is host-permuted "
                             "automatically)")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="expert-parallel MoE FFN every 2nd block")
    parser.add_argument("--fused-ce", action="store_true",
                        help="fused chunked head+loss: never materializes "
                             "the [B,T,vocab] f32 logits (the step's "
                             "largest tensor pair; ops/losses.py)")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialize block forwards in the backward "
                             "(jax.checkpoint): ~1/3 more forward FLOPs for "
                             "O(n_layers*B*T*d) less activation HBM — the "
                             "lever for long context / large token batches")
    parser.add_argument("--moe-top-k", type=int, default=1, choices=[1, 2],
                        help="1 = Switch routing, 2 = GShard top-2")
    parser.add_argument("--tensor-parallel", action="store_true",
                        help="Megatron-style TP: heads + FFN width sharded "
                             "over the mesh axis, batch replicated "
                             "(parallel.tensor; global-objective grads)")
    parser.add_argument("--gspmd", action="store_true",
                        help="GSPMD weights-at-rest: the dense model under "
                             "plain jit with Megatron param layouts (params"
                             "+opt ~1/n per device; parallel.gspmd). "
                             "Combines with --moe-experts via the gshard "
                             "einsum-dispatch MoE")
    parser.add_argument("--pipeline", action="store_true",
                        help="pipeline parallelism: one transformer block "
                             "per mesh rank (GPipe fill-drain microbatch "
                             "schedule; ops.pipeline)")
    parser.add_argument("--microbatches", type=int, default=8,
                        help="with --pipeline: microbatches per step "
                             "(bubble fraction = (S-1)/(M+S-1))")
    parser.add_argument("--vocab-parallel-head", action="store_true",
                        help="with --tensor-parallel: shard the LM head "
                             "over the vocab; full logits are never "
                             "materialized (sharded-vocab cross entropy)")
    parser.add_argument("--resume", action="store_true",
                        help="run the (plain-DP) training loop through "
                             "resilience.resilient_fit: periodic snapshots "
                             "(params + optimizer + iterator + loop "
                             "index), auto-restore on a step failure, and "
                             "cross-launch resume — rerun the same "
                             "command after a crash and it continues from "
                             "the newest intact snapshot")
    parser.add_argument("--checkpoint-dir", default="./lm_checkpoints",
                        help="with --resume: snapshot directory")
    parser.add_argument("--save-every", type=int, default=20,
                        help="with --resume: snapshot cadence in steps")
    parser.add_argument("--async-save", action="store_true",
                        help="with --resume: background checkpointing — "
                             "the loop blocks only on the device_get; "
                             "serialize + write + GC run on the "
                             "checkpointer's writer thread "
                             "(dataflow async hot loop)")
    parser.add_argument("--prefetch-depth", type=int, default=0,
                        help="device-prefetch the batch stream this many "
                             "batches ahead on a producer thread (H2D "
                             "overlaps the step; dataflow."
                             "DevicePrefetcher). 0: synchronous feeding")
    parser.add_argument("--fetch-every", type=int, default=1,
                        help="dispatch-ahead loss cadence: keep losses on "
                             "device and fetch them batched every K steps "
                             "(bounded in-flight window; loss prints lag "
                             "up to K-1 steps). 1: per-step fetch. With "
                             "either this >1 or --prefetch-depth the loop "
                             "runs through training.fit (per-step MoE "
                             "drop-fraction prints are skipped there)")
    parser.add_argument("--inject-fault", type=int, default=0,
                        help="with --resume: crash training at this step "
                             "(a seeded resilience.FaultInjector raise) "
                             "to demo the restore loop end to end "
                             "(0: off)")
    parser.add_argument("--serve-samples", type=int, default=0,
                        help="after training, serve this many shared-"
                             "context continuations through the serving "
                             "fast path (bucketed batched prefill + "
                             "prefix KV reuse) — training-to-serving in "
                             "one script (plain/MoE modes; 0: off)")
    parser.add_argument("--publish-to", default="",
                        help="online train->serve (ISSUE 10): 'engine' "
                             "stands up a live in-process serving engine "
                             "BEFORE training and hot-swaps the params "
                             "into it every --publish-every iterations "
                             "through the deploy version fence (zero "
                             "recompiles, traffic keeps flowing), "
                             "sampling a continuation at each version "
                             "(address-shaped targets are reserved for a "
                             "network front)")
    parser.add_argument("--publish-every", type=int, default=0,
                        help="with --publish-to: publish cadence in "
                             "iterations (default: half the run)")
    parser.add_argument("--snapshot-to", default="",
                        help="save a sharded snapshot of the trained "
                             "params (with the resharding manifest: mesh "
                             "shape, TP degree, head geometry) to this "
                             "directory — serve it on a DIFFERENT mesh/"
                             "TP degree via serve_lm.py --reshard-from")
    parser.add_argument("--trace-out", default="",
                        help="write the run's train-step span trees "
                             "(prefetch-wait / dispatch / loss-fetch / "
                             "checkpoint-enqueue) as Chrome trace-event "
                             "JSON to this path — load in "
                             "chrome://tracing or ui.perfetto.dev")
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--n-tokens", type=int, default=200_000)
    parser.add_argument("--max-len", type=int, default=None,
                        help="positional-embedding table size "
                             "(default: just enough for --seq-len)")
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator("tpu")
    if args.pipeline and (args.seq_parallel or args.moe_experts
                          or args.tensor_parallel):
        raise SystemExit("--pipeline uses the whole mesh axis for stages; "
                         "it does not combine with the other parallel "
                         "flags in this example")
    if args.pipeline and args.remat:
        raise SystemExit("--pipeline builds its blocks via make_pipeline_lm, "
                         "which does not thread --remat; the flag would be "
                         "silently ignored (pipeline microbatching already "
                         "bounds live activations to one microbatch per "
                         "stage)")
    if args.fused_ce and (args.pipeline or args.gspmd
                          or args.tensor_parallel):
        raise SystemExit("--fused-ce is the plain/sequence-parallel step's "
                         "fused head+loss; the pipeline/gspmd/TP paths "
                         "build their own steps and would silently ignore "
                         "it (TP's vocab-parallel head already avoids full "
                         "logits)")
    if args.gspmd and (args.seq_parallel or args.tensor_parallel
                       or args.pipeline):
        raise SystemExit("--gspmd is its own layout (plain jit, partitioner "
                         "collectives); it does not combine with "
                         "--seq-parallel/--tensor-parallel/--pipeline")
    if args.gspmd and args.attention not in ("full", "flash"):
        raise SystemExit("--gspmd runs the dense model; --attention must be "
                         "full or flash (sequence-sharded kinds need the "
                         "shard_map step)")
    if args.resume and (args.gspmd or args.pipeline):
        raise SystemExit("--resume wraps the plain/SP/TP/MoE train loop in "
                         "resilient_fit; the gspmd/pipeline modes build "
                         "their own loops and would silently ignore it")
    if (args.prefetch_depth or args.fetch_every > 1) and (
            args.gspmd or args.pipeline or args.resume):
        raise SystemExit("--prefetch-depth/--fetch-every drive the plain "
                         "loop through training.fit; the gspmd/pipeline/"
                         "resume modes build their own loops and would "
                         "silently ignore them")
    if args.publish_to and args.publish_to != "engine":
        raise SystemExit("--publish-to: only the in-process 'engine' "
                         "target exists (a network front would take an "
                         "address here)")
    if args.publish_to and (
            args.gspmd or args.pipeline or args.seq_parallel
            or args.tensor_parallel or args.resume
            or args.prefetch_depth or args.fetch_every > 1):
        raise SystemExit("--publish-to rides the plain synchronous train "
                         "loop (like --serve-samples): it does not "
                         "combine with the sharded-model, resume, or "
                         "async-loop flags")
    if args.snapshot_to and (args.gspmd or args.pipeline or args.resume):
        raise SystemExit("--snapshot-to snapshots the plain/SP/TP loop's "
                         "params; the gspmd/pipeline/resume modes own "
                         "their state layouts and would silently ignore "
                         "it")
    if args.gspmd:
        return run_gspmd(args, comm)
    if args.pipeline:
        if args.n_layers != parser.get_default("n_layers") and (
                args.n_layers != comm.size):
            raise SystemExit(
                f"--pipeline pins the layer count to one block per rank "
                f"({comm.size} here); --n-layers {args.n_layers} would be "
                "silently ignored")
        return run_pipeline(args, comm)
    if args.seq_parallel and args.attention not in (
            "ring", "ring_flash", "zigzag", "zigzag_flash", "ulysses",
            "ulysses_flash"):
        raise SystemExit("--seq-parallel needs --attention "
                         "ring|zigzag|ulysses (or a _flash variant)")
    if args.tensor_parallel and (args.seq_parallel or args.moe_experts):
        raise SystemExit("--tensor-parallel uses the whole flat mesh axis; "
                         "it does not combine with --seq-parallel or "
                         "--moe-experts in this example")
    if args.tensor_parallel and args.n_heads % comm.size:
        raise SystemExit(f"--tensor-parallel needs n_heads divisible by the "
                         f"{comm.size}-way mesh axis")
    if args.vocab_parallel_head and not args.tensor_parallel:
        raise SystemExit("--vocab-parallel-head needs --tensor-parallel")

    model = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers,
        max_len=args.max_len or max(args.seq_len, 512),
        attention=args.attention,
        sequence_axis=comm.axis_name if args.seq_parallel else None,
        moe_experts=args.moe_experts,
        moe_axis=comm.axis_name if args.moe_experts else None,
        moe_top_k=args.moe_top_k,
        tensor_axis=comm.axis_name if args.tensor_parallel else None,
        vocab_parallel_head=args.vocab_parallel_head,
        remat=args.remat,
        compute_dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
    )

    tokens_all, targets_all, n_seq = _stream_data(args)
    if args.seq_parallel and args.attention.startswith("zigzag"):
        # zigzag shards hold (early, late) chunk pairs: permute the data
        # once on the host; the mean loss is permutation-invariant
        from chainermn_tpu.parallel.sequence import zigzag_permutation

        perm = np.asarray(zigzag_permutation(args.seq_len, comm.size))
        tokens_all = tokens_all[:, perm]
        targets_all = targets_all[:, perm]

    if args.seq_parallel or args.tensor_parallel:
        # SP: the sequence axis shards over the mesh. TP: the WEIGHTS shard
        # over the mesh and the batch is replicated. Either way --batchsize
        # is already the global batch.
        batch = args.batchsize
    else:
        batch = args.batchsize * comm.size
    if n_seq < batch:
        raise SystemExit(
            f"only {n_seq} sequences of length {args.seq_len} in "
            f"{args.n_tokens} tokens but the global batch is {batch}; "
            "raise --n-tokens or lower --batchsize/--seq-len"
        )

    def batches():
        epoch = 0
        while True:
            order = np.random.RandomState(1 + epoch).permutation(n_seq)
            epoch += 1
            for i in range(0, n_seq - batch + 1, batch):
                sel = order[i : i + batch]
                yield tokens_all[sel], targets_all[sel]

    sample = jnp.asarray(tokens_all[:1])
    if args.moe_experts or args.seq_parallel or args.tensor_parallel:
        # collectives inside the model: init under the mesh
        from jax.sharding import PartitionSpec as P

        spec = (P(None, comm.axis_name) if args.seq_parallel
                else P() if args.tensor_parallel
                else comm.data_spec)
        init_tok = jnp.asarray(
            tokens_all[:batch]
            if not (args.seq_parallel or args.tensor_parallel)
            else tokens_all[:1]
        )
        params = jax.jit(comm.shard_map(
            lambda t: model.init(
                jax.random.PRNGKey(0), t[:1] if t.ndim > 1 else t),
            in_specs=spec, out_specs=P(),
        ))(init_tok)
    else:
        params = comm.bcast_data(model.init(jax.random.PRNGKey(0), sample))

    if args.tensor_parallel:
        # plain optax: the TP step's grads are already the exact global
        # gradient (global-objective pattern); a multi-node wrapper's extra
        # mean would shrink them by the axis size
        optimizer = optax.adam(args.lr)
    else:
        optimizer = chainermn_tpu.create_multi_node_optimizer(
            optax.adam(args.lr), comm
        )
    opt_state = jax.device_put(optimizer.init(params), comm.named_sharding())
    step = jit_lm_train_step(model, optimizer, comm,
                             shard_sequence=args.seq_parallel,
                             fused_ce=args.fused_ce)

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    if comm.rank == 0:
        print(f"{n_params / 1e6:.2f}M params  attention={args.attention} "
              f"seq_parallel={args.seq_parallel} moe={args.moe_experts} "
              f"tensor_parallel={args.tensor_parallel} devices={comm.size}")

    if args.resume:
        out = run_resilient(args, comm, step, params, opt_state,
                            tokens_all, targets_all, n_seq, batch)
        _dump_traces(args)
        return out

    if args.prefetch_depth or args.fetch_every > 1:
        # the async hot loop: batches device_put by a producer thread,
        # losses fetched batched — the host leaves the critical path
        from jax.sharding import PartitionSpec as P

        from chainermn_tpu.training import fit

        data_spec = (P(None, comm.axis_name) if args.seq_parallel
                     else P() if args.tensor_parallel
                     else comm.data_spec)

        def on_loss(i, v):
            if (i + 1) % 20 == 0 and comm.rank == 0:
                print(f"iter {i + 1:4d}  loss {v:.3f}")

        t0 = time.time()
        params, opt_state, losses = fit(
            step, params, opt_state, batches(), args.iterations,
            fetch_every=args.fetch_every,
            prefetch_depth=args.prefetch_depth,
            sharding=comm.named_sharding(*data_spec),
            transform=lambda b: (jnp.asarray(b[0]), jnp.asarray(b[1])),
            on_loss=on_loss, name="train_lm")
        if comm.rank == 0:
            tok_s = args.iterations * batch * args.seq_len / (
                time.time() - t0)
            print(f"done: {args.iterations} iterations (prefetch_depth="
                  f"{args.prefetch_depth}, fetch_every={args.fetch_every}),"
                  f" loss {losses[0]:.3f} -> {losses[-1]:.3f}  "
                  f"{tok_s:.0f} tok/s incl. compile")
        if args.snapshot_to:
            _save_snapshot(args, comm, model, params)
        _dump_traces(args)
        return

    from chainermn_tpu.parallel import MoeStatsAccumulator

    publisher = None
    if args.publish_to and comm.rank == 0:
        publisher = _OnlinePublisher(args, model, params, tokens_all)

    gen = batches()
    t0, toks = time.time(), 0
    first = last = None
    acc = MoeStatsAccumulator()
    tracer = monitor.get_tracer()
    for it in range(1, args.iterations + 1):
        # per-step span tree (same taxonomy as training.fit) so
        # --trace-out has causal data even from the synchronous loop
        with tracer.trace("train_step", kind="train", step=it):
            with tracer.span("prefetch_wait"):
                tok, tgt = next(gen)
            # uniform step arity: stats is {} for dense models
            with tracer.span("dispatch"):
                params, opt_state, loss, stats = step(
                    params, opt_state, jnp.asarray(tok), jnp.asarray(tgt))
        acc.update(stats)
        if it == 1:
            jax.block_until_ready(loss)
            first = float(loss)
            t0, toks = time.time(), 0
            if comm.rank == 0:
                print(f"compiled; first loss {first:.3f} "
                      f"(uniform = {np.log(args.vocab):.3f})")
        toks += tok.size
        if publisher is not None and it % publisher.every == 0:
            publisher.publish(it, params)
        if it % 20 == 0 and comm.rank == 0:
            last = float(loss)
            drop = (f"  moe_drop {float(stats['moe_drop_frac']):.1%}"
                    if stats else "")
            print(f"iter {it:4d}  loss {last:.3f}  "
                  f"{toks / (time.time() - t0):.0f} tok/s{drop}")
    last = float(loss)
    if comm.rank == 0:
        print(f"done: {args.iterations} iterations, "
              f"loss {first:.3f} -> {last:.3f}{_drop_suffix(acc)}")
    if publisher is not None:
        publisher.close()
    if args.snapshot_to:
        _save_snapshot(args, comm, model, params)
    if args.serve_samples:
        _serve_samples(args, comm, model, params, tokens_all)
    _dump_traces(args)


if __name__ == "__main__":
    main()
