#!/usr/bin/env python
"""ImageNet training — the reference's benchmark workload.

Parity target: ``[U] examples/imagenet/train_imagenet.py`` (SURVEY.md S2.15
— unverified cite): ResNet-50 (plus alex/googlenet model zoo) under the
pure_nccl communicator with fp16 allreduce and double buffering — the
configuration behind the 15-minute ImageNet run (BASELINE.md). TPU rebuild:
same flag surface, bf16 wire dtype, one fused SPMD step.

Data: ``--train-npz`` with arrays ``x`` (N,H,W,3 uint8) and ``y`` (N,) —
or synthetic ImageNet-shaped data (default) for throughput work.

Run (throughput mode, single host)::

    python examples/imagenet/train_imagenet.py --arch resnet50 \
        --batchsize 128 --iterations 50 --dtype bfloat16 --double-buffering

Run (the "15-minute ImageNet" TRAINING RECIPE, arXiv:1711.04325 — linearly
scaled LR ``0.1 x global_batch/256`` with warmup, label smoothing 0.1, top-1
eval on a held-out shard through the multi-node evaluator)::

    python examples/imagenet/train_imagenet.py --arch resnet50 \
        --batchsize 128 --epoch 90 --dtype bfloat16 --double-buffering \
        --recipe --train-npz /data/imagenet_train.npz
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform, ensure_batch_fits

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers
from chainermn_tpu import models
from chainermn_tpu.training import jit_train_step

ARCHS = {
    "resnet18": lambda n: models.ResNet18(num_classes=n),
    "resnet34": lambda n: models.ResNet34(num_classes=n),
    "resnet50": lambda n: models.ResNet50(num_classes=n),
    "resnet101": lambda n: models.ResNet101(num_classes=n),
    "resnet152": lambda n: models.ResNet152(num_classes=n),
    "alex": lambda n: models.AlexNet(num_classes=n),
    "googlenet": lambda n: models.GoogLeNet(num_classes=n),
    "vgg16": lambda n: models.VGG16(num_classes=n),
}


class SyntheticImageNet:
    """ImageNet-shaped synthetic records (uint8 images, int labels)."""

    def __init__(self, n: int, size: int = 224, classes: int = 1000, seed: int = 0):
        self._rng = np.random.RandomState(seed)
        self.n, self.size, self.classes = n, size, classes
        # small pool of random images, resampled by index (cheap, no 150GB)
        self._pool = self._rng.randint(0, 256, (64, size, size, 3), np.uint8)
        self._labels = self._rng.randint(0, classes, n).astype(np.int32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return self._pool[i % len(self._pool)], self._labels[i]


class NpzImageNet:
    def __init__(self, path: str):
        z = np.load(path)
        self.x, self.y = z["x"], z["y"].astype(np.int32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def collate(batch, dtype):
    from chainermn_tpu.native.dataloader import IMAGENET_MEAN, IMAGENET_STD

    xs, ys = zip(*batch)
    x = np.stack(xs).astype(np.float32) / 255.0
    # per-channel ImageNet normalization (reference subtracts a mean image);
    # constants shared with NativeBatchLoader so both input paths normalize
    # identically
    x = (x - np.array(IMAGENET_MEAN)) / np.array(IMAGENET_STD)
    return x.astype(dtype), np.asarray(ys, np.int32)


def record_source(ds):
    """(base_u8, rows, labels) view of a dataset for zero-copy native
    loading: ``rows[i]`` is sample i's row in ``base_u8`` (SyntheticImageNet
    aliases its small pool; SubDataset shards compose indices)."""
    from chainermn_tpu.datasets import SubDataset

    if isinstance(ds, SubDataset):
        base, rows, labels = record_source(ds._dataset)
        idx = np.asarray(ds.indices)
        return base, rows[idx], labels[idx]
    if isinstance(ds, SyntheticImageNet):
        rows = np.arange(len(ds), dtype=np.int64) % len(ds._pool)
        return ds._pool, rows, ds._labels
    if isinstance(ds, NpzImageNet):
        return ds.x, np.arange(len(ds), dtype=np.int64), ds.y
    raise TypeError(
        f"--native-loader supports the synthetic/npz datasets, got "
        f"{type(ds).__name__}"
    )


def main() -> None:
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: ImageNet")
    parser.add_argument("--arch", "-a", default="resnet50", choices=sorted(ARCHS))
    parser.add_argument("--batchsize", "-B", type=int, default=32,
                        help="per-participant batch size (reference default 32)")
    parser.add_argument("--epoch", "-E", type=int, default=1)
    parser.add_argument("--iterations", type=int, default=None,
                        help="stop after N iterations (throughput mode)")
    parser.add_argument("--communicator", default="tpu",
                        help="reference 'pure_nccl' maps to 'tpu'")
    parser.add_argument("--dtype", default="bfloat16",
                        choices=["float32", "bfloat16", "float16"],
                        help="allreduce wire dtype (reference allreduce_grad_dtype)")
    parser.add_argument("--double-buffering", action="store_true",
                        help="1-step-stale overlapped gradient averaging")
    parser.add_argument("--mnbn", action="store_true",
                        help="multi-node BatchNorm (cross-replica statistics)")
    parser.add_argument("--train-npz", default=None)
    parser.add_argument("--train-dir", default=None,
                        help="directory of JPEGs in class subfolders "
                             "(root/<class>/*.jpg): decoded by the native "
                             "libjpeg pipeline (PIL fallback), classes "
                             "inferred from the tree")
    parser.add_argument("--n-synthetic", type=int, default=100000)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--classes", type=int, default=1000)
    parser.add_argument("--lr", type=float, default=0.1,
                        help="base LR; under --recipe this is the per-256 "
                             "base of the linear scaling rule")
    parser.add_argument(
        "--recipe", action="store_true",
        help="the 15-minute-run training recipe (arXiv:1711.04325): "
             "LR = lr x global_batch/256 with linear warmup then cosine "
             "decay, label smoothing 0.1, per-epoch top-1 eval on a "
             "held-out shard via the multi-node evaluator",
    )
    parser.add_argument("--warmup-epochs", type=float, default=None,
                        help="LR warmup span (recipe default: 5)")
    parser.add_argument("--label-smoothing", type=float, default=None,
                        help="(recipe default: 0.1)")
    parser.add_argument("--val-frac", type=float, default=None,
                        help="held-out fraction for top-1 eval "
                             "(recipe default: 0.02)")
    parser.add_argument("--native-loader", action=argparse.BooleanOptionalAction,
                        default=None,
                        help="C++ batch assembly (gather + fused uint8->f32 "
                             "normalize, GIL-free threads) with one-batch "
                             "prefetch — the MultiprocessIterator slot. "
                             "Defaults ON under --recipe, where a failed "
                             "extension build degrades (all ranks together) "
                             "to numpy; an EXPLICIT --native-loader fails "
                             "hard instead")
    parser.add_argument("--device-prefetch", type=int, default=0,
                        help="wrap the pre-normalized input stream (native "
                             "C++ or JPEG loader) in a dataflow."
                             "DevicePrefetcher: a producer thread "
                             "device_puts N batches ahead with the step's "
                             "data sharding, so H2D overlaps the step "
                             "(0: feed synchronously)")
    parser.add_argument("--fsdp", action="store_true",
                        help="ZeRO-3 layout: params/grads/moments scattered "
                             "over the data axis, XLA-partitioner-inserted "
                             "gather/scatter (parallel.fsdp); BN statistics "
                             "become global-batch (sync-BN) by construction")
    args = parser.parse_args()

    if args.fsdp and (args.mnbn or args.double_buffering):
        # MNBN's explicit collectives need shard_map axis names, which the
        # FSDP global program doesn't have (its BN is already global-batch);
        # double buffering configures the explicit gradient collective the
        # FSDP step doesn't own.
        raise SystemExit("--fsdp is incompatible with --mnbn/--double-buffering")

    if args.recipe:
        if args.warmup_epochs is None:
            args.warmup_epochs = 5.0
        if args.label_smoothing is None:
            args.label_smoothing = 0.1
        if args.val_frac is None and not args.train_dir:
            args.val_frac = 0.02  # --train-dir has no array split to hold out
    # None = unspecified: the recipe defaults the native loader ON (the
    # measured ~3x assembly win, PERF.md); an explicit True keeps hard
    # errors, an explicit False (--no-native-loader) forces numpy
    native_explicit = args.native_loader is True
    if args.native_loader is None:
        args.native_loader = bool(args.recipe)
    args.warmup_epochs = args.warmup_epochs or 0.0
    args.label_smoothing = args.label_smoothing or 0.0
    args.val_frac = args.val_frac or 0.0

    chainermn_tpu.add_global_except_hook()
    # a non-float32 wire dtype is only meaningful for the tpu/pure_nccl
    # strategy; create_communicator raises on unsupported combinations
    # rather than silently running f32 (reference: pure_nccl-only flag)
    comm = chainermn_tpu.create_communicator(
        args.communicator,
        # FSDP has no explicit gradient collective to configure a wire dtype
        # on (the partitioner reduces in the gradient's own dtype)
        allreduce_grad_dtype=None if (args.dtype == "float32" or args.fsdp)
        else args.dtype,
    )
    if comm.rank == 0:
        wire = "n/a (fsdp: partitioner reduces in the gradient dtype)" \
            if args.fsdp else args.dtype
        print(f"arch={args.arch} communicator={args.communicator} "
              f"wire-dtype={wire} double_buffering={args.double_buffering} "
              f"devices={comm.size}")

    jpeg_it = None
    if args.train_dir:
        # JPEG-directory input: the loader shards the FILE LIST per process
        # and decodes via the native libjpeg pipeline (chainermn_tpu.native
        # .jpeg), so the array-dataset scatter machinery is bypassed.
        if args.train_npz:
            raise SystemExit("--train-dir and --train-npz are exclusive")
        if args.val_frac:
            raise SystemExit("--val-frac needs an array dataset and was "
                             "passed explicitly; with --train-dir hold out "
                             "a separate val/ tree instead")
        from chainermn_tpu.native import jpeg as jpeg_mod

        jpeg_it = jpeg_mod.JpegDirectoryLoader(
            args.train_dir, args.batchsize * comm.size,
            image_size=args.image_size, shuffle=True, seed=1,
            rank=jax.process_index(), size=comm.process_size,
        )
        args.classes = len(jpeg_it.class_names)  # labels come from the tree
        if comm.rank == 0:
            print(f"input pipeline: JPEG directory, "
                  f"{'native libjpeg' if jpeg_mod.native_available() else 'PIL fallback'}"
                  f", {args.classes} classes, "
                  f"{len(jpeg_it) * args.batchsize * comm.size} imgs/shard-epoch")
        dataset = val = train = val_shard = None
    else:
        dataset = (NpzImageNet(args.train_npz) if args.train_npz
                   else SyntheticImageNet(args.n_synthetic, args.image_size,
                                          args.classes))
        val = None
    if dataset is not None and args.val_frac:
        # hold out the tail as the eval shard (deterministic split so every
        # process agrees before scattering)
        from chainermn_tpu.datasets import SubDataset

        n_val = max(1, int(len(dataset) * args.val_frac))
        val = SubDataset(dataset, range(len(dataset) - n_val, len(dataset)))
        dataset = SubDataset(dataset, range(len(dataset) - n_val))
    if dataset is not None:
        train = chainermn_tpu.scatter_dataset(dataset, comm, shuffle=True,
                                              seed=0)
        val_shard = (chainermn_tpu.scatter_dataset(val, comm, shuffle=False)
                     if val is not None else None)

    model_fn = ARCHS[args.arch]
    model = model_fn(args.classes)
    if args.mnbn:
        import dataclasses
        import functools
        from chainermn_tpu.links import MultiNodeBatchNormalization
        if hasattr(model, "norm"):
            # ResNet takes a norm factory directly — inject sync-BN with the
            # baseline BN hyperparameters so --mnbn isolates the statistics
            # change (not a changed epsilon/dtype)
            model = dataclasses.replace(model, norm=functools.partial(
                MultiNodeBatchNormalization, communicator=comm,
                momentum=0.9, epsilon=1e-5, dtype=model.compute_dtype))
        else:
            model = chainermn_tpu.create_mnbn_model(model, comm)

    global_batch = args.batchsize * comm.size
    if jpeg_it is not None:
        # the JPEG loader yields ready float32 batches just like
        # NativeBatchLoader -> the loop's pre-normalized branch
        it = jpeg_it
        batches = iter(it)
        pre_normalized = True
    else:
        ensure_batch_fits(train, global_batch, comm.size)
        if args.native_loader:
            try:
                from chainermn_tpu.native.dataloader import NativeBatchLoader

                # zero-copy view of the shard: the C++ path gathers rows from
                # the base array, fuses the normalize, prefetches a batch ahead
                base, rows, ys = record_source(train)
                native_it = NativeBatchLoader(base, ys, global_batch, rows=rows,
                                              shuffle=True, seed=1)
            except Exception as e:  # toolchain/build failure on THIS rank
                # per-rank diagnostic: rank 0's banner can't see this failure
                print(f"[rank {comm.rank}] native loader unavailable "
                      f"({type(e).__name__}: {e})", flush=True)
                native_it = None
            # the step/evaluate cadence is collective — every rank must take
            # the SAME input path, so agree before choosing (one rank's build
            # failure would otherwise desync step counts and hang the job).
            # ALWAYS agree first, even on the explicit-flag failure path: a
            # lone rank raising before the collective would strand the others
            # inside it — fail hard on every rank together instead.
            args.native_loader = comm.allreduce_obj(
                native_it is not None, lambda a, b: a and b)
            if native_explicit and not args.native_loader:
                raise SystemExit(
                    "--native-loader was explicitly requested but the native "
                    "extension is unavailable on at least one rank (see the "
                    "per-rank diagnostics above); an explicit opt-in must not "
                    "silently measure the numpy path")
            if args.native_loader:
                it = native_it
                batches = iter(it)
        if not args.native_loader:
            it = chainermn_tpu.SerialIterator(train, global_batch, shuffle=True, seed=1)
        pre_normalized = args.native_loader
        if comm.rank == 0:
            print(f"input pipeline: "
                  f"{'native C++ prefetch' if args.native_loader else 'numpy'}")

    sample = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.bfloat16)
    variables = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), sample, train=True)
    )
    steps_per_epoch = (max(1, len(it)) if jpeg_it is not None else
                       max(1, (len(train) * comm.process_size) // global_batch))
    if args.warmup_epochs:
        # linear scaling rule + warmup (arXiv:1711.04325): ramp to
        # lr x global_batch/256 over the warmup span, cosine-decay to 0.
        # The x global_batch/256 multiplier applies only under --recipe —
        # a bare --warmup-epochs must not silently rescale the user's --lr.
        scaled_lr = (args.lr * global_batch / 256.0 if args.recipe
                     else args.lr)
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=scaled_lr,
            warmup_steps=max(1, int(args.warmup_epochs * steps_per_epoch)),
            decay_steps=max(2, args.epoch * steps_per_epoch),
        )
    else:
        lr = args.lr
    if args.fsdp:
        from chainermn_tpu.parallel import fsdp_shard, jit_fsdp_train_step

        optimizer = optax.sgd(lr, momentum=0.9)  # no multi-node wrapper:
        # the gradient mean falls out of the global-batch loss (fsdp.py)
        variables = fsdp_shard(variables, comm)
        opt_state = fsdp_shard(jax.jit(optimizer.init)(variables["params"]), comm)
        step = jit_fsdp_train_step(
            model, optimizer, comm, train_kwargs={"train": True},
            label_smoothing=args.label_smoothing,
        )
    else:
        optimizer = chainermn_tpu.create_multi_node_optimizer(
            optax.sgd(lr, momentum=0.9), comm,
            double_buffering=args.double_buffering,
        )
        opt_state = jax.device_put(
            optimizer.init(variables["params"]), comm.named_sharding()
        )
        step = jit_train_step(
            model, optimizer, comm, train_kwargs={"train": True},
            label_smoothing=args.label_smoothing,
        )

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    if comm.rank == 0:
        print(f"{n_params / 1e6:.1f}M params, global batch {global_batch}")

    evaluate = None
    if val_shard is not None:
        from jax.sharding import PartitionSpec as P

        if args.fsdp:
            # variables live scattered; a global program gathers them at use
            eval_forward = jax.jit(lambda v, x: model.apply(v, x, train=False))
        else:
            eval_forward = jax.jit(comm.shard_map(
                lambda v, x: model.apply(v, x, train=False),
                in_specs=(P(), comm.data_spec), out_specs=comm.data_spec,
            ))

        def _local_eval():
            # top-1 over this process's held-out shard; the multi-node
            # evaluator averages the dicts across processes (SURVEY.md S2.14)
            correct = n = 0
            for batch in chainermn_tpu.SerialIterator(
                val_shard, global_batch, repeat=False, shuffle=False
            ):
                x, y = collate(batch, np.float32)
                if len(y) < global_batch:  # pad ragged tail to jitted shape
                    pad = global_batch - len(y)
                    x = np.concatenate(
                        [x, np.zeros((pad,) + x.shape[1:], x.dtype)]
                    )
                logits = np.asarray(eval_forward(variables, x))
                pred = logits[: len(y)].argmax(-1)
                correct += int((pred == y).sum())
                n += len(y)
            return {"validation/main/accuracy": correct / max(n, 1)}

        evaluate = chainermn_tpu.create_multi_node_evaluator(_local_eval, comm)

    if args.device_prefetch:
        if not pre_normalized:
            raise SystemExit(
                "--device-prefetch wraps the pre-normalized input stream "
                "(native C++ or JPEG loader); the numpy SerialIterator "
                "path collates inside the loop — use --native-loader or "
                "--train-dir")
        from chainermn_tpu.dataflow import DevicePrefetcher

        # epoch/is_new_epoch on the wrapper track DELIVERED batches, so
        # the epoch-cadenced eval below keys off the wrapper, not the
        # producer-paced loader
        batches = it = DevicePrefetcher(
            it, depth=args.device_prefetch,
            sharding=comm.named_sharding(*comm.data_spec),
            name="imagenet")
        if comm.rank == 0:
            print(f"device prefetch: depth {args.device_prefetch} "
                  "(H2D on a producer thread)")

    iteration = 0
    t0 = time.time()
    imgs = 0
    loss = jnp.float32(0)  # stays 0 if every batch is a ragged tail
    while it.epoch < args.epoch:
        if pre_normalized:
            images, labels = next(batches)  # pre-normalized, never ragged
        else:
            images, labels = collate(next(it), np.float32)
        if len(labels) == global_batch:  # ragged tails skip the jitted step
            variables, opt_state, loss = step(variables, opt_state, images, labels)
            iteration += 1
            imgs += global_batch
            if iteration == 1:
                jax.block_until_ready(loss)
                t0, imgs = time.time(), 0  # exclude compile from throughput
                if comm.rank == 0:
                    print(f"compiled; first loss {float(loss):.3f}")
            elif iteration % 20 == 0 and comm.rank == 0:
                dt = time.time() - t0
                print(f"iter {iteration:5d}  loss {float(loss):.3f}  "
                      f"{imgs / dt:.1f} img/s ({imgs / dt / comm.size:.1f}/chip)")
        if it.is_new_epoch and evaluate is not None:
            metrics = evaluate()
            if comm.rank == 0:
                print(f"epoch {it.epoch:3d}  "
                      f"top-1 {metrics['validation/main/accuracy']:.4f}")
        if args.iterations and iteration >= args.iterations:
            break
    jax.block_until_ready(loss)
    if args.device_prefetch:
        it.close()  # stop + join the producer thread
    if evaluate is not None and not it.is_new_epoch:
        # exited mid-epoch (--iterations): still report a final top-1
        metrics = evaluate()
        if comm.rank == 0:
            print(f"final top-1 {metrics['validation/main/accuracy']:.4f}")
    if comm.rank == 0 and imgs:
        dt = time.time() - t0
        print(f"done: {iteration} iterations, {imgs / dt:.1f} img/s "
              f"({imgs / dt / comm.size:.2f} img/s/chip)")


if __name__ == "__main__":
    main()
