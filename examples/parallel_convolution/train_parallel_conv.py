#!/usr/bin/env python
"""Channel-parallel convolution — tensor parallelism from differentiable
collectives.

Parity target: ``[U] examples/parallel_convolution/`` (SURVEY.md S2.15/S2.16 —
unverified cite): the reference's only tensor-parallel construct, a CIFAR CNN
whose conv layers' channels are split across ranks and stitched with the
differentiable ``alltoall``/``allgather`` function nodes; the backward runs
the transposed collectives.

TPU re-design (one SPMD program over the mesh):

- the batch enters **batch-sharded** (how data arrives in practice);
- an ``alltoall`` re-shards activations batch->channel (split the channel
  axis, concatenate the batch axis — the Ulysses collective shape applied to
  channels) so the parallel section sees the FULL batch with ``C/n`` channels
  per rank;
- each parallel conv holds only its ``F/n`` out-channel slice of the kernel
  (the global kernel array is sharded over the mesh on its out-feature axis);
  the full input is assembled per layer with a tiled ``allgather`` whose
  autodiff transpose routes every rank's cotangents back to the owning
  channel shard — the reference's hand-written backward, derived;
- a closing ``alltoall`` returns to batch-sharded for the replicated head and
  the per-shard loss.

Gradients: channel-sharded kernels get their full cross-rank gradient through
the collective transposes; replicated (conv1/head) parameters need an explicit
``psum`` of the per-shard contributions — the example does both and documents
which is which.

Run (2+ emulated devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/parallel_convolution/train_parallel_conv.py --check
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform, ensure_batch_fits

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers


# --------------------------------------------------------------------------- #
# Model: conv1 (replicated) -> pconv2 -> pconv3 (channel-parallel) -> head    #
# --------------------------------------------------------------------------- #

CH1, CH2, CH3 = 32, 64, 64


def init_params(key, image_size: int, classes: int):
    """Full (unsharded) parameters; the pconv kernels' out-feature axis is
    what gets sharded over the mesh at train time."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    he = jax.nn.initializers.he_normal()
    feat = (image_size // 4) * (image_size // 4) * CH3
    return {
        "conv1": {"w": he(k1, (3, 3, 3, CH1)), "b": jnp.zeros((CH1,))},
        "pconv2": {"w": he(k2, (3, 3, CH1, CH2)), "b": jnp.zeros((CH2,))},
        "pconv3": {"w": he(k3, (3, 3, CH2, CH3)), "b": jnp.zeros((CH3,))},
        "head": {
            "w": he(k4, (feat, classes)),
            "b": jnp.zeros((classes,)),
        },
    }


def param_specs(axis: str):
    """Sharding: pconv kernels/biases split on the out-channel axis; the rest
    replicated (the reference's 'every rank holds a channel slice' layout)."""
    return {
        "conv1": {"w": P(), "b": P()},
        "pconv2": {"w": P(None, None, None, axis), "b": P(axis)},
        "pconv3": {"w": P(None, None, None, axis), "b": P(axis)},
        "head": {"w": P(), "b": P()},
    }


def _conv(x, p, stride: int = 1):
    y = lax.conv_general_dilated(
        x, p["w"], (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + p["b"]


def _pool(x):
    return lax.reduce_window(
        x, 0.0, lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    ) / 4.0


def serial_forward(params, x):
    """Single-device reference semantics: what the parallel program must
    reproduce bit-for-bit-ish (fp tolerance) with the same weights."""
    h = jax.nn.relu(_conv(x, params["conv1"]))
    h = _pool(h)
    h = jax.nn.relu(_conv(h, params["pconv2"]))
    h = jax.nn.relu(_conv(h, params["pconv3"]))
    h = _pool(h)
    h = h.reshape((h.shape[0], -1))
    return h @ params["head"]["w"] + params["head"]["b"]


# --------------------------------------------------------------------------- #
# Parallel program (runs inside comm.shard_map)                               #
# --------------------------------------------------------------------------- #

def _batch_to_channel(h, comm):
    """[N/n, H, W, C] batch-sharded -> [N, H, W, C/n] channel-sharded."""
    n = comm.size
    nl, hh, ww, c = h.shape
    h = h.reshape(nl, hh, ww, n, c // n).transpose(3, 0, 1, 2, 4)
    h = chainermn_tpu.functions.alltoall(h, comm)  # leading axis: peers
    return h.reshape(n * nl, hh, ww, c // n)


def _channel_to_batch(h, comm):
    """[N, H, W, C/n] channel-sharded -> [N/n, H, W, C] batch-sharded."""
    n = comm.size
    nn_, hh, ww, cl = h.shape
    h = h.reshape(n, nn_ // n, hh, ww, cl)
    h = chainermn_tpu.functions.alltoall(h, comm)
    return h.transpose(1, 2, 3, 0, 4).reshape(nn_ // n, hh, ww, n * cl)


def parallel_forward(params, x, comm):
    """Per-rank body: ``params`` are the LOCAL views (pconv slices), ``x`` is
    the local batch shard."""
    h = jax.nn.relu(_conv(x, params["conv1"]))  # batch-sharded, replicated w
    h = _pool(h)
    h = _batch_to_channel(h, comm)              # full batch, C/n channels
    # each parallel conv: assemble full input channels, compute local slice
    full = chainermn_tpu.functions.allgather(h, comm)  # [n, N, H, W, C/n]
    full = jnp.moveaxis(full, 0, -2).reshape(h.shape[:3] + (-1,))
    h = jax.nn.relu(_conv(full, params["pconv2"]))     # -> [N, H, W, CH2/n]
    full = chainermn_tpu.functions.allgather(h, comm)
    full = jnp.moveaxis(full, 0, -2).reshape(h.shape[:3] + (-1,))
    h = jax.nn.relu(_conv(full, params["pconv3"]))     # -> [N, H, W, CH3/n]
    h = _pool(h)
    h = _channel_to_batch(h, comm)              # back to batch shards, full C
    h = h.reshape((h.shape[0], -1))
    return h @ params["head"]["w"] + params["head"]["b"]


def make_train_step(comm, optimizer):
    axis = comm.axis_name

    def body(params, opt_state, images, labels):
        def loss_fn(p):
            logits = parallel_forward(p, images, comm)
            local = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return comm.allreduce(local, "mean")  # global mean loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # channel-sharded kernels already carry their full cross-rank gradient
        # (collective transposes); replicated params hold only the local
        # shard's contribution scaled 1/n -> sum across ranks.
        for name in ("conv1", "head"):
            grads[name] = jax.tree_util.tree_map(
                lambda g: comm.allreduce(g, "sum"), grads[name]
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # optimizer state: plain SGD is stateless (EmptyState), so a replicated
    # P() prefix-spec covers it; a param-shaped state (adam moments) would
    # need the same sharding tree as the params.
    specs = param_specs(axis)
    sm = comm.shard_map(
        body,
        in_specs=(specs, P(), comm.data_spec, comm.data_spec),
        out_specs=(specs, P(), P()),
    )
    return jax.jit(sm, donate_argnums=(0, 1))


# --------------------------------------------------------------------------- #

def synthetic_cifar(n: int, image_size: int, classes: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    templates = rng.rand(classes, image_size, image_size, 3).astype(np.float32)
    y = rng.randint(0, classes, size=n).astype(np.int32)
    x = templates[y] + 0.25 * rng.randn(n, image_size, image_size, 3).astype(np.float32)
    return np.clip(x, 0.0, 1.0), y


def main() -> None:
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: channel-parallel convolution"
    )
    parser.add_argument("--batchsize", "-b", type=int, default=64)
    parser.add_argument("--epoch", "-e", type=int, default=5)
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--classes", type=int, default=10)
    parser.add_argument("--n-train", type=int, default=2048)
    parser.add_argument("--check", action="store_true",
                        help="assert parallel forward == serial forward "
                             "with the same weights before training")
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator("tpu")
    n = comm.size
    for ch in (CH1, CH2, CH3):
        if ch % n:
            raise SystemExit(f"channel counts {CH1}/{CH2}/{CH3} must divide "
                             f"the device count ({n})")

    params = init_params(jax.random.PRNGKey(0), args.image_size, args.classes)
    specs = param_specs(comm.axis_name)
    params = jax.device_put(
        params, jax.tree_util.tree_map(
            lambda s: comm.named_sharding(*s), specs,
            is_leaf=lambda s: isinstance(s, P),
        )
    )

    x, y = synthetic_cifar(args.n_train, args.image_size, args.classes)
    ensure_batch_fits(x, args.batchsize)

    if args.check:
        xb = jnp.asarray(x[: args.batchsize])
        want = serial_forward(jax.device_get(params), xb)
        got = jax.jit(comm.shard_map(
            lambda p, xs: parallel_forward(p, xs, comm),
            in_specs=(specs, comm.data_spec), out_specs=comm.data_spec,
        ))(params, xb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        if comm.rank == 0:
            print(f"parity check OK: parallel({n} ranks) == serial forward")

    optimizer = optax.sgd(5e-2)  # stateless: see make_train_step spec note
    opt_state = jax.device_put(
        optimizer.init(jax.device_get(params)), comm.named_sharding()
    )
    step = make_train_step(comm, optimizer)

    steps_per_epoch = max(1, args.n_train // args.batchsize)
    t0 = time.time()
    first = last = None
    for epoch in range(1, args.epoch + 1):
        perm = np.random.RandomState(epoch).permutation(args.n_train)
        losses = []
        for it in range(steps_per_epoch):
            idx = perm[it * args.batchsize:(it + 1) * args.batchsize]
            if len(idx) < args.batchsize:
                continue
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx])
            )
            losses.append(float(loss))
        mean_loss = float(np.mean(losses))
        first = first if first is not None else mean_loss
        last = mean_loss
        if comm.rank == 0:
            print(f"epoch {epoch:3d}  train/loss {mean_loss:.4f}")
    if comm.rank == 0:
        print(f"done in {time.time() - t0:.1f}s  "
              f"(ranks={n}, loss {first:.3f} -> {last:.3f})")


if __name__ == "__main__":
    main()
