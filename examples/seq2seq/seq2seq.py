#!/usr/bin/env python
"""Seq2seq model-parallel training — encoder and decoder on different ranks.

Parity target: ``[U] examples/seq2seq/seq2seq.py`` (SURVEY.md S2.15 —
unverified cite): the reference trains a WMT encoder–decoder with the
encoder's NStepLSTM on rank 0 and the decoder on rank 1, wired by
differentiable ``send``/``recv``; ``seq2seq_mp1.py`` adds hybrid data x model
parallelism via ``comm.split`` (S2.16, med confidence).

TPU re-design: the chain is declared once (``MultiNodeChainList``); the
encoder's final GRU state crosses the rank boundary as a device-to-device
transfer whose autodiff transpose is the reference's backward ``recv``. The
task is synthetic sequence reversal (no corpus download): source = random
token sequence, target = its reverse — a real seq2seq task with non-trivial
alignment that a GRU encoder/decoder genuinely has to learn.

Hybrid DP x MP (``--hybrid``, needs >= 4 devices): devices are paired into
``size // 2`` model-parallel groups (pair g = ranks {2g, 2g+1}); each pair
trains a full encoder/decoder chain on its own batch shard, and gradients are
averaged *across pairs, per role* with a grouped collective on the
``comm.split``-derived communicator (even ranks = encoders, odd = decoders) —
the same split-by-color topology the reference's hybrid example builds.

Run (2+ emulated devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        python examples/seq2seq/seq2seq.py --epoch 3
"""

from __future__ import annotations

import argparse
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers

BOS = 0  # decoder start token; task vocabulary occupies [1, vocab)


class Encoder(nn.Module):
    """Stage 0 (rank 0): embed source tokens, run a GRU, emit the final
    state. Passes the decoder inputs through untouched — in the reference
    both ranks read the batch; in the single-controller chain the boundary
    payload carries everything the next stage consumes."""

    vocab: int
    units: int

    @nn.compact
    def __call__(self, src, tgt_in):
        e = nn.Embed(self.vocab, self.units)(src)
        state, _ = nn.RNN(nn.GRUCell(self.units))(e, return_carry=True)
        return state, tgt_in


class Decoder(nn.Module):
    """Stage 1 (rank 1): teacher-forced GRU conditioned on the encoder
    state (received across the rank boundary), projecting to logits."""

    vocab: int
    units: int

    @nn.compact
    def __call__(self, inputs):
        state, tgt_in = inputs
        e = nn.Embed(self.vocab, self.units)(tgt_in)
        ys = nn.RNN(nn.GRUCell(self.units))(e, initial_carry=state)
        return nn.Dense(self.vocab)(ys)


def make_reversal_batch(rng, n, seq_len, vocab):
    """source: random tokens in [1, vocab); target: reversed source.
    Decoder input is the BOS-shifted target (teacher forcing)."""
    src = rng.randint(1, vocab, size=(n, seq_len)).astype(np.int32)
    tgt = src[:, ::-1].copy()
    tgt_in = np.concatenate([np.full((n, 1), BOS, np.int32), tgt[:, :-1]], axis=1)
    return src, tgt_in, tgt


def build_chain(comm, vocab, units, rank_enc, rank_dec):
    chain = chainermn_tpu.MultiNodeChainList(comm)
    chain.add_link(Encoder(vocab, units), rank=rank_enc, rank_in=None,
                   rank_out=rank_dec)
    chain.add_link(Decoder(vocab, units), rank=rank_dec, rank_in=rank_enc,
                   rank_out=None)
    return chain


def chain_loss(chain):
    def loss_fn(variables, src, tgt_in, tgt):
        logits = chain.apply(variables, src, tgt_in)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt
        ).mean()
    return loss_fn


def token_accuracy(chain, variables, src, tgt_in, tgt) -> float:
    logits = chain.apply(variables, src, tgt_in)
    pred = np.argmax(np.asarray(logits), axis=-1)
    return float((pred == tgt).mean())


def mean_grads_across_pairs(dp_comm, grads_per_pair, role, n_slots):
    """Average one role's gradient pytrees across the MP pairs with a grouped
    collective on the split communicator.

    The eager grouped allreduce takes rank-major arrays over ALL global ranks;
    pair g's role-``role`` grads sit in slot ``2g + role`` (their owning
    device rank) and the other role's slots are zero-padding whose group never
    mixes with ours (split color = rank % 2). Each pair's grads arrive
    committed to that pair's device, so packing stages through the host and
    the averaged result is committed back to each owner."""

    devices = list(dp_comm.mesh.devices.flat)

    def pack(*leaves):
        z = np.zeros((n_slots,) + leaves[0].shape, np.asarray(leaves[0]).dtype)
        for g, leaf in enumerate(leaves):
            z[2 * g + role] = np.asarray(jax.device_get(leaf))
        return jnp.asarray(z)

    packed = jax.tree_util.tree_map(pack, *grads_per_pair)
    meaned = jax.device_get(dp_comm.allreduce(packed, "mean"))
    return [
        jax.tree_util.tree_map(
            lambda l, s=2 * g + role: jax.device_put(l[s], devices[s]), meaned
        )
        for g in range(len(grads_per_pair))
    ]


def main() -> None:
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: seq2seq model parallelism"
    )
    parser.add_argument("--batchsize", "-b", type=int, default=64)
    parser.add_argument("--epoch", "-e", type=int, default=20)
    parser.add_argument("--unit", "-u", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=8)
    parser.add_argument("--n-train", type=int, default=2048)
    parser.add_argument("--n-test", type=int, default=256)
    parser.add_argument("--hybrid", action="store_true",
                        help="data x model parallel over >= 4 devices "
                             "(comm.split by role, reference seq2seq_mp1)")
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator("naive")
    if comm.size < 2:
        raise SystemExit("seq2seq model-parallel example needs >= 2 devices")

    rng = np.random.RandomState(0)
    train = make_reversal_batch(rng, args.n_train, args.seq_len, args.vocab)
    test = make_reversal_batch(rng, args.n_test, args.seq_len, args.vocab)

    optimizer = optax.adam(2e-3)
    n_pairs = comm.size // 2 if args.hybrid else 1
    if args.hybrid and (comm.size < 4 or comm.size % 2):
        raise SystemExit(
            f"--hybrid needs an even device count >= 4 (2 per MP pair); "
            f"got {comm.size}"
        )

    # one chain per MP pair; identical init (same key) keeps pairs in sync,
    # the reference's bcast_data-at-start contract
    chains = [
        build_chain(comm, args.vocab, args.unit, 2 * g, 2 * g + 1)
        for g in range(n_pairs)
    ]
    variables = [
        c.init(jax.random.PRNGKey(0), jnp.asarray(train[0][:1]),
               jnp.asarray(train[1][:1]))
        for c in chains
    ]
    opt_states = [[optimizer.init(v) for v in vs] for vs in variables]
    grad_fns = [jax.value_and_grad(chain_loss(c)) for c in chains]
    dp_comm = (
        comm.split([r % 2 for r in range(comm.size)]) if args.hybrid else None
    )

    steps_per_epoch = max(1, args.n_train // args.batchsize)
    t0 = time.time()
    for epoch in range(1, args.epoch + 1):
        perm = rng.permutation(args.n_train)
        losses = []
        for it in range(steps_per_epoch):
            idx = perm[it * args.batchsize:(it + 1) * args.batchsize]
            shards = np.array_split(idx, n_pairs)
            grads_all, loss_sum = [], 0.0
            for g in range(n_pairs):
                src, tgt_in, tgt = (a[shards[g]] for a in train)
                loss, grads = grad_fns[g](variables[g], src, tgt_in, tgt)
                grads_all.append(grads)
                loss_sum += float(loss)
            if dp_comm is not None:
                # grads_all[g] is a 2-list [enc_grads, dec_grads]
                for role in range(2):
                    meaned = mean_grads_across_pairs(
                        dp_comm, [gs[role] for gs in grads_all], role, comm.size
                    )
                    for g in range(n_pairs):
                        grads_all[g][role] = meaned[g]
            for g in range(n_pairs):
                new_vs, new_ss = [], []
                for v, gr, s in zip(variables[g], grads_all[g], opt_states[g]):
                    updates, s = optimizer.update(gr, s, v)
                    new_vs.append(optax.apply_updates(v, updates))
                    new_ss.append(s)
                variables[g], opt_states[g] = new_vs, new_ss
            losses.append(loss_sum / n_pairs)
        if comm.rank == 0:
            acc = token_accuracy(chains[0], variables[0], *test)
            print(f"epoch {epoch:3d}  train/loss {np.mean(losses):.4f}  "
                  f"val/token_acc {acc:.4f}")
    if comm.rank == 0:
        print(f"done in {time.time() - t0:.1f}s  "
              f"(pairs={n_pairs}, hybrid={args.hybrid})")


if __name__ == "__main__":
    main()
