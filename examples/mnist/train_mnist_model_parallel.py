#!/usr/bin/env python
"""MNIST model-parallel training — MLP split across two ranks.

Parity target: ``[U] examples/mnist/train_mnist_model_parallel.py``
(SURVEY.md S2.15 — unverified cite): the reference builds a
``MultiNodeChainList`` whose first half runs on rank 0 and second half on
rank 1, wired by differentiable send/recv. Here the chain is declared once
by the single controller; boundary tensors move device-to-device (ICI) and
autodiff produces the transposed backward transfers (S3.3).

Run (2+ emulated devices)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        python examples/mnist/train_mnist_model_parallel.py --epoch 3
"""

from __future__ import annotations

import argparse
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers

from train_mnist import ArrayDataset, collate, load_mnist  # noqa: E402 (sibling)


class MLPHalf0(nn.Module):
    """Stage 0: input -> hidden (runs on rank 0)."""

    n_units: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.relu(nn.Dense(self.n_units)(x))


class MLPHalf1(nn.Module):
    """Stage 1: hidden -> logits (runs on rank 1)."""

    n_units: int
    n_out: int = 10

    @nn.compact
    def __call__(self, h):
        h = nn.relu(nn.Dense(self.n_units)(h))
        return nn.Dense(self.n_out)(h)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: MNIST model-parallel"
    )
    parser.add_argument("--batchsize", "-b", type=int, default=100)
    parser.add_argument("--epoch", "-e", type=int, default=10)
    parser.add_argument("--unit", "-u", type=int, default=500)
    parser.add_argument("--data", type=str, default=None)
    parser.add_argument("--n-train", type=int, default=8000)
    parser.add_argument("--n-test", type=int, default=1000)
    parser.add_argument(
        "--fused", action="store_true",
        help="one jitted program over the whole chain (replicated variables) "
             "instead of a jit per stage",
    )
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator("naive")
    if comm.size < 2:
        raise SystemExit("model-parallel example needs >= 2 devices")
    r0, r1 = 0, 1  # the two stage-owning ranks (reference: MPI ranks 0/1)

    model = chainermn_tpu.MultiNodeChainList(comm)
    model.add_link(MLPHalf0(args.unit), rank=r0, rank_in=None, rank_out=r1)
    model.add_link(MLPHalf1(args.unit), rank=r1, rank_in=r0, rank_out=None)

    (x_train, y_train), (x_test, y_test) = load_mnist(
        args.data, args.n_train, args.n_test
    )
    train = ArrayDataset(x_train, y_train)
    test = ArrayDataset(x_test, y_test)
    it = chainermn_tpu.SerialIterator(train, args.batchsize, shuffle=True, seed=1)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
    if args.fused:
        # Fused mode trades per-rank placement for a single compiled
        # program: variables are replicated over the mesh and the whole
        # chain (and its backward) is one XLA program.
        variables = model.replicate(variables)
    # One optimizer per stage, exactly like the reference. In the default
    # mode each stage's optimizer state is co-located with its parameters on
    # the owning rank; under --fused it follows the replicated placement.
    optimizer = optax.adam(1e-3)
    opt_states = [optimizer.init(v) for v in variables]

    def loss_fn(variables, images, labels):
        logits = model.apply(variables, images, fused=args.fused)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    grad_fn = jax.value_and_grad(loss_fn)

    def train_step(variables, opt_states, images, labels):
        # The chain's stages are separately jitted (placement is per-stage);
        # the outer autodiff stitches their VJPs with reversed transfers.
        loss, grads = grad_fn(variables, images, labels)
        new_vars, new_states = [], []
        for v, g, s in zip(variables, grads, opt_states):
            updates, s = optimizer.update(g, s, v)
            new_vars.append(optax.apply_updates(v, updates))
            new_states.append(s)
        return new_vars, new_states, loss

    def evaluate() -> dict:
        correct = n = 0
        for batch in chainermn_tpu.SerialIterator(
            test, args.batchsize, repeat=False, shuffle=False
        ):
            images, labels = collate(batch)
            logits = model.apply(variables, images, fused=args.fused)
            correct += int((np.argmax(np.asarray(logits), -1) == labels).sum())
            n += len(labels)
        return {"validation/main/accuracy": correct / max(n, 1)}

    t0 = time.time()
    while it.epoch < args.epoch:
        images, labels = collate(next(it))
        variables, opt_states, loss = train_step(variables, opt_states, images, labels)
        if it.is_new_epoch and comm.rank == 0:
            metrics = evaluate()
            print(f"epoch {it.epoch:3d}  train/loss {float(loss):.4f}  "
                  f"val/acc {metrics['validation/main/accuracy']:.4f}")
    if comm.rank == 0:
        print(f"done in {time.time() - t0:.1f}s  "
              f"(stage devices: {[str(d) for d in list(comm.mesh.devices.flat)[:2]]})")


if __name__ == "__main__":
    main()
