#!/usr/bin/env python
"""MNIST data-parallel training — the reference's minimum end-to-end slice.

Parity target: ``[U] examples/mnist/train_mnist.py`` (SURVEY.md S2.15 —
unverified cite). Exercises: communicator factory, ``scatter_dataset``,
multi-node optimizer, multi-node evaluator, root-only reporting.

Where the reference runs ``mpiexec -n N python train_mnist.py``, this runs as
ONE controller over all local devices (SPMD over a Mesh). To emulate N
"ranks" without a TPU pod slice::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mnist/train_mnist.py --epoch 2

MNIST itself needs a download; without ``--data mnist.npz`` a deterministic
synthetic stand-in with class structure is used (the training dynamics are
real, the digits are not).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform, ensure_batch_fits

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers
from chainermn_tpu.models import MLP
from chainermn_tpu.training import jit_train_step


def load_mnist(path: str | None, n_train: int, n_test: int, seed: int = 0):
    """``mnist.npz`` (keras layout: x_train/y_train/x_test/y_test) or a
    synthetic, learnable stand-in: each class has a fixed random template,
    samples are template + noise."""
    if path:
        with np.load(path) as z:
            return (
                (z["x_train"][:n_train].astype(np.float32) / 255.0,
                 z["y_train"][:n_train].astype(np.int32)),
                (z["x_test"][:n_test].astype(np.float32) / 255.0,
                 z["y_test"][:n_test].astype(np.int32)),
            )
    rng = np.random.RandomState(seed)
    templates = rng.rand(10, 28, 28).astype(np.float32)

    def draw(n):
        y = rng.randint(0, 10, size=n).astype(np.int32)
        x = templates[y] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
        return np.clip(x, 0.0, 1.0), y

    return draw(n_train), draw(n_test)


class ArrayDataset:
    """(x, y) record view over parallel arrays (chainer's TupleDataset shape)."""

    def __init__(self, x: np.ndarray, y: np.ndarray) -> None:
        assert len(x) == len(y)
        self.x, self.y = x, y

    def __len__(self) -> int:
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def collate(batch) -> tuple[np.ndarray, np.ndarray]:
    xs, ys = zip(*batch)
    return np.stack(xs), np.asarray(ys, np.int32)


def main() -> None:
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: MNIST")
    parser.add_argument("--batchsize", "-b", type=int, default=100,
                        help="per-participant batch size (reference default)")
    parser.add_argument("--epoch", "-e", type=int, default=20)
    parser.add_argument("--unit", "-u", type=int, default=1000)
    parser.add_argument("--communicator", type=str, default="tpu",
                        help="naive | flat | tpu | pure_nccl | hierarchical | "
                             "two_dimensional | single_node")
    parser.add_argument("--data", type=str, default=None,
                        help="path to mnist.npz (keras layout); synthetic if absent")
    parser.add_argument("--n-train", type=int, default=10000)
    parser.add_argument("--n-test", type=int, default=2000)
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"communicator: {args.communicator}  size: {comm.size} "
              f"(intra {comm.intra_size} x inter {comm.inter_size})")

    (x_train, y_train), (x_test, y_test) = load_mnist(
        args.data, args.n_train, args.n_test
    )
    # Process-space scatter (multi-host); within a process the global batch is
    # sharded over devices by the train step itself.
    train = chainermn_tpu.scatter_dataset(
        ArrayDataset(x_train, y_train), comm, shuffle=True, seed=0
    )
    test = chainermn_tpu.scatter_dataset(ArrayDataset(x_test, y_test), comm)

    model = MLP(n_units=args.unit)
    global_batch = args.batchsize * comm.size
    ensure_batch_fits(train, global_batch, comm.size)
    it = chainermn_tpu.SerialIterator(train, global_batch, shuffle=True, seed=1)

    variables = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
    )
    optimizer = chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-3), comm)
    opt_state = jax.device_put(
        optimizer.init(variables["params"]), comm.named_sharding()
    )
    step = jit_train_step(model, optimizer, comm)

    @jax.jit
    def eval_batch(variables, images, labels):
        logits = model.apply(variables, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        acc = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        return loss.sum(), acc.sum()

    def evaluate() -> dict:
        tot_loss = tot_acc = n = 0.0
        ev_it = chainermn_tpu.SerialIterator(
            test, global_batch, repeat=False, shuffle=False
        )
        for batch in ev_it:
            images, labels = collate(batch)
            loss, acc = eval_batch(variables, images, labels)
            tot_loss += float(loss)
            tot_acc += float(acc)
            n += len(labels)
        n = max(n, 1.0)
        return {"validation/main/loss": tot_loss / n,
                "validation/main/accuracy": tot_acc / n}

    evaluator = chainermn_tpu.create_multi_node_evaluator(evaluate, comm)

    steps_per_epoch = max(1, len(train) // global_batch)
    t0 = time.time()
    loss = jnp.float32(0)
    while it.epoch < args.epoch:
        images, labels = collate(next(it))
        if len(labels) == global_batch:  # ragged tail: skip (reference drops too)
            variables, opt_state, loss = step(variables, opt_state, images, labels)
        if it.is_new_epoch:
            metrics = evaluator.evaluate()
            if comm.rank == 0:
                print(f"epoch {it.epoch:3d}  train/loss {float(loss):.4f}  "
                      f"val/loss {metrics['validation/main/loss']:.4f}  "
                      f"val/acc {metrics['validation/main/accuracy']:.4f}  "
                      f"({(time.time() - t0) / it.epoch:.2f}s/epoch, "
                      f"{steps_per_epoch} steps)")
    if comm.rank == 0:
        print(f"done in {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
