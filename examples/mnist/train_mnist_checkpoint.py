#!/usr/bin/env python
"""MNIST data-parallel training with fault-tolerant checkpoint/resume.

Parity target: ``[U] examples/mnist/train_mnist_checkpoint.py`` (SURVEY.md
S2.15 — unverified cite): the reference attaches
``create_multi_node_checkpointer`` to the trainer so a killed job resumes
from the newest snapshot every rank still has. Here the checkpointer
snapshots {variables, opt_state, iterator state} every ``--frequency``
iterations; rerunning the same command resumes automatically.

Try it: run with ``--stop-at 12`` (simulated crash), then run again without
it and watch training resume from the snapshot instead of iteration 0.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu
from chainermn_tpu.utils import apply_env_platform, ensure_batch_fits

apply_env_platform()  # honor JAX_PLATFORMS even under plugin-forcing containers
from chainermn_tpu.models import MLP
from chainermn_tpu.training import jit_train_step

from train_mnist import ArrayDataset, collate, load_mnist  # noqa: E402 (sibling)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: MNIST with checkpointing"
    )
    parser.add_argument("--batchsize", "-b", type=int, default=100)
    parser.add_argument("--epoch", "-e", type=int, default=5)
    parser.add_argument("--unit", "-u", type=int, default=200)
    parser.add_argument("--communicator", type=str, default="tpu")
    parser.add_argument("--out", type=str, default="/tmp/chainermn_tpu_ckpt")
    parser.add_argument("--frequency", type=int, default=5,
                        help="snapshot every N iterations")
    parser.add_argument("--stop-at", type=int, default=None,
                        help="simulate a crash after N iterations")
    parser.add_argument("--data", type=str, default=None)
    parser.add_argument("--n-train", type=int, default=4000)
    args = parser.parse_args()

    chainermn_tpu.add_global_except_hook()
    comm = chainermn_tpu.create_communicator(args.communicator)

    (x_train, y_train), _ = load_mnist(args.data, args.n_train, 1)
    train = chainermn_tpu.scatter_dataset(
        ArrayDataset(x_train, y_train), comm, shuffle=True, seed=0
    )
    global_batch = args.batchsize * comm.size
    ensure_batch_fits(train, global_batch, comm.size)
    it = chainermn_tpu.SerialIterator(train, global_batch, shuffle=True, seed=1)

    model = MLP(n_units=args.unit)
    variables = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
    )
    optimizer = chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-3), comm)
    opt_state = jax.device_put(
        optimizer.init(variables["params"]), comm.named_sharding()
    )
    step = jit_train_step(model, optimizer, comm)

    checkpointer = chainermn_tpu.create_multi_node_checkpointer(
        name="mnist_example", comm=comm, path=args.out
    )
    state, iteration = checkpointer.maybe_load(
        {"variables": variables, "opt_state": opt_state, "iterator": it.state_dict()}
    )
    if iteration > 0:
        sharding = comm.named_sharding()
        variables = jax.device_put(state["variables"], sharding)
        opt_state = jax.device_put(state["opt_state"], sharding)
        it.load_state_dict(state["iterator"])
        if comm.rank == 0:
            print(f"resumed from iteration {iteration}")
    elif comm.rank == 0:
        print("fresh start (no common snapshot)")

    while it.epoch < args.epoch:
        images, labels = collate(next(it))
        if len(labels) < global_batch:
            continue
        variables, opt_state, loss = step(variables, opt_state, images, labels)
        iteration += 1
        if iteration % args.frequency == 0:
            checkpointer.save(
                {"variables": variables, "opt_state": opt_state,
                 "iterator": it.state_dict()},
                iteration,
            )
            if comm.rank == 0:
                print(f"iter {iteration:4d}  loss {float(loss):.4f}  [snapshot]")
        if args.stop_at is not None and iteration >= args.stop_at:
            if comm.rank == 0:
                print(f"simulated crash at iteration {iteration}")
            raise SystemExit(1)
    if comm.rank == 0:
        print(f"finished at iteration {iteration}; "
              f"checkpoint stats: {checkpointer.get_stats()}")


if __name__ == "__main__":
    main()
