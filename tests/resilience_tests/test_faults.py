"""FaultInjector: determinism, kinds, cut-point no-op contract, telemetry."""

import threading
import time

import pytest

from chainermn_tpu.monitor import get_event_log, get_registry
from chainermn_tpu.resilience import (
    FaultInjector,
    InjectedFault,
    get_injector,
    inject,
    torn_fraction,
)


def test_inject_is_noop_without_injector():
    assert get_injector() is None
    inject("anything.at.all")              # must not raise
    assert torn_fraction("anything") is None


def test_context_manager_installs_and_uninstalls():
    inj = FaultInjector()
    with inj:
        assert get_injector() is inj
    assert get_injector() is None


def test_raise_after_and_times():
    inj = FaultInjector()
    inj.arm("p", kind="raise", after=2, times=1)
    with inj:
        inject("p")                        # hit 1: within `after`
        inject("p")                        # hit 2: within `after`
        with pytest.raises(InjectedFault) as ei:
            inject("p")                    # hit 3: fires
        assert ei.value.point == "p"
        inject("p")                        # `times` exhausted: no-op again
    assert inj.fired_log == [("p", "raise")]


def test_custom_exception():
    inj = FaultInjector()
    inj.arm("p", kind="raise", exc=ValueError("boom"))
    with inj:
        with pytest.raises(ValueError, match="boom"):
            inject("p")


def test_point_isolation():
    inj = FaultInjector()
    inj.arm("a", kind="raise")
    with inj:
        inject("b")                        # different point: untouched
        with pytest.raises(InjectedFault):
            inject("a")


def test_delay_sleeps():
    inj = FaultInjector()
    inj.arm("p", kind="delay", delay_s=0.05, times=1)
    with inj:
        t0 = time.perf_counter()
        inject("p")
        assert time.perf_counter() - t0 >= 0.05
        t0 = time.perf_counter()
        inject("p")                        # exhausted: no sleep
        assert time.perf_counter() - t0 < 0.04


def test_hang_blocks_until_release():
    inj = FaultInjector()
    inj.arm("p", kind="hang", hang_s=60.0)
    unblocked = threading.Event()

    def victim():
        inject("p")
        unblocked.set()

    with inj:
        t = threading.Thread(target=victim, daemon=True)
        t.start()
        assert not unblocked.wait(0.15)    # genuinely wedged
        inj.release()
        assert unblocked.wait(5.0)         # release() cuts the hang short
        t.join(5.0)


def test_hang_times_out_on_its_own():
    inj = FaultInjector()
    inj.arm("p", kind="hang", hang_s=0.1)
    with inj:
        t0 = time.perf_counter()
        inject("p")
        assert time.perf_counter() - t0 >= 0.1


def test_seeded_probability_is_deterministic():
    def run(seed):
        inj = FaultInjector(seed=seed)
        inj.arm("p", kind="raise", p=0.5, times=None)
        fired = []
        with inj:
            for _ in range(40):
                try:
                    inject("p")
                    fired.append(False)
                except InjectedFault:
                    fired.append(True)
        return fired

    a, b = run(7), run(7)
    assert a == b                          # replayable chaos
    assert any(a) and not all(a)           # p=0.5 actually mixes
    assert run(8) != a                     # and the seed matters


def test_torn_fraction_only_answers_torn_write():
    inj = FaultInjector()
    inj.arm("w", kind="torn_write", frac=0.25, times=1)
    inj.arm("w", kind="raise", after=10)   # raise-kind must not leak in
    with inj:
        assert torn_fraction("w") == 0.25
        assert torn_fraction("w") is None  # times exhausted
        inject("w")                        # raise still counting its after


def test_clear():
    inj = FaultInjector()
    inj.arm("a", kind="raise")
    inj.arm("b", kind="raise")
    inj.clear("a")
    with inj:
        inject("a")                        # cleared: no-op
        with pytest.raises(InjectedFault):
            inject("b")
    inj.clear()
    with inj:
        inject("b")                        # clear() drops everything


def test_fault_emits_event_and_counter():
    c = get_registry().counter("faults_injected_total",
                               {"point": "tele", "kind": "raise"})
    before = c.value
    inj = FaultInjector()
    inj.arm("tele", kind="raise", times=1)
    with inj:
        with pytest.raises(InjectedFault):
            inject("tele", step=3)
    assert c.value == before + 1
    evs = [e for e in get_event_log().tail(50)
           if e["kind"] == "fault_injected" and e.get("point") == "tele"]
    assert evs and evs[-1]["step"] == 3
