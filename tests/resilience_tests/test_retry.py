"""RetryPolicy: bounded attempts, deterministic backoff, telemetry."""

import pytest

from chainermn_tpu.monitor import get_event_log, get_registry
from chainermn_tpu.resilience import FaultInjector, InjectedFault, RetryPolicy
from chainermn_tpu.resilience.faults import inject


def _flaky(n_failures, exc=RuntimeError):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= n_failures:
            raise exc(f"transient {calls['n']}")
        return "ok"

    return fn, calls


def test_succeeds_after_transients():
    c = get_registry().counter("retries_total", {"op": "t.ok"})
    before = c.value
    fn, calls = _flaky(2)
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0)
    assert policy.call(fn, op="t.ok") == "ok"
    assert calls["n"] == 3
    assert c.value == before + 2           # two absorbed transients


def test_exhaustion_reraises_last_error():
    c = get_registry().counter("retries_exhausted_total", {"op": "t.bad"})
    before = c.value
    fn, calls = _flaky(99)
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0)
    with pytest.raises(RuntimeError, match="transient 3"):
        policy.call(fn, op="t.bad")
    assert calls["n"] == 3
    assert c.value == before + 1
    evs = [e for e in get_event_log().tail(50)
           if e["kind"] == "retry_exhausted" and e.get("op") == "t.bad"]
    assert evs and evs[-1]["attempts"] == 3


def test_retry_on_filter_propagates_immediately():
    fn, calls = _flaky(99, exc=ValueError)
    policy = RetryPolicy(max_attempts=5, base_delay_s=0.001,
                         retry_on=(KeyError,))
    with pytest.raises(ValueError):
        policy.call(fn, op="t.filtered")
    assert calls["n"] == 1                 # a shape error is not a transient


def test_backoff_shape_and_determinism():
    p = RetryPolicy(max_attempts=9, base_delay_s=0.1, multiplier=2.0,
                    max_delay_s=0.5, jitter=0)
    assert [p.delay_s(k) for k in (1, 2, 3, 4, 5)] == \
        [0.1, 0.2, 0.4, 0.5, 0.5]          # exponential, capped
    a = RetryPolicy(max_attempts=9, jitter=0.5, seed=3)
    b = RetryPolicy(max_attempts=9, jitter=0.5, seed=3)
    seq_a = [a.delay_s(k) for k in range(1, 6)]
    assert seq_a == [b.delay_s(k) for k in range(1, 6)]   # seeded jitter
    assert all(d > 0 for d in seq_a)


def test_invalid_attempts_rejected():
    with pytest.raises(ValueError):
        RetryPolicy(0)


def test_wrap_is_drop_in():
    fn, calls = _flaky(1)
    wrapped = RetryPolicy(3, base_delay_s=0.001, jitter=0).wrap(fn, op="t.w")
    assert wrapped() == "ok" and calls["n"] == 2


def test_absorbs_injected_fault():
    """The chaos story end-to-end: an armed transient at a cut-point inside
    the retried body is absorbed exactly like a real one."""
    inj = FaultInjector()
    inj.arm("t.cut", kind="raise", times=1)
    policy = RetryPolicy(3, base_delay_s=0.001, jitter=0)

    def op():
        inject("t.cut")
        return 42

    with inj:
        assert policy.call(op, op="t.cut") == 42
    assert inj.fired_log == [("t.cut", "raise")]


def test_injected_fault_outlasting_budget_escapes():
    inj = FaultInjector()
    inj.arm("t.cut2", kind="raise", times=None)    # every attempt fails
    policy = RetryPolicy(3, base_delay_s=0.001, jitter=0)
    with inj:
        with pytest.raises(InjectedFault):
            policy.call(lambda: inject("t.cut2"), op="t.cut2")
