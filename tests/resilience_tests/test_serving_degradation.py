"""Serving graceful degradation: bounded admission, deadline shedding, the
terminal ERRORED state, error propagation, warm engine restart."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models import TransformerLM, generate
from chainermn_tpu.resilience import FaultInjector, InjectedFault, RetryPolicy
from chainermn_tpu.serving import (
    DeadlineExceededError,
    EngineFailed,
    FCFSScheduler,
    QueueFullError,
    RequestState,
    ServingClient,
    ServingEngine,
)


@pytest.fixture(scope="module")
def lm_and_params():
    lm = TransformerLM(vocab_size=17, d_model=16, n_heads=4, n_layers=1,
                       max_len=48, compute_dtype=jnp.float32)
    params = lm.init(jax.random.PRNGKey(0),
                     jnp.asarray([[1, 2, 3]], jnp.int32))
    return lm, params


def make(lm, params, n_slots=2, **kw):
    engine = ServingEngine(lm, params, n_slots=n_slots, prefill_len=6,
                           cache_len=32)
    return engine, FCFSScheduler(engine, **kw)


# --------------------------------------------------------------------- #
# bounded admission                                                      #
# --------------------------------------------------------------------- #


def test_queue_full_rejects_at_submit(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, max_queue=2)
    r1 = sched.submit(np.array([1]), 2)
    r2 = sched.submit(np.array([2]), 2)
    with pytest.raises(QueueFullError):
        sched.submit(np.array([3]), 2)
    assert sched.metrics.report()["requests_rejected"] == 1
    sched.run_until_idle()                 # accepted work is unaffected
    assert r1.state is RequestState.DONE and r2.state is RequestState.DONE


def test_queue_drains_reopen_admission(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, max_queue=1)
    sched.submit(np.array([1]), 1)
    with pytest.raises(QueueFullError):
        sched.submit(np.array([2]), 1)
    sched.run_until_idle()
    r = sched.submit(np.array([2]), 1)     # capacity is back
    sched.run_until_idle()
    assert r.state is RequestState.DONE


def test_max_queue_validation(lm_and_params):
    lm, params = lm_and_params
    with pytest.raises(ValueError, match="max_queue"):
        make(lm, params, max_queue=0)


# --------------------------------------------------------------------- #
# deadlines                                                              #
# --------------------------------------------------------------------- #


def test_expired_queued_requests_are_shed(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, default_deadline_s=0.05)
    # generous override so r1 holding the only slot isn't itself shed
    # mid-decode by the round-18 total-service-time contract
    r1 = sched.submit(np.array([1]), 8, deadline_s=30.0)
    r2 = sched.submit(np.array([2]), 2)
    r3 = sched.submit(np.array([3]), 2, deadline_s=30.0)  # generous override
    sched.step()                           # r1 takes the only slot
    time.sleep(0.1)                        # r2's deadline expires queued
    sched.run_until_idle()
    assert r1.state is RequestState.DONE
    assert r2.state is RequestState.ERRORED
    with pytest.raises(DeadlineExceededError):
        r2.wait(timeout=1)
    with pytest.raises(DeadlineExceededError):
        _ = r2.output
    assert r2.error.retry_after_s is not None   # structured backoff hint
    assert r3.state is RequestState.DONE   # per-request deadline respected
    assert sched.metrics.report()["requests_shed"] == 1


def test_deadline_bounds_total_service_time(lm_and_params):
    """Round 18 contract: the deadline bounds TOTAL service time, not
    just queue wait — a request still decoding past its deadline is
    retired at the next step boundary (its already-delivered tokens
    stand; the terminal error says how far it got)."""
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, default_deadline_s=0.05)
    r = sched.submit(np.array([1]), 6)
    sched.step()                           # admitted within deadline
    got = len(r.tokens)
    assert got >= 1                        # decoding had started
    time.sleep(0.1)                        # ...then blew its budget
    sched.run_until_idle()
    assert r.state is RequestState.ERRORED
    with pytest.raises(DeadlineExceededError, match="decoded token"):
        r.wait(timeout=1)
    assert r.error.retry_after_s is not None


# --------------------------------------------------------------------- #
# engine exception boundary + warm restart                               #
# --------------------------------------------------------------------- #


def test_engine_raise_errors_in_flight_and_restarts(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=2)
    r0 = sched.submit(np.array([7, 8]), 2)     # warm both executables
    sched.run_until_idle()
    assert r0.state is RequestState.DONE
    compiles_before = engine.compile_counts()
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", after=1, times=1)
    with inj:
        r1 = sched.submit(np.array([1, 2]), 6)
        r2 = sched.submit(np.array([3, 4]), 6)
        sched.run_until_idle()
        # both were in flight when decode raised: terminal ERRORED, loudly
        for r in (r1, r2):
            assert r.state is RequestState.ERRORED
            with pytest.raises(EngineFailed) as ei:
                r.wait(timeout=1)
            assert isinstance(ei.value.__cause__, InjectedFault)
        # the engine warm-restarted: same compiled programs, fresh slots
        assert sched.engine_restarts == 1
        assert engine.free_slots == {0, 1}
        r3 = sched.submit(np.array([5, 6]), 4)
        sched.run_until_idle()
    assert r3.state is RequestState.DONE
    # zero recompiles across the restart (same shapes/shardings)
    assert engine.compile_counts() == compiles_before
    # post-restart output is still correct, not just terminal
    ref = generate(lm, params, jnp.asarray([[5, 6]], jnp.int32), 4)
    np.testing.assert_array_equal(r3.output, np.asarray(ref[0]))
    m = sched.metrics.report()
    assert m["requests_errored"] == 2 and m["engine_restarts"] == 1


@pytest.mark.slow  # ~6s; in-flight raise + warm restart stays tier-1 via test_engine_raise_errors_in_flight_and_restarts — keep tier-1 inside its timeout
def test_spec_verify_raise_errors_in_flight_and_restarts(lm_and_params):
    """The speculative target-verify call is an engine-failure boundary
    like ``serving.decode``: a raise inside ``serving.spec_verify``
    fails every in-flight request loudly, the warm restart resets the
    drafter alongside the slots, and post-restart speculative traffic
    decodes to parity with zero recompiles."""
    from chainermn_tpu.serving import SpeculativeConfig
    lm, params = lm_and_params
    engine = ServingEngine(lm, params, n_slots=2, prefill_len=6,
                           cache_len=32, paged=True, kv_block_size=2,
                           speculative=SpeculativeConfig(k=2))
    engine.warmup()
    sched = FCFSScheduler(engine)
    compiles_before = engine.compile_counts()
    inj = FaultInjector()
    inj.arm("serving.spec_verify", kind="raise", after=1, times=1)
    with inj:
        r1 = sched.submit(np.array([1, 2]), 6)
        r2 = sched.submit(np.array([3, 4]), 6)
        sched.run_until_idle()
        for r in (r1, r2):
            assert r.state is RequestState.ERRORED
            with pytest.raises(EngineFailed) as ei:
                r.wait(timeout=1)
            assert isinstance(ei.value.__cause__, InjectedFault)
        assert sched.engine_restarts == 1
        assert engine.free_slots == {0, 1}
        r3 = sched.submit(np.array([5, 6]), 4)
        sched.run_until_idle()
    assert r3.state is RequestState.DONE
    assert engine.compile_counts() == compiles_before
    ref = generate(lm, params, jnp.asarray([[5, 6]], jnp.int32), 4)
    np.testing.assert_array_equal(r3.output, np.asarray(ref[0]))


def test_prefill_raise_errors_admitting_request(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1)
    inj = FaultInjector()
    inj.arm("serving.prefill", kind="raise", times=1)
    with inj:
        r1 = sched.submit(np.array([1, 2]), 3)
        r2 = sched.submit(np.array([3, 4]), 3)
        sched.run_until_idle()
    assert r1.state is RequestState.ERRORED    # the admitting victim
    assert r2.state is RequestState.DONE       # queue kept being served


def test_prefill_retry_absorbs_transient(lm_and_params):
    """With an admission RetryPolicy, an injected transient prefill fault
    never becomes an engine failure — the request just completes."""
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1,
                         retry=RetryPolicy(3, base_delay_s=0.001, jitter=0))
    inj = FaultInjector()
    inj.arm("serving.prefill", kind="raise", times=1)
    with inj:
        r = sched.submit(np.array([1, 2]), 3)
        sched.run_until_idle()
    assert r.state is RequestState.DONE
    assert sched.engine_restarts == 0
    assert sched.metrics.report()["requests_errored"] == 0


def test_restart_disabled_reraises(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, restart_on_error=False)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", times=1)
    with inj:
        r = sched.submit(np.array([1, 2]), 4)
        with pytest.raises(InjectedFault):
            sched.run_until_idle()
    assert r.state is RequestState.ERRORED     # still no silent hang


def test_restart_budget_exhausted_reraises(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, max_restarts=1)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", times=None)
    with inj:
        sched.submit(np.array([1, 2]), 4)
        sched.submit(np.array([3, 4]), 4)
        with pytest.raises(InjectedFault):
            sched.run_until_idle()
    assert sched.engine_restarts == 1


# --------------------------------------------------------------------- #
# error propagation surfaces (satellite)                                 #
# --------------------------------------------------------------------- #


def test_streaming_iterator_reraises(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", after=2, times=1)
    with inj:
        r = sched.submit(np.array([1, 2]), 8)
        sched.run_until_idle()
    got = []
    with pytest.raises(EngineFailed):
        for tok in r.stream():
            got.append(tok)
    assert got == r.tokens and len(got) >= 1   # delivered prefix, then raise


def test_stream_of_successful_request_terminates(lm_and_params):
    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1)
    r = sched.submit(np.array([1, 2]), 4)
    sched.run_until_idle()
    assert list(r.stream()) == r.tokens and len(r.tokens) == 4


def test_client_reraises_in_caller_thread(lm_and_params):
    lm, params = lm_and_params
    engine = ServingEngine(lm, params, n_slots=2, prefill_len=6,
                           cache_len=32)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", after=1, times=1)
    with inj, ServingClient(engine) as client:
        with pytest.raises(EngineFailed):
            client.generate(np.array([1, 2]), 6, timeout=120)
        # the engine restarted under the client thread: still serving
        out = client.generate(np.array([5, 6]), 4, timeout=120)
    ref = generate(lm, params, jnp.asarray([[5, 6]], jnp.int32), 4)
    np.testing.assert_array_equal(out, np.asarray(ref[0]))


def test_no_stranded_clients_on_transient_hang(lm_and_params):
    """Acceptance: with an injected engine hang, every submitted request
    reaches a terminal state, nothing blocks forever. Under the round-18
    total-service-time deadline the 0.4s stall blows every request's
    0.2s budget — in-flight work is retired at the first step boundary
    after the stall clears (a loud DeadlineExceededError, not a silent
    late answer), queued work sheds the same way."""
    lm, params = lm_and_params
    engine = ServingEngine(lm, params, n_slots=2, prefill_len=6,
                           cache_len=32)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="hang", hang_s=0.4, after=1, times=1)
    reqs = []
    with inj, ServingClient(engine, default_deadline_s=0.2) as client:
        for i in range(6):                 # 2 in flight, 4 queued
            reqs.append(client.submit(np.array([1 + i, 2 + i]), 8))
        t0 = time.perf_counter()
        states = []
        for r in reqs:
            try:
                finished = r.wait(timeout=30)
                assert finished
                states.append(r.state)
            except DeadlineExceededError:
                states.append(r.state)
        waited = time.perf_counter() - t0
    assert waited < 30                     # nobody blocked forever
    assert all(s in (RequestState.DONE, RequestState.ERRORED)
               for s in states)
    # the stall consumed every budget: all shed, each with a backoff hint
    assert states.count(RequestState.ERRORED) == len(reqs)
    assert all(r.error.retry_after_s is not None for r in reqs)


def test_degradation_is_observable(lm_and_params):
    """Every reject/shed/errored/restart shows up in the registry snapshot
    and the flight recorder (acceptance)."""
    from chainermn_tpu import monitor

    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=1, max_queue=2,
                         default_deadline_s=0.03)
    sched.submit(np.array([1]), 6)
    queued = sched.submit(np.array([2]), 2)
    with pytest.raises(QueueFullError):
        sched.submit(np.array([3]), 2)     # 2 already queued: bounced
    sched.step()
    time.sleep(0.06)
    inj = FaultInjector()
    inj.arm("serving.decode", kind="raise", times=1)
    with inj:
        sched.run_until_idle()
    assert queued.state is RequestState.ERRORED
    snap = monitor.snapshot()
    for name in ("serving_requests_rejected_total",
                 "serving_requests_shed_total",
                 "serving_requests_errored_total",
                 "serving_scheduler_restarts_total",
                 "faults_injected_total"):
        hits = {k: v for k, v in snap["counters"].items()
                if k.startswith(name)}
        assert any(v > 0 for v in hits.values()), (name, hits)
    kinds = [e["kind"] for e in monitor.get_event_log().tail(200)]
    for kind in ("reject", "shed", "engine_error", "engine_restart",
                 "fault_injected"):
        assert kind in kinds, kind


# --------------------------------------------------------------------- #
# batched admission + prefix copy: contained failure (PR 5)              #
# --------------------------------------------------------------------- #


def make_fast_path(lm, params, **kw):
    """An engine with the PR-5 admission fast path on: bucket ladder,
    batch-2 prefill, prefix cache."""
    engine = ServingEngine(lm, params, n_slots=3,
                           prefill_buckets=(4, 6), prefill_batch=2,
                           prefix_cache_blocks=8, prefix_block_size=2,
                           cache_len=32)
    engine.warmup()
    return engine, FCFSScheduler(engine, **kw)


def test_prefill_batch_fault_errors_only_the_group(lm_and_params):
    """Chaos-smoke (acceptance): a fault during BATCHED admission errors
    only the admitting group — the slot already decoding keeps decoding
    to a correct completion, no restart is burned, no waiter strands."""
    lm, params = lm_and_params
    engine, sched = make_fast_path(lm, params)
    inflight = sched.submit(np.array([9, 10]), 8)
    sched.step()                               # decoding before the fault
    assert inflight.slot >= 0
    inj = FaultInjector()
    inj.arm("serving.prefill_batch", kind="raise", times=1)
    with inj:
        v1 = sched.submit(np.array([1, 2]), 4)
        v2 = sched.submit(np.array([3, 4]), 4)
        sched.run_until_idle()
    # the group died terminally and loudly...
    for v in (v1, v2):
        assert v.state is RequestState.ERRORED
        with pytest.raises(EngineFailed) as ei:
            v.wait(timeout=1)
        assert isinstance(ei.value.__cause__, InjectedFault)
    # ...but the engine never restarted and the in-flight request is
    # untouched: token-for-token a solo decode
    assert sched.engine_restarts == 0
    assert inflight.state is RequestState.DONE
    ref = generate(lm, params, jnp.asarray([[9, 10]], jnp.int32), 8)
    np.testing.assert_array_equal(inflight.output, np.asarray(ref[0]))
    # and admission keeps working after the contained failure
    r = sched.submit(np.array([5, 6]), 3)
    sched.run_until_idle()
    assert r.state is RequestState.DONE


def test_prefix_copy_fault_is_contained_too(lm_and_params):
    """A fault at the prefix-copy cut-point (the fetch before the batched
    prefill) is contained the same way: only the group errors; a later
    identical prompt still admits and matches solo decode."""
    lm, params = lm_and_params
    engine, sched = make_fast_path(lm, params)
    donor = sched.submit(np.array([1, 2, 3, 4, 5]), 2)   # seeds the trie
    sched.run_until_idle()
    assert donor.state is RequestState.DONE
    inj = FaultInjector()
    inj.arm("serving.prefix_copy", kind="raise", times=1)
    with inj:
        victim = sched.submit(np.array([1, 2, 3, 4, 6]), 4)  # hits -> fetch
        sched.run_until_idle()
    assert victim.state is RequestState.ERRORED
    assert sched.engine_restarts == 0
    redo = sched.submit(np.array([1, 2, 3, 4, 6]), 4)
    sched.run_until_idle()
    ref = generate(lm, params, jnp.asarray([[1, 2, 3, 4, 6]], jnp.int32), 4)
    np.testing.assert_array_equal(redo.output, np.asarray(ref[0]))


def test_batch_retry_absorbs_transient_admission_fault(lm_and_params):
    """RetryPolicy wraps the WHOLE batched admission (fetch + prefill are
    idempotent until commit): one transient fault, zero errored
    requests."""
    lm, params = lm_and_params
    engine, sched = make_fast_path(
        lm, params, retry=RetryPolicy(3, base_delay_s=0.001, jitter=0))
    inj = FaultInjector()
    inj.arm("serving.prefill_batch", kind="raise", times=1)
    with inj:
        r1 = sched.submit(np.array([1, 2]), 3)
        r2 = sched.submit(np.array([3, 4]), 3)
        sched.run_until_idle()
    assert r1.state is RequestState.DONE and r2.state is RequestState.DONE
    assert sched.engine_restarts == 0
    assert sched.metrics.report()["requests_errored"] == 0


# --------------------------------------------------------------------- #
# fleet tier: route faults, replica failure, quarantine (ISSUE 8)        #
# --------------------------------------------------------------------- #


def make_fleet(lm, params, n=2, **kw):
    from chainermn_tpu.fleet import FleetRouter

    engines = [ServingEngine(lm, params, n_slots=2, prefill_len=6,
                             cache_len=32) for _ in range(n)]
    return FleetRouter(engines, **kw)


def test_fleet_route_fault_falls_back_then_replica_fault_reroutes(
        lm_and_params):
    """One router session, both fleet cut-points. (1) ``fleet.route``
    raise: placement degrades to the lowest-id accepting replica — the
    request still lands, with solo parity. (2) ``fleet.replica`` raise:
    the supervisor fails in-flight work loudly, drains QUEUED work,
    warm-restarts the replica (no recompiles), and the router replays
    the affected requests on a healthy replica — every request DONE
    with solo parity, and the fleet keeps serving after."""
    lm, params = lm_and_params
    with make_fleet(lm, params, max_restarts=2) as router:
        assert router.wait_ready(300)
        inj = FaultInjector()
        inj.arm("fleet.route", kind="raise", times=1)
        with inj:
            fr = router.submit(np.array([3, 4, 5]), 4)
        assert fr.wait(timeout=120)
        assert fr.state is RequestState.DONE
        assert fr.replica_id == 0                    # the fallback replica
        assert router.fleet_report()["route_fallbacks_total"] >= 1
        ref = generate(lm, params, jnp.asarray([[3, 4, 5]], jnp.int32), 4)
        np.testing.assert_array_equal(fr.output, np.asarray(ref[0]))
        # (2) replica-level failure -> supervisor restart + re-route
        inj2 = FaultInjector()
        inj2.arm("fleet.replica", kind="raise", times=1)
        with inj2:
            frs = [router.submit(np.array([1 + i, 2 + i]), 6)
                   for i in range(4)]
            for r in frs:
                assert r.wait(timeout=120)
        assert all(r.state is RequestState.DONE for r in frs)
        for i, r in enumerate(frs):
            ref = generate(lm, params,
                           jnp.asarray([[1 + i, 2 + i]], jnp.int32), 6)
            np.testing.assert_array_equal(r.output, np.asarray(ref[0]))
        assert sum(r.restarts for r in router.replicas) == 1
        assert router.capacity == 2                  # restarted, not lost
        for r in router.replicas:
            assert r.engine.recompiles == {}         # warm restart
        # and the fleet is still serving
        out = router.generate(np.array([9, 9]), 3, timeout=120)
        ref = generate(lm, params, jnp.asarray([[9, 9]], jnp.int32), 3)
        np.testing.assert_array_equal(out, np.asarray(ref[0]))


def test_fleet_quarantine_shrinks_capacity_sheds_counted(lm_and_params):
    """Past max_restarts the supervisor quarantines: capacity shrinks to
    the survivors, fleet-edge sheds are counted against the global
    queue bound, and no waiter strands — every accepted request reaches
    a terminal state on the surviving replica."""
    lm, params = lm_and_params
    router = make_fleet(lm, params, max_restarts=0, max_queue=2,
                        autostart=False)
    try:
        accepted = [router.submit(np.array([1 + i, 2 + i]), 3)
                    for i in range(2)]
        from chainermn_tpu.serving import QueueFullError

        with pytest.raises(QueueFullError):          # edge shed, counted
            router.submit(np.array([9, 9]), 3)
        inj = FaultInjector()
        inj.arm("fleet.replica", kind="raise", times=1)
        with inj:
            router.start()
            assert router.wait_ready(300)
            for fr in accepted:                      # no stranded waiters
                assert fr.wait(timeout=120)
        assert all(fr.state is RequestState.DONE for fr in accepted)
        rep = router.fleet_report()
        assert router.capacity == 1                  # quarantined, for good
        states = sorted(v["state"] for v in rep["replicas"].values())
        assert states == ["healthy", "quarantined"]
        assert rep["shed_total"] >= 1
        # the quarantined replica's drained work was re-routed or it had
        # none; either way the fleet serves on
        out = router.generate(np.array([5, 6]), 4, timeout=120)
        ref = generate(lm, params, jnp.asarray([[5, 6]], jnp.int32), 4)
        np.testing.assert_array_equal(out, np.asarray(ref[0]))
    finally:
        router.close()


def test_publish_fault_fails_commit_engine_unharmed(lm_and_params):
    """Chaos case (ISSUE 10): a fault at the ``deploy.publish`` cut-point
    kills the commit BEFORE any fence goes up — the publish fails loudly
    (PublishError caused by the injected fault, counted and event-logged),
    the engine never leaves version 0, the mid-decode request finishes
    token-exact on the old weights, and a retried publish lands."""
    from chainermn_tpu import monitor
    from chainermn_tpu.deploy import PublishError, WeightPublisher

    lm, params = lm_and_params
    engine, sched = make(lm, params, n_slots=2)
    pub = WeightPublisher(engine, sched)
    r = sched.submit(np.array([1, 2]), 6)
    sched.step()                             # decoding when the fault hits
    new = jax.tree_util.tree_map(lambda l: l * 1.001, params)
    inj = FaultInjector()
    inj.arm("deploy.publish", kind="raise", times=1)
    with inj:
        with pytest.raises(PublishError) as ei:
            pub.publish_async(new)
    assert isinstance(ei.value.__cause__, InjectedFault)
    assert engine.weight_version == 0        # prior version, by construction
    sched.run_until_idle()                   # no fence was left behind
    assert r.state is RequestState.DONE and r.weight_version == 0
    ref = generate(lm, params, jnp.asarray([[1, 2]], jnp.int32), 6)
    np.testing.assert_array_equal(r.output, np.asarray(ref[0]))
    # observable through the shared telemetry spine
    snap = monitor.snapshot()
    fails = {k: v for k, v in snap["counters"].items()
             if k.startswith("deploy_swap_failures_total")}
    assert any(v > 0 for v in fails.values()), fails
    kinds = [e["kind"] for e in monitor.get_event_log().tail(200)]
    assert "publish_failed" in kinds
    # the failure was transient: the disarmed retry goes through
    h = pub.publish_async(new)
    while not h.done:
        sched.step()
    assert h.wait(0) == 1 and engine.weight_version == 1


def test_kv_append_fault_preempts_without_burning_a_restart(lm_and_params):
    """Chaos case (PR 7): an injected fault at the paged engine's lazy
    block append is contained by PREEMPTING only that slot's request —
    requeued, replayed, finished — while the other slot decodes straight
    through to a solo-parity completion. No engine restart, no ERRORED
    request, exactly one preemption counted."""
    lm, params = lm_and_params
    engine = ServingEngine(lm, params, n_slots=2, prefill_len=6,
                           cache_len=24, paged=True, kv_block_size=4)
    engine.warmup()
    sched = FCFSScheduler(engine)
    inj = FaultInjector()
    inj.arm("serving.kv_append", kind="raise", times=1)
    ra = sched.submit(np.array([1, 2, 3]), 8)    # crosses a boundary first
    rb = sched.submit(np.array([4, 5]), 8)
    with inj:
        sched.run_until_idle()
    assert inj.fired_log == [("serving.kv_append", "raise")]
    assert sched.engine_restarts == 0
    assert ra.state is RequestState.DONE and rb.state is RequestState.DONE
    for req, prompt in ((ra, [1, 2, 3]), (rb, [4, 5])):
        ref = generate(lm, params, jnp.asarray([prompt], jnp.int32), 8)
        np.testing.assert_array_equal(req.output, np.asarray(ref[0]))
    m = sched.metrics.report()
    assert m["kv_preemptions"] == 1
    assert m["requests_errored"] == 0
