"""Chaos coverage: the serving stack under seeded randomized injected
faults. Invariants: every request terminates (DONE/ERRORED — never a
stranded waiter), survivors are token-for-token equal to solo
``generate()``, and every fault is observable in the registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu import monitor
from chainermn_tpu.models import TransformerLM, generate
from chainermn_tpu.resilience import FaultInjector
from chainermn_tpu.serving import RequestState, ServingClient, ServingEngine


@pytest.fixture(scope="module")
def lm_and_params():
    lm = TransformerLM(vocab_size=17, d_model=16, n_heads=4, n_layers=1,
                       max_len=48, compute_dtype=jnp.float32)
    params = lm.init(jax.random.PRNGKey(0),
                     jnp.asarray([[1, 2, 3]], jnp.int32))
    return lm, params


def _drive(lm, params, injector, jobs, *, n_slots=2, deadline_s=30.0):
    """Submit every job under the injector; wait all out; return requests
    (every one in a terminal state or the test fails)."""
    engine = ServingEngine(lm, params, n_slots=n_slots, prefill_len=8,
                           cache_len=32)
    reqs = []
    with injector, ServingClient(
            engine, default_deadline_s=deadline_s) as client:
        for prompt, n in jobs:
            reqs.append(client.submit(prompt, n))
        for r in reqs:
            try:
                assert r.wait(timeout=120), "request never terminated"
            except Exception:
                pass                       # stored failure: terminal too
    states = [r.state for r in reqs]
    assert all(s in (RequestState.DONE, RequestState.ERRORED)
               for s in states), states
    return reqs


def _check_survivor_parity(lm, params, reqs, jobs):
    done = [(r, j) for r, j in zip(reqs, jobs)
            if r.state is RequestState.DONE]
    assert done, "chaos killed every request — faults are mis-scaled"
    for r, (prompt, n) in done:
        ref = generate(lm, params, jnp.asarray(prompt)[None], n)
        np.testing.assert_array_equal(r.output, np.asarray(ref[0]))
    return len(done)


def _jobs(rng, n, vocab=17, max_prompt=8, max_new=8):
    return [(rng.randint(1, vocab, rng.randint(1, max_prompt + 1))
             .astype(np.int32), int(rng.randint(1, max_new + 1)))
            for _ in range(n)]


def test_chaos_smoke_seeded(lm_and_params):
    """Fast tier-1 cell: bounded raise faults at both engine cut-points;
    everything terminates, survivors match solo decode."""
    lm, params = lm_and_params
    rng = np.random.RandomState(0)
    jobs = _jobs(rng, 10)
    inj = FaultInjector(seed=0)
    inj.arm("serving.decode", kind="raise", after=3, times=2)
    inj.arm("serving.prefill", kind="raise", after=2, times=1)
    reqs = _drive(lm, params, inj, jobs)
    assert len(inj.fired_log) == 3         # all armed faults actually fired
    n_done = _check_survivor_parity(lm, params, reqs, jobs)
    n_err = sum(r.state is RequestState.ERRORED for r in reqs)
    assert n_done + n_err == len(jobs)
    snap = monitor.snapshot()
    fired = {k: v for k, v in snap["counters"].items()
             if k.startswith("faults_injected_total")}
    assert sum(fired.values()) >= 3


@pytest.mark.slow
def test_chaos_soak_randomized(lm_and_params):
    """Soak: a larger randomized workload under probabilistic raise faults
    plus transient delay/hang stalls, all from one seed — the run replays
    exactly. Every request terminates; survivors stay token-for-token
    equal to solo ``generate()``; restarts stay within budget."""
    lm, params = lm_and_params
    rng = np.random.RandomState(1)
    jobs = _jobs(rng, 40)
    inj = FaultInjector(seed=1)
    inj.arm("serving.decode", kind="raise", p=0.03, times=3, after=5)
    inj.arm("serving.prefill", kind="raise", p=0.05, times=2, after=5)
    inj.arm("serving.decode", kind="delay", p=0.05, times=5, delay_s=0.02)
    inj.arm("serving.decode", kind="hang", times=1, after=30, hang_s=0.3)
    reqs = _drive(lm, params, inj, jobs, n_slots=3, deadline_s=60.0)
    n_done = _check_survivor_parity(lm, params, reqs, jobs)
    n_err = sum(r.state is RequestState.ERRORED for r in reqs)
    assert n_done + n_err == len(jobs)
    assert n_done >= len(jobs) // 2        # chaos degrades, not destroys
    # the stalls really happened and really were absorbed
    kinds = {}
    for point, kind in inj.fired_log:
        kinds[kind] = kinds.get(kind, 0) + 1
    assert kinds.get("delay", 0) >= 1 and kinds.get("hang", 0) == 1
