"""resilient_fit: crash-resume bit-exactness, restore budget, checkpoint
torn-write hardening (kill mid-write / silent truncation), dump idempotence."""

import io
import pickle

import jax
import numpy as np
import pytest

from chainermn_tpu import (
    SerialIterator,
    create_communicator,
    create_multi_node_checkpointer,
)
from chainermn_tpu.monitor import get_event_log, get_registry
from chainermn_tpu.resilience import (
    FaultInjector,
    InjectedFault,
    ResilientTrainer,
    RetryPolicy,
    resilient_fit,
)


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


def _dataset():
    return [float(i) for i in range(20)]


def _iterator():
    return SerialIterator(_dataset(), batch_size=3, shuffle=True, seed=5)


def _step(state, batch):
    """Deterministic step over a state pytree that includes a PRNG key —
    the key must round-trip through the snapshot for bit-exact resume."""
    key, sub = jax.random.split(state["key"])
    noise = float(jax.random.uniform(sub, ()))
    w = state["w"] * 0.9 + float(np.mean(batch)) + 0.01 * noise
    return {"w": w, "key": key}


def _init_state():
    return {"w": 0.0, "key": jax.random.PRNGKey(42)}


def _run(tmp_path, comm, n_steps, *, name, injector=None, save_every=4,
         **fit_kw):
    ckpt = create_multi_node_checkpointer(name, comm, path=str(tmp_path))
    traj: list[tuple[int, float]] = []

    def on_step(i, state):
        traj.append((i, state["w"]))

    if injector is not None:
        with injector:
            state, report = resilient_fit(
                _step, _init_state(), _iterator(), n_steps, ckpt,
                save_every=save_every, on_step=on_step, **fit_kw)
    else:
        state, report = resilient_fit(
            _step, _init_state(), _iterator(), n_steps, ckpt,
            save_every=save_every, on_step=on_step, **fit_kw)
    return state, report, traj


def test_crash_resume_bit_exact(tmp_path, comm):
    """Acceptance: a fault injected at step k, recovered via snapshot
    restore, leaves the post-resume trajectory IDENTICAL to an
    uninterrupted run (state + RNG key + iterator order all round-trip)."""
    ref_state, ref_report, ref_traj = _run(
        tmp_path / "ref", comm, 12, name="ref")
    assert ref_report["failures"] == 0 and ref_report["restores"] == 0

    inj = FaultInjector()
    inj.arm("trainer.step", kind="raise", after=7, times=1)  # fails at i=7
    state, report, traj = _run(tmp_path / "crash", comm, 12, name="crash",
                               injector=inj)
    assert report["failures"] == 1 and report["restores"] == 1
    assert report["mttr_s"] and report["mttr_s"][0] > 0

    # replayed steps (4..7 re-run from the iteration-4 snapshot) must equal
    # their first-pass values exactly — and the whole run must equal the
    # uninterrupted reference, float-for-float
    final = {}
    for i, w in traj:
        if i in final:
            assert w == final[i], f"replay of step {i} diverged"
        final[i] = w
    assert final == dict(ref_traj)
    assert state["w"] == ref_state["w"]
    np.testing.assert_array_equal(np.asarray(state["key"]),
                                  np.asarray(ref_state["key"]))


def test_cross_launch_resume(tmp_path, comm):
    """A fresh process over the same snapshot dir continues where the last
    one stopped, and lands on the same trajectory."""
    ref_state, _, ref_traj = _run(tmp_path / "r", comm, 10, name="x")

    _run(tmp_path / "s", comm, 6, name="y")           # "first launch"
    state, report, traj = _run(tmp_path / "s", comm, 10, name="y")
    assert report["resumed_from"] == 6                 # snapshot at n_steps
    assert [i for i, _ in traj] == [6, 7, 8, 9]
    assert dict(traj) == {i: w for i, w in ref_traj if i >= 6}
    assert state["w"] == ref_state["w"]


def test_restore_budget_exhausted_reraises(tmp_path, comm):
    c = get_registry().counter("trainer_failures_total")
    before = c.value
    inj = FaultInjector()
    inj.arm("trainer.step", kind="raise", times=None)  # every step fails
    with pytest.raises(InjectedFault):
        _run(tmp_path, comm, 8, name="doomed", injector=inj,
             max_restores=2, dump_on_failure=False)
    # initial failure + one per restore attempt + the one that gives up
    assert c.value == before + 3
    evs = [e["kind"] for e in get_event_log().tail(100)]
    assert "trainer_giving_up" in evs


def test_transient_checkpoint_io_absorbed_by_retry(tmp_path, comm):
    """An injected transient in checkpoint I/O is retried away before it
    counts as a training failure."""
    inj = FaultInjector()
    inj.arm("checkpoint.save", kind="raise", after=1, times=1)
    state, report, _ = _run(
        tmp_path, comm, 8, name="t", injector=inj,
        retry=RetryPolicy(3, base_delay_s=0.001, jitter=0))
    assert report["failures"] == 0 and report["restores"] == 0
    assert inj.fired_log == [("checkpoint.save", "raise")]


# --------------------------------------------------------------------- #
# torn-snapshot hardening (satellite)                                    #
# --------------------------------------------------------------------- #


def test_kill_mid_write_resume_succeeds(tmp_path, comm):
    """Fault-injection acceptance: die mid-write of the snapshot tmp file;
    the next launch sweeps the orphan and resumes from the previous
    intact iteration."""
    ckpt = create_multi_node_checkpointer("k", comm, path=str(tmp_path))
    ckpt.save({"w": 1.0}, 1)
    inj = FaultInjector()
    inj.arm("checkpoint.write", kind="raise", times=1)
    with inj:
        with pytest.raises(InjectedFault):
            ckpt.save({"w": 2.0}, 2)
    import os
    assert os.path.exists(ckpt.filename(2) + ".tmp")   # torn tmp left
    assert not os.path.exists(ckpt.filename(2))        # rename never ran

    ckpt2 = create_multi_node_checkpointer("k", comm, path=str(tmp_path))
    assert not os.path.exists(ckpt.filename(2) + ".tmp")  # startup sweep
    state, it = ckpt2.maybe_load()
    assert it == 1 and state["w"] == 1.0


def test_mid_write_crash_absorbed_by_retry(tmp_path, comm):
    ckpt = create_multi_node_checkpointer(
        "kr", comm, path=str(tmp_path),
        retry=RetryPolicy(3, base_delay_s=0.001, jitter=0))
    inj = FaultInjector()
    inj.arm("checkpoint.write", kind="raise", times=1)
    with inj:
        ckpt.save({"w": 2.0}, 2)                       # 2nd attempt lands
    state, it = ckpt.maybe_load()
    assert it == 2 and state["w"] == 2.0


def test_torn_write_detected_and_skipped_back(tmp_path, comm):
    """A truncation that survives the atomic rename is caught by the
    checksum footer; maybe_load skips back to the newest intact
    iteration and counts the corruption."""
    c = get_registry().counter("checkpoint_corrupt_total", {"name": "torn"})
    before = c.value
    ckpt = create_multi_node_checkpointer("torn", comm, path=str(tmp_path))
    ckpt.save({"w": 1.0}, 1)
    ckpt.save({"w": 2.0}, 2)
    inj = FaultInjector()
    inj.arm("checkpoint.write", kind="torn_write", frac=0.5, times=1)
    with inj:
        ckpt.save({"w": 3.0}, 3)                       # silently truncated
    import os
    assert os.path.exists(ckpt.filename(3))            # rename DID run
    state, it = ckpt.maybe_load()
    assert it == 2 and state["w"] == 2.0               # skipped back
    assert c.value == before + 1
    evs = [e for e in get_event_log().tail(100)
           if e["kind"] == "checkpoint_corrupt"]
    assert evs and evs[-1]["iteration"] == 3


def test_legacy_footerless_snapshot_still_loads(tmp_path, comm):
    ckpt = create_multi_node_checkpointer("leg", comm, path=str(tmp_path))
    with open(ckpt.filename(5), "wb") as f:            # pre-hardening file
        pickle.dump({"world_size": 1, "state": {"w": 5.0}}, f, protocol=4)
    state, it = ckpt.maybe_load()
    assert it == 5 and state["w"] == 5.0


def test_resilient_fit_survives_torn_write_then_crash(tmp_path, comm):
    """Compose the two failure modes: iteration 8's snapshot is torn, the
    next step crashes — recovery must land on iteration 4 (the newest
    INTACT snapshot), then still finish bit-exact vs the reference."""
    ref_state, _, _ = _run(tmp_path / "ref", comm, 12, name="ref")

    inj = FaultInjector()
    inj.arm("checkpoint.write", kind="torn_write", frac=0.6, times=1,
            after=2)                                   # 3rd write = iter 8
    inj.arm("trainer.step", kind="raise", after=9, times=1)   # fails at i=9
    state, report, _ = _run(tmp_path / "t", comm, 12, name="t",
                            injector=inj)
    assert report["failures"] == 1 and report["restores"] == 1
    assert state["w"] == ref_state["w"]
    evs = [e for e in get_event_log().tail(200) if e["kind"] ==
           "trainer_restore"]
    assert evs and evs[-1]["iteration"] == 4           # skipped past torn 8


# --------------------------------------------------------------------- #
# dump idempotence (satellite bugfix)                                    #
# --------------------------------------------------------------------- #


def test_one_failure_one_dump():
    """Layered failure paths (trainer boundary -> watchdog -> excepthook)
    share the per-sink once-guard: a single failure episode produces
    exactly one flight-recorder dump; recovery re-arms it."""
    log = get_event_log()
    log.reset_dump_guard()
    log.emit("something")
    sink = io.StringIO()
    assert log.dump(file=sink, once="failure") > 0
    assert log.dump(file=sink, once="failure") == 0    # suppressed
    out = sink.getvalue()
    assert out.count("flight recorder: last") == 1
    assert "suppressing duplicate" in out
    log.reset_dump_guard()                             # episode over
    assert log.dump(file=sink, once="failure") > 0     # next failure dumps


def test_unguarded_dump_unaffected():
    log = get_event_log()
    log.emit("x")
    sink = io.StringIO()
    assert log.dump(file=sink) > 0
    assert log.dump(file=sink) > 0                     # no once key: always


# --------------------------------------------------------------------- #
# async checkpointing through the trainer (dataflow async hot loop)      #
# --------------------------------------------------------------------- #


def test_async_save_crash_resume_bit_exact(tmp_path, comm):
    """The tentpole guarantee: with background checkpointing on, a crash
    at step k restores from an async-written snapshot and the whole run
    stays float-for-float identical to the synchronous reference."""
    ref_state, ref_report, ref_traj = _run(
        tmp_path / "ref", comm, 12, name="aref")
    inj = FaultInjector()
    inj.arm("trainer.step", kind="raise", after=7, times=1)
    state, report, traj = _run(tmp_path / "async", comm, 12, name="async",
                               injector=inj, async_save=True)
    assert report["failures"] == 1 and report["restores"] == 1
    final = {}
    for i, w in traj:
        if i in final:
            assert w == final[i], f"replay of step {i} diverged"
        final[i] = w
    assert final == dict(ref_traj)
    assert state["w"] == ref_state["w"]
    np.testing.assert_array_equal(np.asarray(state["key"]),
                                  np.asarray(ref_state["key"]))


def test_async_save_cross_launch_resume(tmp_path, comm):
    """fit()'s closing wait_async makes the final async snapshot durable:
    a second launch resumes exactly at n_steps of the first."""
    ref_state, _, ref_traj = _run(tmp_path / "r", comm, 10, name="ax")
    _run(tmp_path / "s", comm, 6, name="ay", async_save=True)
    state, report, traj = _run(tmp_path / "s", comm, 10, name="ay",
                               async_save=True)
    assert report["resumed_from"] == 6
    assert dict(traj) == {i: w for i, w in ref_traj if i >= 6}
    assert state["w"] == ref_state["w"]


def test_async_save_requires_capable_checkpointer(tmp_path, comm):
    class NoAsync:
        pass

    with pytest.raises(TypeError, match="save_async"):
        ResilientTrainer(_step, NoAsync(), async_save=True)


def test_prefetched_iterator_crash_resume_bit_exact(tmp_path, comm):
    """resilient_fit driving a DevicePrefetcher-wrapped iterator: the
    snapshot's iterator state excludes prefetched-but-unstepped batches,
    so crash-resume (with async checkpointing on, both overlaps live)
    replays the IDENTICAL batch sequence and trajectory."""
    from chainermn_tpu.dataflow import DevicePrefetcher

    ref_state, _, ref_traj = _run(tmp_path / "ref", comm, 12, name="pref")

    ckpt = create_multi_node_checkpointer("pf", comm,
                                          path=str(tmp_path / "pf"))
    pre = DevicePrefetcher(_iterator(), depth=3, name="trainer_pf")
    traj = []
    inj = FaultInjector()
    inj.arm("trainer.step", kind="raise", after=7, times=1)
    with inj:
        state, report = resilient_fit(
            _step, _init_state(), pre, 12, ckpt, save_every=4,
            async_save=True,
            on_step=lambda i, s: traj.append((i, s["w"])))
    pre.close()
    assert report["failures"] == 1 and report["restores"] == 1
    final = {}
    for i, w in traj:
        if i in final:
            assert w == final[i], f"replay of step {i} diverged"
        final[i] = w
    assert final == dict(ref_traj)
    assert state["w"] == ref_state["w"]
