"""Objstore cut-points: injected transients at put/get, absorbed by the
client's RetryPolicy — over the real C++ sidecar and TCP."""

import pytest

from chainermn_tpu.resilience import FaultInjector, InjectedFault, RetryPolicy

objstore = pytest.importorskip("chainermn_tpu.native.objstore")

try:
    objstore._load()
    _HAVE_LIB = True
except Exception:
    _HAVE_LIB = False

pytestmark = pytest.mark.skipif(
    not _HAVE_LIB, reason="g++ toolchain unavailable; sidecar not built"
)


@pytest.fixture()
def server():
    with objstore.ObjStoreServer() as s:
        yield s


def test_injected_put_fault_escapes_without_retry(server):
    c = objstore.ObjStoreClient("127.0.0.1", server.port)
    inj = FaultInjector()
    inj.arm("objstore.put", kind="raise", times=1)
    with inj:
        with pytest.raises(InjectedFault):
            c.put("k", b"v")
        c.put("k", b"v")                   # fault exhausted: next put lands
    assert c.get("k") == b"v"
    c.close()


def test_retry_absorbs_put_and_get_transients(server):
    c = objstore.ObjStoreClient(
        "127.0.0.1", server.port,
        retry=RetryPolicy(3, base_delay_s=0.001, jitter=0))
    inj = FaultInjector()
    inj.arm("objstore.put", kind="raise", times=1)
    inj.arm("objstore.get", kind="raise", times=1)
    with inj:
        c.put("k2", b"payload")            # first attempt faults, retried
        assert c.get("k2") == b"payload"   # same on the read side
    assert sorted(inj.fired_log) == [("objstore.get", "raise"),
                                     ("objstore.put", "raise")]
    c.close()
