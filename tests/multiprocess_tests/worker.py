"""Subprocess body for the true multi-process (DCN-path) tests.

The reference exercises its object comm under ``mpiexec -n 2`` (SURVEY.md
S4); the TPU-rebuild analog is N processes joined through
``jax.distributed.initialize`` whose coordination KV store carries
``KVStoreObjectComm`` traffic. This worker runs the full host-side suite —
obj collectives, typed-pytree p2p, ack-GC key deletion, ``scatter_dataset``
with ``force_transport``, checkpointer agreement with a deliberately missing
snapshot, and the multi-node/synchronized iterators — and prints
``WORKER_OK <rank>`` only if every scenario passes.

Run via ``test_multiprocess.py`` (spawns the processes), not directly.
"""

import os
import sys

import numpy as np


class HostComm:
    """Minimal communicator facade over an object comm: exactly the surface
    ``scatter_dataset`` / checkpointer / iterators need (``rank``,
    ``inter_size``, ``*_obj``). A full ``MeshCommunicator`` would add device
    collectives; host-side subsystems must work without them."""

    def __init__(self, oc, rank, size):
        self._oc = oc
        self.rank = rank
        self.size = size
        self.inter_size = size
        self.intra_rank = 0

    def __getattr__(self, name):
        if name.endswith("_obj") or name == "barrier":
            return getattr(self._oc, name)
        raise AttributeError(name)


def check(cond, msg):
    if not cond:
        raise AssertionError(msg)


def scenario_collectives(oc, rank, size):
    # bcast: nested mixed payload
    payload = {"a": np.arange(6, dtype=np.float32), "b": ("x", [1, 2, 3])}
    got = oc.bcast_obj(payload if rank == 0 else None, root=0)
    check(np.array_equal(got["a"], np.arange(6, dtype=np.float32)), "bcast a")
    check(got["b"] == ("x", [1, 2, 3]), "bcast b")

    # gather at a non-zero root (roots can rotate)
    g = oc.gather_obj(rank * 10, root=size - 1)
    if rank == size - 1:
        check(g == [r * 10 for r in range(size)], f"gather: {g}")
    else:
        check(g is None, "gather non-root must get None")

    # scatter
    objs = [f"shard-{r}" for r in range(size)] if rank == 0 else None
    got = oc.scatter_obj(objs, root=0)
    check(got == f"shard-{rank}", f"scatter: {got}")

    # allgather + allreduce
    ag = oc.allgather_obj({"r": rank})
    check([d["r"] for d in ag] == list(range(size)), f"allgather: {ag}")
    s = oc.allreduce_obj(rank + 1)
    check(s == sum(range(1, size + 1)), f"allreduce sum: {s}")

    oc.barrier()


def scenario_p2p(oc, rank, size):
    # typed pytree both directions between 0 and 1 (the _MessageType parity
    # payload: nested tuple of mixed-dtype ndarrays)
    tree = (
        np.arange(4, dtype=np.int32),
        {"f": np.ones((2, 3), np.float16), "s": "tag"},
        [np.float64(2.5)],
    )
    if rank == 0:
        oc.send_obj(tree, dest=1, tag=7)
        back = oc.recv_obj(source=1, tag=8)
        check(np.array_equal(back[0], np.arange(4, dtype=np.int32) * 2), "p2p back")
    elif rank == 1:
        got = oc.recv_obj(source=0, tag=7)
        check(np.array_equal(got[0], np.arange(4, dtype=np.int32)), "p2p fwd int32")
        check(got[1]["f"].dtype == np.float16 and got[1]["s"] == "tag", "p2p fwd f16")
        oc.send_obj((got[0] * 2,), dest=0, tag=8)
    oc.barrier()


def scenario_array_p2p(comm2, rank, size):
    """The ARRAY p2p API (MeshCommunicator.send/recv, typed _MessageType
    header + raw buffers) across real processes — distinct from the obj
    pickle path in scenario_p2p (VERDICT r2 #5)."""
    import jax.numpy as jnp

    tree = {
        "i": np.arange(5, dtype=np.int32) + rank,
        "pair": (np.full((2, 2), 1.5, np.float32),
                 jnp.full((3,), 0.25, jnp.bfloat16)),
    }
    if rank == 0:
        comm2.send(tree, dest=1, tag=11)
        back = comm2.recv(source=1, tag=12)
        check(np.array_equal(np.asarray(back["i"]),
                             np.arange(5, dtype=np.int32) * 3),
              "array p2p round trip values")
        check(back["pair"][1].dtype == jnp.bfloat16, "array p2p bf16 dtype")
    elif rank == 1:
        got = comm2.recv(source=0, tag=11)
        check(np.asarray(got["i"]).dtype == np.int32, "array p2p int32")
        check(got["pair"][1].dtype == jnp.bfloat16, "array p2p bf16 fwd")
        reply = {
            "i": np.asarray(got["i"]) * 3,
            "pair": (np.asarray(got["pair"][0]),
                     jnp.asarray(got["pair"][1])),
        }
        comm2.send(reply, dest=0, tag=12)
    comm2._obj.barrier()
    # protocol mismatch: comm.recv on send_obj traffic must fail loudly,
    # not reinterpret the pickle as a header
    if rank == 0:
        comm2.send_obj("plain-object", dest=1, tag=13)
    elif rank == 1:
        try:
            comm2.recv(source=0, tag=13)
            check(False, "recv accepted send_obj traffic")
        except RuntimeError as e:
            check("_MessageType" in str(e), f"wrong mismatch error: {e}")
    comm2._obj.barrier()


def scenario_eager_device_collective(comm2, rank, size):
    """An eager ARRAY collective across processes: the global mesh spans
    all processes' devices, each passes the rank-major input, and the jitted
    shard_map program runs the real cross-process (DCN-path) collective."""
    x = np.stack([np.full((4,), float(r + 1), np.float32) for r in range(size)])
    out = comm2.allreduce(x, "sum")
    local = np.asarray(out.addressable_data(0))
    want = sum(range(1, size + 1))
    check(np.allclose(local, want), f"eager cross-process allreduce: {local}")
    # second call with the same signature: the CACHED path must work too
    out2 = comm2.allreduce(x * 2.0, "sum")
    local2 = np.asarray(out2.addressable_data(0))
    check(np.allclose(local2, 2.0 * want), f"cached eager allreduce: {local2}")
    # mean via the gradient path (strategy collective)
    grads = {"w": x * 2.0}
    mean = comm2.multi_node_mean_grad(grads)
    local_m = np.asarray(mean["w"].addressable_data(0))
    check(np.allclose(local_m, (size + 1.0)), f"mean_grad: {local_m}")
    comm2._obj.barrier()


def _list_keys(oc, prefix):
    """Transport-agnostic key listing (KV store vs native sidecar)."""
    if hasattr(oc, "_store"):
        return oc._store.list_prefix(prefix)
    return oc._client.key_value_dir_get(prefix)


def scenario_ack_gc(oc, rank, size):
    # Round keys must actually get deleted once every reader acked. GC is
    # lazy: round k's keys die when the writer's NEXT use of the op runs
    # _gc_pending and sees all acks. Barriers make ack arrival deterministic.
    import re

    uid = oc._uid
    prefix = f"chainermn_tpu/obj/{uid}/bcast/"
    oc.bcast_obj("round0" if rank == 0 else None, root=0)
    oc.barrier()  # all readers have acked round 0
    oc.bcast_obj("round1" if rank == 0 else None, root=0)  # root GCs round 0
    oc.barrier()
    if rank == 0:
        keys = _list_keys(oc, prefix)
        left = [k for k in keys if re.search(r"/bcast/0/", str(k))]
        check(not left, f"ack-GC left round-0 keys: {left}")
    oc.barrier()


def scenario_scatter_dataset(comm, rank, size):
    from chainermn_tpu.datasets import scatter_dataset

    data = [(i, f"rec{i}") for i in range(23)]  # only root "can read" it
    shard = scatter_dataset(
        data if rank == 0 else None, comm, shuffle=True, seed=5,
        force_transport=True,
    )
    local = list(shard)
    counts = comm._oc.allgather_obj([rec[0] for rec in local])
    flat = sorted(i for sub in counts for i in sub)
    check(flat == list(range(23)), f"scatter_dataset not a partition: {flat}")
    lo, hi = 23 // size, -(-23 // size)
    check(all(lo <= len(s) <= hi for s in counts),
          f"unbalanced: {[len(s) for s in counts]}")


def scenario_checkpointer(comm, rank, size, tmpdir):
    from chainermn_tpu.extensions import create_multi_node_checkpointer

    cp = create_multi_node_checkpointer("mp", comm, path=tmpdir)
    state = {"w": np.full((3,), float(rank)), "it": 0}
    cp.save(state, iteration=1)
    cp.save({**state, "it": 2}, iteration=2)
    comm._oc.barrier()
    if rank == 1:  # rank 1 "lost" its newest snapshot
        os.remove(cp.filename(2))
    comm._oc.barrier()
    loaded, it = cp.maybe_load()
    check(it == 1, f"agreement must fall back to newest COMMON iteration, got {it}")
    check(float(loaded["w"][0]) == float(rank), "checkpoint rank-local state")
    comm._oc.barrier()
    cp.finalize()


def scenario_iterators(comm, rank, size):
    from chainermn_tpu.iterators import (
        SerialIterator,
        create_multi_node_iterator,
        create_synchronized_iterator,
    )

    data = list(range(10))
    base = SerialIterator(data, batch_size=3, repeat=False, shuffle=False) \
        if rank == 0 else None
    it = create_multi_node_iterator(base, comm, rank_master=0)
    batches = []
    try:
        while True:
            batches.append(next(it))
    except StopIteration:
        pass
    all_b = comm._oc.allgather_obj(batches)
    check(all(b == all_b[0] for b in all_b), f"multi-node iterator diverged: {all_b}")
    check(sum(len(b) for b in all_b[0]) == 10, "iterator lost records")

    sync = SerialIterator(data, batch_size=5, shuffle=True)
    sync = create_synchronized_iterator(sync, comm)
    first = next(sync)
    orders = comm._oc.allgather_obj(first)
    check(all(o == orders[0] for o in orders), f"synchronized iterator diverged: {orders}")


def main():
    rank = int(os.environ["MP_TEST_RANK"])
    size = int(os.environ["MP_TEST_SIZE"])
    port = os.environ["MP_TEST_PORT"]
    tmpdir = os.environ["MP_TEST_TMPDIR"]

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=size,
        process_id=rank,
    )
    check(jax.process_index() == rank, "process_index mismatch")
    check(jax.process_count() == size, "process_count mismatch")

    from chainermn_tpu.communicators._object_comm import (
        KVStoreObjectComm,
        create_object_comm,
    )

    transport = os.environ.get("MP_TEST_TRANSPORT", "kv")
    oc = create_object_comm()
    if transport == "native":
        check(type(oc).__name__ == "NativeObjectComm",
              f"expected native transport, got {type(oc)}")
    else:
        check(type(oc) is KVStoreObjectComm,
              f"expected KV transport, got {type(oc)}")
    comm = HostComm(oc, rank, size)

    scenario_collectives(oc, rank, size)
    scenario_p2p(oc, rank, size)

    # Real MeshCommunicator for the typed ARRAY p2p path (its send/recv ride
    # the same object transport but speak the _MessageType protocol).
    import chainermn_tpu

    comm_mesh = chainermn_tpu.create_communicator("naive")
    scenario_array_p2p(comm_mesh, rank, size)
    scenario_eager_device_collective(comm_mesh, rank, size)

    scenario_ack_gc(oc, rank, size)
    scenario_scatter_dataset(comm, rank, size)
    scenario_checkpointer(comm, rank, size, tmpdir)
    scenario_iterators(comm, rank, size)

    print(f"WORKER_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
