"""Crash/resume fault injection under real processes (SURVEY.md S5
"failure detection / elastic recovery": fail-fast + fail-and-restart).

Launch 1 trains with per-step snapshots and rank 1 dies mid-run with
``os._exit(1)`` — no cleanup, no distributed shutdown. Launch 2 is a fresh
world (new coordinator) over the same snapshot directory: the multi-node
checkpointer must agree on the newest COMMON iteration (discarding the
orphan snapshot rank 0 wrote after the crash), resume, and reach exactly
the state of an uninterrupted run. The reference exercises recovery by
deleting a snapshot file in-process; this drives the real thing — an
abrupt process death and a cross-launch resume."""

import os
import socket
import subprocess
import sys

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "worker_resume.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(phase: str, tmpdir: str, size: int = 2, timeout: float = 240.0):
    port = _free_port()
    # Strip XLA_FLAGS (the conftest's 8-device forcing is for THIS process)
    # and CHAINERMN_TPU_OBJSTORE (an ambient native-sidecar address from an
    # earlier test must not redirect these KV-transport workers) — same
    # reasoning as test_multiprocess._launch_world.
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "CHAINERMN_TPU_OBJSTORE")}
    procs = []
    for r in range(size):
        env = dict(
            env_base,
            MP_TEST_RANK=str(r),
            MP_TEST_SIZE=str(size),
            MP_TEST_PORT=str(port),
            MP_TEST_TMPDIR=tmpdir,
            MP_TEST_PHASE=phase,
            PYTHONPATH=_REPO + os.pathsep + env_base.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_crash_then_resume(tmp_path):
    tmpdir = str(tmp_path)

    procs, outs = _launch("crash", tmpdir)
    assert procs[0].returncode == 0, f"rank 0:\n{outs[0][-4000:]}"
    assert "WORKER_CRASH_PHASE_OK 0" in outs[0], outs[0][-4000:]
    # the injected fault: rank 1 must have died abruptly with rc=1
    assert procs[1].returncode == 1, (
        f"rank 1 should have crashed (rc={procs[1].returncode}):\n"
        f"{outs[1][-4000:]}")

    procs, outs = _launch("resume", tmpdir)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"resume rank {r} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert f"WORKER_OK {r}" in out, f"resume rank {r}:\n{out[-4000:]}"
