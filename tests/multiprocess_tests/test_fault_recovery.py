"""Crash/resume fault injection under real processes (SURVEY.md S5
"failure detection / elastic recovery": fail-fast + fail-and-restart).

Launch 1 trains with per-step snapshots and rank 1 dies mid-run with
``os._exit(1)`` — no cleanup, no distributed shutdown. Launch 2 is a fresh
world (new coordinator) over the same snapshot directory: the multi-node
checkpointer must agree on the newest COMMON iteration (discarding the
orphan snapshot rank 0 wrote after the crash), resume, and reach exactly
the state of an uninterrupted run. The reference exercises recovery by
deleting a snapshot file in-process; this drives the real thing — an
abrupt process death and a cross-launch resume."""

import os

import jax
import pytest

from .test_multiprocess import _launch_world

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "worker_resume.py")


_requires_cpu_multiprocess = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="legacy jaxlib: 'Multiprocess computations aren't implemented "
    "on the CPU backend' — the emulated multi-controller harness needs a "
    "newer runtime",
)


def _launch(phase: str, tmpdir: str, size: int = 2, timeout: float = 240.0):
    return _launch_world(size, tmpdir, timeout=timeout, worker=_WORKER,
                         extra_env={"MP_TEST_PHASE": phase})


@_requires_cpu_multiprocess
def test_crash_then_resume(tmp_path):
    tmpdir = str(tmp_path)

    procs, outs = _launch("crash", tmpdir)
    assert procs[0].returncode == 0, f"rank 0:\n{outs[0][-4000:]}"
    assert "WORKER_CRASH_PHASE_OK 0" in outs[0], outs[0][-4000:]
    # the injected fault: rank 1 must have died abruptly with rc=1
    assert procs[1].returncode == 1, (
        f"rank 1 should have crashed (rc={procs[1].returncode}):\n"
        f"{outs[1][-4000:]}")

    procs, outs = _launch("resume", tmpdir)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"resume rank {r} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert f"WORKER_OK {r}" in out, f"resume rank {r}:\n{out[-4000:]}"
