"""Multi-controller traced training: 2 real processes x 4 CPU devices each
run the jitted DP / FSDP / GSPMD-LM train steps over ONE global mesh and
must reproduce the single-process 8-device losses exactly (VERDICT r4
missing #3 — the evidence the parallelism layer survives the real pod
process model: global-mesh jit, per-host data feeding, and device_put /
megatron_shard / fsdp_shard placement onto a mesh spanning processes)."""

import json
import os
import socket
import subprocess
import sys

import jax
import pytest

import chainermn_tpu

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "worker_traced.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


_requires_cpu_multiprocess = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="legacy jaxlib: 'Multiprocess computations aren't implemented "
    "on the CPU backend' — the emulated multi-controller harness needs a "
    "newer runtime",
)


def _free_port() -> int:
    # bind-close-reuse has an inherent race (another process can claim the
    # port in the gap); if it ever fires, the failure surfaces with full
    # worker logs via the TimeoutExpired path below
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@_requires_cpu_multiprocess
def test_multicontroller_traced_training(tmp_path):
    from tests.multiprocess_tests import worker_traced

    # 1. expected losses from THIS process's single-process 8-device mesh
    #    (the conftest world every other parallelism test runs in)
    comm = chainermn_tpu.create_communicator("tpu")
    assert comm.size == 8 and comm.process_size == 1
    expected = worker_traced.run_scenarios(comm)
    expected_path = tmp_path / "expected.json"
    expected_path.write_text(json.dumps(expected))

    # 2. the same scenarios on a 2-process x 4-device global mesh
    size, n_local = 2, 4
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs, logs = [], []
    for r in range(size):
        env = dict(
            env_base,
            XLA_FLAGS=f"--xla_force_host_platform_device_count={n_local}",
            MP_TEST_RANK=str(r),
            MP_TEST_SIZE=str(size),
            MP_TEST_PORT=str(port),
            MP_TEST_LOCAL_DEVICES=str(n_local),
            MP_TEST_EXPECTED=str(expected_path),
            PYTHONPATH=_REPO + os.pathsep + env_base.get("PYTHONPATH", ""),
        )
        # stdout to FILES, not pipes: the workers synchronize through
        # collectives, so a sequential communicate() on pipe-captured
        # output can deadlock if the not-yet-read worker fills its 64KB
        # pipe mid-collective
        log = open(tmp_path / f"worker{r}.log", "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env,
            stdout=log, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p, log in zip(procs, logs):
            try:
                p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                # a hung worker is the canonical multi-controller failure:
                # fail with every rank's log tail, not a bare timeout
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                tails = []
                for r, lg in enumerate(logs):
                    lg.seek(0)
                    tails.append(f"--- rank {r} log tail ---\n"
                                 f"{lg.read()[-2000:]}")
                raise AssertionError(
                    "worker hung (600s); logs:\n" + "\n".join(tails))
            log.seek(0)
            outs.append(log.read())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert f"TRACED_OK {r}" in out, (
            f"rank {r} did not finish:\n{out[-4000:]}")
