"""Multi-CONTROLLER traced training (VERDICT r4 missing #3).

The multiprocess suite (worker.py) proves the host-side/object paths and
eager device collectives across real processes; THIS worker proves the
traced training steps under the real pod process model: a global mesh built
from 2 processes x 4 local CPU devices, per-host data feeding via
``jax.make_array_from_callback``, ``device_put`` placement onto a mesh
spanning processes (``bcast_data``, ``fsdp_shard``, ``megatron_shard``),
and multi-step jitted DP / FSDP / GSPMD-LM training whose losses must equal
the single-process 8-device run bit-for-tolerance.

``run_scenarios(comm)`` is importable and runs in BOTH worlds: the pytest
process (single-process, 8 virtual devices via conftest) computes the
expected losses; each worker process recomputes them on the 2x4 global mesh
and compares against the expected file. Identical losses = the parallelism
layer is layout-invariant across the process model, not just across mesh
shapes.

Run via test_multicontroller.py, not directly.
"""

import json
import os
import sys

N_STEPS = 3
GLOBAL_BATCH = 32


def _global_array(comm, np_value):
    """Per-host data feeding: every process holds the same deterministic
    global numpy batch; each contributes only its addressable shards."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(comm.mesh, comm.data_spec)
    return jax.make_array_from_callback(
        np_value.shape, sharding, lambda idx: np_value[idx])


def _mlp():
    import flax.linen as nn
    import jax.numpy as jnp

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            h = nn.relu(nn.Dense(32, dtype=jnp.float32)(x))
            return nn.Dense(10, dtype=jnp.float32)(h)

    return MLP()


def _class_data():
    import numpy as np

    rs = np.random.RandomState(0)
    x = rs.randn(GLOBAL_BATCH, 8).astype("float32")
    y = (np.arange(GLOBAL_BATCH) % 10).astype("int32")
    return x, y


def scenario_dp(comm):
    """Replicated-params DP through jit_train_step (multi-node optimizer,
    shard_map pmean)."""
    import jax
    import optax

    import chainermn_tpu
    from chainermn_tpu.training import jit_train_step

    model = _mlp()
    x_np, y_np = _class_data()
    variables = comm.bcast_data(model.init(jax.random.PRNGKey(0), x_np[:2]))
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.05), comm)
    opt_state = jax.device_put(opt.init(variables["params"]),
                               comm.named_sharding())
    step = jit_train_step(model, opt, comm)
    x, y = _global_array(comm, x_np), _global_array(comm, y_np)
    losses = []
    for _ in range(N_STEPS):
        variables, opt_state, loss = step(variables, opt_state, x, y)
        losses.append(float(loss))
    return losses


def scenario_fsdp(comm):
    """ZeRO-3 layout: params/opt-state scattered at rest via fsdp_shard
    (device_put onto the process-spanning mesh), one global jitted step."""
    import jax
    import optax

    from chainermn_tpu.parallel import fsdp_shard, jit_fsdp_train_step

    model = _mlp()
    x_np, y_np = _class_data()
    variables = fsdp_shard(model.init(jax.random.PRNGKey(0), x_np[:2]), comm)
    opt = optax.sgd(0.05)
    opt_state = fsdp_shard(jax.jit(opt.init)(variables["params"]), comm)
    step = jit_fsdp_train_step(model, opt, comm)
    x, y = _global_array(comm, x_np), _global_array(comm, y_np)
    losses = []
    for _ in range(N_STEPS):
        variables, opt_state, loss = step(variables, opt_state, x, y)
        losses.append(float(loss))
    return losses


def scenario_gspmd_lm(comm):
    """Megatron weights-at-rest LM: megatron_shard / megatron_opt_shard
    placement across processes, plain-jit partitioner-inserted collectives."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.parallel import (
        gspmd_lm_train_step,
        megatron_opt_shard,
        megatron_shard,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_heads=8, n_layers=2,
                          max_len=32, compute_dtype=jnp.float32)
    rs = np.random.RandomState(1)
    tok_np = rs.randint(0, 32, (8, 16)).astype("int32")
    tgt_np = np.roll(tok_np, -1, 1)
    params = megatron_shard(
        model.init(jax.random.PRNGKey(1), jnp.asarray(tok_np[:1])), comm)
    opt = optax.adam(1e-2)
    state = megatron_opt_shard(opt, jax.jit(opt.init)(params), params, comm)
    step = gspmd_lm_train_step(model, opt, comm, donate=False)
    # LM data is replicated here (pure TP layout): same array everywhere
    tok = jax.device_put(tok_np, comm.named_sharding())
    tgt = jax.device_put(tgt_np, comm.named_sharding())
    losses = []
    for _ in range(N_STEPS):
        params, state, loss, _ = step(params, state, tok, tgt)
        losses.append(float(loss))
    return losses


def run_scenarios(comm) -> dict:
    return {
        "dp": scenario_dp(comm),
        "fsdp": scenario_fsdp(comm),
        "gspmd_lm": scenario_gspmd_lm(comm),
    }


def main():
    rank = int(os.environ["MP_TEST_RANK"])
    size = int(os.environ["MP_TEST_SIZE"])
    port = os.environ["MP_TEST_PORT"]
    expected_path = os.environ["MP_TEST_EXPECTED"]

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=size,
        process_id=rank,
    )
    n_local = int(os.environ["MP_TEST_LOCAL_DEVICES"])
    assert jax.local_device_count() == n_local, jax.local_device_count()
    assert jax.device_count() == size * n_local, jax.device_count()

    import chainermn_tpu

    comm = chainermn_tpu.create_communicator("tpu")
    assert comm.size == size * n_local
    assert comm.process_size == size

    got = run_scenarios(comm)
    with open(expected_path) as f:
        expected = json.load(f)
    for name, exp in expected.items():
        g = got[name]
        for i, (a, b) in enumerate(zip(g, exp)):
            if abs(a - b) > 1e-5 * max(1.0, abs(b)):
                raise AssertionError(
                    f"{name} step {i}: multi-controller loss {a!r} != "
                    f"single-process loss {b!r}")
    print(f"TRACED_OK {rank} {json.dumps(got)}", flush=True)


if __name__ == "__main__":
    main()
