"""True multi-process tests: the DCN/object-comm path under real
``jax.distributed`` processes (SURVEY.md S4 test-contract item (b) — the
analog of the reference's ``mpiexec -n 2 pytest`` runs).

Spawns N fresh Python processes (the in-process conftest already owns the
jax runtime, so workers must be subprocesses), joins them through a local
coordinator, and runs ``worker.py``'s scenario suite over the
coordination-service KV store.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_requires_cpu_multiprocess = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="legacy jaxlib: 'Multiprocess computations aren't implemented "
    "on the CPU backend' — the emulated multi-controller harness needs a "
    "newer runtime",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_world(size: int, tmpdir: str, timeout: float = 240.0,
                  transport: str = "kv", worker: str = None,
                  extra_env: dict = None):
    worker = worker or _WORKER
    port = _free_port()
    env_base = {
        k: v for k, v in os.environ.items()
        # XLA_FLAGS: the conftest's forced 8-device flag is for THIS process;
        # workers stay at 1 CPU device each so the geometry is process-shaped.
        # CHAINERMN_TPU_OBJSTORE: the transport param controls it below — an
        # ambient native-sidecar address must not redirect the KV runs.
        if k not in ("XLA_FLAGS", "CHAINERMN_TPU_OBJSTORE")
    }
    server = None
    if transport == "native":
        # The test process hosts the C++ sidecar (the "process 0's launcher
        # runs serve()" deployment contract); workers connect over TCP.
        from chainermn_tpu.native import objstore

        server = objstore.ObjStoreServer()
        env_base["CHAINERMN_TPU_OBJSTORE"] = f"127.0.0.1:{server.port}"
    procs = []
    try:
        for r in range(size):
            env = dict(
                env_base,
                MP_TEST_RANK=str(r),
                MP_TEST_SIZE=str(size),
                MP_TEST_PORT=str(port),
                MP_TEST_TMPDIR=tmpdir,
                MP_TEST_TRANSPORT=transport,
                PYTHONPATH=_REPO + os.pathsep + env_base.get("PYTHONPATH", ""),
            )
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, worker],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            ))
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    finally:
        if server is not None:
            server.stop()
    return procs, outs


@pytest.mark.parametrize("size", [2, 4])
@_requires_cpu_multiprocess
def test_multiprocess_suite(size, tmp_path):
    procs, outs = _launch_world(size, str(tmp_path))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} failed (rc={p.returncode}):\n{out[-4000:]}"
        )
        assert f"WORKER_OK {r}" in out, f"rank {r} did not finish:\n{out[-4000:]}"


@_requires_cpu_multiprocess
def test_multiprocess_suite_native_transport(tmp_path):
    """The FULL worker scenario suite again, but over the C++ objstore
    sidecar instead of the KV store — NativeObjectComm under a real
    multi-process launch (VERDICT r2 #6)."""
    pytest.importorskip("chainermn_tpu.native.objstore")
    from chainermn_tpu.native import objstore

    if not objstore_builds():
        pytest.skip("objstore sidecar cannot build here")
    procs, outs = _launch_world(2, str(tmp_path), transport="native")
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {r} failed (rc={p.returncode}):\n{out[-4000:]}"
        )
        assert f"WORKER_OK {r}" in out, f"rank {r} did not finish:\n{out[-4000:]}"


def objstore_builds() -> bool:
    from chainermn_tpu.native import objstore

    try:
        objstore._load()
        return True
    except Exception:
        return False
