"""Subprocess body for the crash/resume fault-injection test.

The reference's resilience story is fail-fast + fail-and-restart recovery
(global except hook + multi-node checkpointer, SURVEY.md S5 "failure
detection / elastic recovery"): a lost rank kills the job, the relaunch
resumes from the newest snapshot every rank HAS. This worker drives that
story end to end under real processes:

  phase=crash : train a deterministic quadratic under eager device
      collectives, checkpointing every step; after step CRASH_AT rank 1
      dies with ``os._exit(1)`` — no finalize, no distributed shutdown,
      the genuine article — while rank 0 saves one iteration it is
      "ahead" by (as if it noticed the peer's death later) and exits.
  phase=resume : a FRESH world (new coordinator) over the same snapshot
      dir; ``maybe_load`` must agree on the newest COMMON iteration
      (CRASH_AT, not rank 0's orphan), then training continues to
      N_STEPS and the final weights must equal an uninterrupted run —
      computed in-process, closed form, no tolerance games.

Run via ``test_fault_recovery.py``, not directly.
"""

import os
import sys

import numpy as np

N_STEPS = 6
CRASH_AT = 3
LR = 0.1


def targets_for(rank: int) -> np.ndarray:
    return np.full((4,), float(rank + 1))


def reference_weights(size: int, n_steps: int) -> np.ndarray:
    """Uninterrupted training, computed locally: w <- w - lr * mean_r
    2*(w - target_r)."""
    w = np.ones((4,))
    mean_target = np.mean([targets_for(r) for r in range(size)], axis=0)
    for _ in range(n_steps):
        w = w - LR * 2.0 * (w - mean_target)
    return w


def check(cond, msg):
    if not cond:
        raise AssertionError(msg)


def train_step(comm, w: np.ndarray, size: int) -> np.ndarray:
    # Eager collectives take rank-major global arrays (every process passes
    # the same [size, ...] host array; row r is rank r's contribution —
    # the documented contract, see MeshCommunicator._eager). w is identical
    # on every rank after each allreduce, so each process can build the
    # full stack.
    grads = np.stack([2.0 * (w - targets_for(r)) for r in range(size)])
    mean_grad = comm.allreduce(grads.astype(np.float32), "mean")
    local = np.asarray(mean_grad.addressable_data(0))[0]
    return w - LR * local


def main():
    rank = int(os.environ["MP_TEST_RANK"])
    size = int(os.environ["MP_TEST_SIZE"])
    port = os.environ["MP_TEST_PORT"]
    tmpdir = os.environ["MP_TEST_TMPDIR"]
    phase = os.environ["MP_TEST_PHASE"]

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=size,
        process_id=rank,
    )

    import chainermn_tpu
    from chainermn_tpu.extensions import create_multi_node_checkpointer

    comm = chainermn_tpu.create_communicator("naive")
    cp = create_multi_node_checkpointer("resume", comm, path=tmpdir)

    if phase == "crash":
        w, start = np.ones((4,)), 0
    else:
        loaded, it = cp.maybe_load()
        check(it == CRASH_AT,
              f"agreement must resume at the newest COMMON iteration "
              f"{CRASH_AT} (rank 0's orphan save must lose), got {it}")
        w, start = loaded["w"], int(loaded["step"])
        check(start == CRASH_AT, f"stale step in snapshot: {start}")

    for step in range(start, N_STEPS):
        w = train_step(comm, w, size)
        cp.save({"w": w, "step": step + 1}, iteration=step + 1)
        comm.barrier()
        if phase == "crash" and step + 1 == CRASH_AT:
            if rank == 1:
                os._exit(1)  # the fault: no cleanup, no shutdown
            # rank 0 "got ahead" before noticing the peer died: an orphan
            # snapshot the resume agreement must discard
            cp.save({"w": w, "step": step + 1}, iteration=CRASH_AT + 1)
            print(f"WORKER_CRASH_PHASE_OK {rank}", flush=True)
            # skip jax.distributed's atexit shutdown barrier: the peer is
            # dead, the barrier can only time out (observed: ~90s stall,
            # then a heartbeat-timeout error flips the exit code)
            os._exit(0)

    ref = reference_weights(size, N_STEPS)
    check(np.allclose(w, ref, atol=1e-5),  # grads ride float32 on device
          f"resumed training diverged from uninterrupted run: {w} vs {ref}")
    cp.finalize()
    print(f"WORKER_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
