"""Iterator tests (reference iterators_tests — SURVEY.md S2.13)."""

import numpy as np
import pytest

from chainermn_tpu import create_communicator
from chainermn_tpu.iterators import (
    SerialIterator,
    create_multi_node_iterator,
    create_synchronized_iterator,
)


@pytest.fixture(scope="module")
def comm():
    return create_communicator("naive")


class TestSerialIterator:
    def test_epoch_sequential(self):
        it = SerialIterator(list(range(10)), batch_size=3)
        batches = [next(it) for _ in range(4)]
        assert batches[0] == [0, 1, 2]
        assert batches[3] == [9]  # final short batch flushes the epoch
        assert it.epoch == 1 and it.is_new_epoch
        assert next(it) == [0, 1, 2]  # repeat=True rolls over
        assert not it.is_new_epoch

    def test_no_repeat_stops(self):
        it = SerialIterator(list(range(4)), batch_size=2, repeat=False)
        assert next(it) == [0, 1]
        assert next(it) == [2, 3]
        with pytest.raises(StopIteration):
            next(it)

    def test_shuffle_covers_epoch(self):
        it = SerialIterator(list(range(12)), batch_size=5, shuffle=True, seed=0)
        seen = []
        while not it.is_new_epoch:
            seen.extend(next(it))
        assert sorted(seen) == list(range(12))
        assert seen != list(range(12))  # actually shuffled (seed-dependent)

    def test_epoch_detail(self):
        it = SerialIterator(list(range(8)), batch_size=4)
        next(it)
        assert it.epoch_detail == pytest.approx(0.5)
        next(it)
        assert it.epoch_detail == pytest.approx(1.0)

    def test_state_roundtrip(self):
        it = SerialIterator(list(range(10)), batch_size=3, shuffle=True, seed=1)
        next(it)
        state = it.state_dict()
        a = [next(it) for _ in range(3)]
        it2 = SerialIterator(list(range(10)), batch_size=3, shuffle=True, seed=1)
        it2.load_state_dict(state)
        b = [next(it2) for _ in range(3)]
        assert a == b
        assert it.epoch == it2.epoch


class TestMultiNodeIterator:
    def test_master_path(self, comm):
        base = SerialIterator(list(range(6)), batch_size=2)
        it = create_multi_node_iterator(base, comm)
        assert next(it) == [0, 1]
        assert next(it) == [2, 3]
        assert it.epoch_detail == pytest.approx(4 / 6)

    def test_master_stop_propagates(self, comm):
        base = SerialIterator(list(range(2)), batch_size=2, repeat=False)
        it = create_multi_node_iterator(base, comm)
        assert next(it) == [0, 1]
        with pytest.raises(StopIteration):
            next(it)

    def test_master_requires_iterator(self, comm):
        with pytest.raises(ValueError):
            create_multi_node_iterator(None, comm)


class TestSynchronizedIterator:
    def test_reseeds_in_place(self, comm):
        a = SerialIterator(list(range(20)), batch_size=5, shuffle=True, seed=3)
        b = SerialIterator(list(range(20)), batch_size=5, shuffle=True, seed=99)
        sa = create_synchronized_iterator(a, comm, seed=1234)
        sb = create_synchronized_iterator(b, comm, seed=1234)
        # single-process: both got root's broadcast seed -> identical draws
        assert a._seed == b._seed
        assert next(sa) == next(sb)

    def test_rejects_unseedable(self, comm):
        with pytest.raises(TypeError):
            create_synchronized_iterator(iter([1, 2]), comm)
