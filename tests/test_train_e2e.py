"""The minimum end-to-end slice (SURVEY.md S7 step 2).

Data-parallel training of the MNIST-shaped MLP across the 8-device mesh with
the full reference workflow: scatter_dataset -> bcast_data ->
create_multi_node_optimizer inside a jitted shard_map step ->
create_multi_node_evaluator. Asserts learning happens and replicas agree —
the TPU analog of the reference CI's `mpiexec -n 2 train_mnist.py` smoke run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.models import MLP


def _synthetic_mnist(n=512, d=64, n_classes=10, seed=0):
    """Linearly-separable-ish synthetic data (fast, deterministic)."""
    rng = np.random.RandomState(seed)
    w = rng.randn(d, n_classes)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, n_classes), axis=1).astype(np.int32)
    return x, y


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("tpu", allreduce_grad_dtype="bfloat16")


def test_data_parallel_training_e2e(comm):
    n_dev = comm.size
    x, y = _synthetic_mnist()
    dataset = list(zip(x, y))

    # shard across the mesh (device-space sharding via override; process-space
    # sharding is the multi-host path)
    shards = [
        chainermn_tpu.scatter_dataset(dataset, comm, shuffle=True, seed=0,
                                      n_shards=n_dev, shard_id=i)
        for i in range(n_dev)
    ]
    per_shard = min(len(s) for s in shards)

    model = MLP(n_units=32, n_out=10, compute_dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, x.shape[1])))
    params = comm.bcast_data(params)

    opt = chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-2), comm)
    opt_state = jax.device_put(opt.init(params), comm.named_sharding())

    def loss_fn(p, xb, yb):
        logits = model.apply(p, xb)
        local = optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()
        # hand-written steps define the GLOBAL objective; the auto-psum'd
        # backward then yields the exact global gradient (invariant), which
        # multi_node_mean_grad passes through untouched
        return comm.allreduce(local, "mean")

    def train_step(p, s, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        updates, s = opt.update(grads, s, p)
        p = optax.apply_updates(p, updates)
        return p, s, loss[None]

    step = jax.jit(
        comm.shard_map(
            train_step,
            in_specs=(P(), P(), P(comm.axis_name), P(comm.axis_name)),
            out_specs=(P(), P(), P(comm.axis_name)),
        )
    )

    # rank-major batches: [n_dev * b, ...] with each device's block contiguous
    b = 16
    losses = []
    for it in range(30):
        xb = np.stack([
            np.stack([shards[r][(it * b + j) % per_shard][0] for j in range(b)])
            for r in range(n_dev)
        ]).reshape(n_dev * b, -1)
        yb = np.stack([
            np.stack([shards[r][(it * b + j) % per_shard][1] for j in range(b)])
            for r in range(n_dev)
        ]).reshape(n_dev * b)
        params, opt_state, loss = step(params, opt_state, xb, yb)
        losses.append(float(np.asarray(loss)[0]))

    assert losses[-1] < losses[0] * 0.7, f"no learning: {losses[0]} -> {losses[-1]}"
    # replicas must agree (params replicated by construction)
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda l: l.sharding.is_fully_replicated, params)
    )

    # -- multi-node evaluation over the trained model ------------------- #
    @jax.jit
    def accuracy(p, xb, yb):
        return jnp.mean(jnp.argmax(model.apply(p, xb), axis=-1) == yb)

    class ShardEvaluator:
        def __init__(self, shard):
            self.shard = shard

        def evaluate(self):
            xs = np.stack([item[0] for item in self.shard])
            ys = np.stack([item[1] for item in self.shard])
            return {"accuracy": float(accuracy(params, xs, ys))}

    evaluator = chainermn_tpu.create_multi_node_evaluator(ShardEvaluator(shards[0]), comm)
    result = evaluator.evaluate()
    assert result["accuracy"] > 0.5, result
