"""jit_train_step coverage: with and without mutable collections (regression
for the flax ``mutable=[]`` tuple-return pitfall)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu
from chainermn_tpu.models import MLP, ResNet
from chainermn_tpu.training import jit_train_step


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("tpu")


def test_step_without_mutable_collections(comm):
    model = MLP(n_units=16, n_out=4, compute_dtype=jnp.float32)
    imgs = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    labels = jnp.zeros((16,), jnp.int32)
    variables = comm.bcast_data(model.init(jax.random.PRNGKey(0), imgs[:1]))
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.05), comm)
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    step = jit_train_step(model, opt, comm)
    v1, s1, loss1 = step(variables, opt_state, imgs, labels)
    _, _, loss2 = step(v1, s1, imgs, labels)
    assert np.isfinite(float(loss1)) and float(loss2) < float(loss1)


def test_label_smoothing_step(comm):
    """label_smoothing=s must reproduce optax.softmax_cross_entropy on
    smoothed one-hot targets exactly (recipe ingredient, arXiv:1711.04325
    era); smoothing raises the optimal-fit loss floor above the hard-label
    one."""
    model = MLP(n_units=16, n_out=4, compute_dtype=jnp.float32)
    rng = np.random.RandomState(3)
    imgs = jnp.asarray(rng.randn(16, 8), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    variables = comm.bcast_data(model.init(jax.random.PRNGKey(0), imgs[:1]))
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.0), comm)
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())

    step_s = jit_train_step(model, opt, comm, donate=False, label_smoothing=0.1)
    _, _, loss_s = step_s(variables, opt_state, imgs, labels)
    # host reference on the same (lr=0 -> unchanged) params
    logits = model.apply(variables, imgs)
    targets = optax.smooth_labels(jax.nn.one_hot(labels, 4), 0.1)
    want = float(optax.softmax_cross_entropy(jnp.asarray(logits), targets).mean())
    np.testing.assert_allclose(float(loss_s), want, rtol=1e-6)

    step_h = jit_train_step(model, opt, comm, donate=False)
    _, _, loss_h = step_h(variables, opt_state, imgs, labels)
    assert float(loss_s) != float(loss_h)


@pytest.mark.slow  # ~9s; BN batch-stats plumbing stays tier-1 via links_tests BatchNorm coverage — keep tier-1 inside its timeout
def test_step_with_batch_stats(comm):
    model = ResNet(stage_sizes=[1, 1], width=4, num_classes=4,
                   compute_dtype=jnp.float32)
    imgs = jnp.asarray(np.random.RandomState(0).randn(8, 16, 16, 3), jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    variables = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), imgs[:1], train=True)
    )
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.01), comm)
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    step = jit_train_step(model, opt, comm)
    old = jax.device_get(variables["batch_stats"])  # before donation invalidates
    v1, s1, loss = step(variables, opt_state, imgs, labels)
    assert np.isfinite(float(loss))
    # batch stats actually moved (train mode) and stayed replica-consistent
    old = jax.tree_util.tree_leaves(old)
    new = jax.tree_util.tree_leaves(v1["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))


@pytest.mark.parametrize(
    "strategy", ["tpu", "flat", "naive", "hierarchical", "two_dimensional",
                 "single_node"]
)
def test_step_update_equals_global_batch_gradient(strategy):
    """Every strategy's distributed step must produce the SAME first update
    as a single-device step on the full global batch — i.e. it applies the
    MEAN of per-rank grads, not the sum. Regression test for the shard_map
    replication-tracking auto-psum: differentiating wrt invariant params
    yields pre-summed grads, which double-counted with the communicator's
    own mean and silently scaled the effective lr by comm.size (r2 fix in
    training.py: pcast params to varying before the local grad)."""
    import flax.linen as nn

    comm = chainermn_tpu.create_communicator(strategy)

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4, use_bias=False,
                            kernel_init=nn.initializers.zeros)(x)

    model = Lin()
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(4 * comm.size, 3), jnp.float32)
    labels = jnp.asarray(np.arange(4 * comm.size) % 4)
    variables = model.init(jax.random.PRNGKey(0), images[:1])
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(1.0), comm)
    st = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    step = jit_train_step(model, opt, comm, donate=False)
    v1, _, _ = step(variables, st, images, labels)

    def global_loss(p):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    g = jax.grad(global_loss)(variables)
    truth = -1.0 * np.asarray(g["params"]["Dense_0"]["kernel"])
    np.testing.assert_allclose(
        np.asarray(v1["params"]["Dense_0"]["kernel"]), truth,
        rtol=1e-5, atol=1e-7,
    )


def test_hand_written_step_global_mean_loss_is_exact(comm):
    """The hand-written user recipe: define the GLOBAL objective
    (``comm.allreduce(local_mean, "mean")``) and differentiate wrt the
    replicated params — shard_map's replication tracking auto-psums the
    backward, so the grads arriving at the optimizer are already the exact
    global gradient, marked invariant. multi_node_mean_grad must pass those
    through untouched (mean of equal copies == the value; the strategy psum
    would sum them into size x the gradient)."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, use_bias=False,
                            kernel_init=nn.initializers.zeros)(x)

    model = Lin()
    rng = np.random.RandomState(1)
    images = jnp.asarray(rng.randn(4 * comm.size, 3), jnp.float32)
    labels = jnp.asarray(np.arange(4 * comm.size) % 4)
    params = model.init(jax.random.PRNGKey(0), images[:1])
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(1.0), comm)

    def train_step(p, s, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            local = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()
            return comm.allreduce(local, "mean")  # the global objective

        grads = jax.grad(loss_fn)(p)  # auto-psummed: exact global gradient
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s

    step = jax.jit(comm.shard_map(
        train_step,
        in_specs=(P(), P(), comm.data_spec, comm.data_spec),
        out_specs=(P(), P()),
    ))
    p1, _ = step(params, opt.init(params["params"]), images, labels)

    def global_loss(p):
        logits = model.apply(p, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    truth = -1.0 * np.asarray(
        jax.grad(global_loss)(params)["params"]["Dense_0"]["kernel"]
    )
    np.testing.assert_allclose(
        np.asarray(p1["params"]["Dense_0"]["kernel"]), truth,
        rtol=1e-5, atol=1e-7,
    )
