"""jit_train_step coverage: with and without mutable collections (regression
for the flax ``mutable=[]`` tuple-return pitfall)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu
from chainermn_tpu.models import MLP, ResNet
from chainermn_tpu.training import jit_train_step


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("tpu")


def test_step_without_mutable_collections(comm):
    model = MLP(n_units=16, n_out=4, compute_dtype=jnp.float32)
    imgs = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    labels = jnp.zeros((16,), jnp.int32)
    variables = comm.bcast_data(model.init(jax.random.PRNGKey(0), imgs[:1]))
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.05), comm)
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    step = jit_train_step(model, opt, comm)
    v1, s1, loss1 = step(variables, opt_state, imgs, labels)
    _, _, loss2 = step(v1, s1, imgs, labels)
    assert np.isfinite(float(loss1)) and float(loss2) < float(loss1)


def test_step_with_batch_stats(comm):
    model = ResNet(stage_sizes=[1, 1], width=4, num_classes=4,
                   compute_dtype=jnp.float32)
    imgs = jnp.asarray(np.random.RandomState(0).randn(8, 16, 16, 3), jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    variables = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), imgs[:1], train=True)
    )
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.01), comm)
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    step = jit_train_step(model, opt, comm)
    old = jax.device_get(variables["batch_stats"])  # before donation invalidates
    v1, s1, loss = step(variables, opt_state, imgs, labels)
    assert np.isfinite(float(loss))
    # batch stats actually moved (train mode) and stayed replica-consistent
    old = jax.tree_util.tree_leaves(old)
    new = jax.tree_util.tree_leaves(v1["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))
