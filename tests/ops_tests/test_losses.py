"""Chunked softmax CE vs the materialized-logits oracle (values + grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.ops.losses import chunked_softmax_cross_entropy


def _setup(key, n=24, d=8, v=40, dtype=jnp.float32):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    hidden = jax.random.normal(k1, (n, d), dtype)
    kernel = jax.random.normal(k2, (d, v), dtype) * 0.3
    bias = jax.random.normal(k3, (v,), dtype) * 0.1
    targets = jax.random.randint(k4, (n,), 0, v)
    return hidden, kernel, bias, targets


def _oracle(hidden, kernel, bias, targets):
    lg = (hidden.astype(jnp.float32) @ kernel.astype(jnp.float32))
    if bias is not None:
        lg = lg + bias.astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(lg, targets)


@pytest.mark.parametrize("chunk", [8, 7, 24, 100])
def test_values_match_oracle(chunk):
    """Chunk sizes that divide N, don't divide N (padding), equal N, and
    exceed N must all reproduce the materialized-logits CE."""
    hidden, kernel, bias, targets = _setup(jax.random.PRNGKey(0))
    got = chunked_softmax_cross_entropy(hidden, kernel, bias, targets,
                                        chunk_size=chunk)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_oracle(hidden, kernel, bias,
                                                  targets)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("with_bias", [True, False])
def test_grads_match_oracle(with_bias):
    hidden, kernel, bias, targets = _setup(jax.random.PRNGKey(1))
    if not with_bias:
        bias = None

    def loss_chunked(h, k, b):
        return chunked_softmax_cross_entropy(h, k, b, targets,
                                             chunk_size=7).mean()

    def loss_oracle(h, k, b):
        return _oracle(h, k, b, targets).mean()

    args = (hidden, kernel, bias)
    wrt = (0, 1) if bias is None else (0, 1, 2)
    g_c = jax.grad(loss_chunked, argnums=wrt)(*args)
    g_o = jax.grad(loss_oracle, argnums=wrt)(*args)
    for a, b_, name in zip(g_c, g_o, ["hidden", "kernel", "bias"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=2e-5, err_msg=name)


def test_leading_shape_and_bf16():
    """[B, T] leading shape round-trips; bf16 hidden/kernel accumulate the
    tile in f32 (no bf16 logsumexp)."""
    hidden, kernel, bias, targets = _setup(jax.random.PRNGKey(2), n=32,
                                           dtype=jnp.bfloat16)
    h2 = hidden.reshape(4, 8, -1)
    t2 = targets.reshape(4, 8)
    got = chunked_softmax_cross_entropy(h2, kernel, bias, t2, chunk_size=8)
    assert got.shape == (4, 8)
    assert got.dtype == jnp.float32
    want = _oracle(hidden, kernel, bias, targets).reshape(4, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_weighted_cotangent():
    """Non-uniform per-token cotangents (e.g. masked means) flow exactly."""
    hidden, kernel, bias, targets = _setup(jax.random.PRNGKey(3))
    w = jnp.linspace(0.0, 1.0, targets.shape[0])

    def loss_chunked(h):
        return jnp.sum(chunked_softmax_cross_entropy(
            h, kernel, bias, targets, chunk_size=7) * w)

    def loss_oracle(h):
        return jnp.sum(_oracle(h, kernel, bias, targets) * w)

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_chunked)(hidden)),
        np.asarray(jax.grad(loss_oracle)(hidden)),
        rtol=2e-5, atol=2e-5)
