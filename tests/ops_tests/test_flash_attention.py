"""Flash attention kernel vs the XLA reference: values and gradients.

Runs in Pallas interpret mode on CPU (the TPU-compiled path is the same
kernel code; interpret mode checks the math, SURVEY.md S4's 'multi-node
without a cluster' testing stance applied to kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.ops import flash_attention
from chainermn_tpu.parallel.sequence import full_attention


def _qkv(key, b=2, t=64, h=2, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1), t=32, d=8)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = full_attention(q, k, v, causal=causal)
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_cross_attention_rectangular():
    """T_q != T_k (cross attention shape)."""
    q, _, _ = _qkv(jax.random.PRNGKey(2), t=24)
    _, k, v = _qkv(jax.random.PRNGKey(3), t=48)
    got = flash_attention(q, k, v, block_q=8, block_k=16)
    want = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_offsets_reproduce_sharded_causal_slice():
    """flash on a q slice with q_offset equals the slice of full causal
    attention — the sequence-sharding contract."""
    q, k, v = _qkv(jax.random.PRNGKey(4), t=32)
    want = full_attention(q, k, v, causal=True)
    t_half = 16
    got_hi = flash_attention(
        q[:, t_half:], k, v, causal=True,
        q_offset=t_half, k_offset=0, block_q=8, block_k=8,
    )
    np.testing.assert_allclose(np.asarray(got_hi), np.asarray(want[:, t_half:]),
                               rtol=1e-5, atol=1e-5)


def test_traced_offsets():
    """Offsets may be traced values (axis_index-style callers)."""
    q, k, v = _qkv(jax.random.PRNGKey(5), t=16)

    @jax.jit
    def f(off):
        return flash_attention(q[:, 8:], k, v, causal=True,
                               q_offset=off, block_q=8, block_k=8)

    got = f(jnp.int32(8))
    want = full_attention(q, k, v, causal=True)[:, 8:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fully_masked_rows_zero_grads():
    """A q slice entirely BEFORE all keys (causal): output 0, grads 0 — the
    -inf lse sentinel must not produce NaN/garbage in backward."""
    q, k, v = _qkv(jax.random.PRNGKey(6), t=16)

    def loss(k, v):
        o = flash_attention(q, k, v, causal=True,
                            q_offset=0, k_offset=100,  # all keys in future
                            block_q=8, block_k=8)
        return jnp.sum(o * o), o

    (l, o), g = jax.value_and_grad(loss, argnums=(0, 1), has_aux=True)(k, v)
    assert float(l) == 0.0
    np.testing.assert_array_equal(np.asarray(o), 0.0)
    for gi in g:
        np.testing.assert_array_equal(np.asarray(gi), 0.0)


def test_bfloat16_inputs():
    q, k, v = _qkv(jax.random.PRNGKey(7), dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    want = full_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_partially_masked_block_rows_zero():
    """Causal with k_offset not a multiple of block_q: rows 0..3 are fully
    masked INSIDE a visited k-block. They must output exactly 0 (not
    mean-of-V garbage from exp(sentinel - sentinel) == 1)."""
    q, k, v = _qkv(jax.random.PRNGKey(9), t=8, h=1, d=4)
    got = flash_attention(q, k, v, causal=True, q_offset=0, k_offset=4,
                          block_q=8, block_k=8)
    # reference with explicit global-position mask
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (4 ** -0.5)
    mask = (jnp.arange(8)[:, None] >= (4 + jnp.arange(8))[None, :])
    s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jnp.where(mask[None, None], jax.nn.softmax(s, axis=-1), 0.0)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_array_equal(np.asarray(got[:, :4]), 0.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_awkward_length_falls_back_to_xla():
    """T prime and above the block size has no usable divisor (block would
    degenerate to 1): the XLA fallback must engage (same numerics), and the
    offset-causal case must raise clearly."""
    q, k, v = _qkv(jax.random.PRNGKey(8), t=251)
    got = flash_attention(q, k, v, causal=True)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v, causal=True, q_offset=13)


def test_flash_kind_rejects_sharded_axis():
    from chainermn_tpu.parallel.sequence import sequence_parallel_attention
    with pytest.raises(ValueError, match="ring"):
        sequence_parallel_attention("flash", "ranks")


def test_pick_block_contract():
    """Sublane-granular block picker (round 5): candidates are multiples of
    8 for t > 8 (Mosaic's tiling rule — the old picker could emit e.g. one
    251-row block that only lowers in interpret mode), sub-8 requests on
    t > 8 round up to the hardware-minimum 8, no-divisor lengths return 1
    (the callers' fallback/raise sentinel), and t <= 8 keeps the plain
    largest-divisor-<=-preferred search."""
    from chainermn_tpu.ops.flash_attention import _pick_block

    assert _pick_block(1024, 512) == 512       # default path
    assert _pick_block(2048, 512) == 512
    assert _pick_block(64, 512) == 64          # whole (multiple-of-8) block
    assert _pick_block(24, 512) == 24
    assert _pick_block(16, 512) == 16
    assert _pick_block(251, 512) == 1          # prime: fallback sentinel
    assert _pick_block(12, 512) == 1           # no multiple-of-8 divisor
    assert _pick_block(64, 4) == 8             # sub-8 request rounds up
    assert _pick_block(8, 4) == 4              # t <= 8: plain divisor search
    assert _pick_block(6, 512) == 6
    assert _pick_block(4, 512) == 4


def test_default_block():
    """The data-driven default (round-5 on-chip sweep + the block-1024
    T=131072 AOT ceiling proof): 1024 at every length. This widening was
    the deliberate test change the previous revision's comment promised,
    backed by the landed ceiling run (aot_flash_ceiling.jsonl)."""
    from chainermn_tpu.ops.flash_attention import _default_block

    assert _default_block(2048) == 1024
    assert _default_block(8192) == 1024
    assert _default_block(16384) == 1024
    assert _default_block(131072) == 1024
