"""Microbatch pipeline: forward parity with serial stage application,
gradients, and training convergence on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.ops import pipeline_apply


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("tpu")


def _stage(params, x):
    # shape-preserving residual MLP stage
    return x + jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(key, n, d):
    kw, kb = jax.random.split(key)
    return {
        "w": 0.3 * jax.random.normal(kw, (n, d, d)),
        "b": 0.1 * jax.random.normal(kb, (n, d)),
    }


def _serial(stacked, x):
    for i in range(stacked["w"].shape[0]):
        x = _stage(jax.tree_util.tree_map(lambda l: l[i], stacked), x)
    return x


def _pipelined(comm, n_micro):
    def body(stacked, x):
        local = jax.tree_util.tree_map(lambda l: l[0], stacked)
        return pipeline_apply(_stage, local, x, comm.axis_name, n_micro)

    return jax.jit(
        comm.shard_map(body, in_specs=(comm.data_spec, P()), out_specs=P())
    )


def test_pipeline_matches_serial(comm):
    n, d, b = comm.size, 8, 16
    stacked = _stacked_params(jax.random.PRNGKey(0), n, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d))
    want = _serial(stacked, x)
    got = _pipelined(comm, n_micro=4)(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_single_microbatch_and_many(comm):
    n, d, b = comm.size, 4, 8
    stacked = _stacked_params(jax.random.PRNGKey(2), n, d)
    x = jax.random.normal(jax.random.PRNGKey(3), (b, d))
    want = _serial(stacked, x)
    for n_micro in (1, 8):
        got = _pipelined(comm, n_micro)(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5, err_msg=str(n_micro))


def test_pipeline_gradients_match_serial(comm):
    n, d, b = comm.size, 6, 12
    stacked = _stacked_params(jax.random.PRNGKey(4), n, d)
    x = jax.random.normal(jax.random.PRNGKey(5), (b, d))
    y = jax.random.normal(jax.random.PRNGKey(6), (b, d))

    def loss_serial(p):
        return jnp.mean((_serial(p, x) - y) ** 2)

    def body(stacked, x, y):
        local = jax.tree_util.tree_map(lambda l: l[0], stacked)
        out = pipeline_apply(_stage, local, x, comm.axis_name, 4)
        return jnp.mean((out - y) ** 2)

    def loss_pipe(p):
        f = comm.shard_map(body, in_specs=(comm.data_spec, P(), P()),
                           out_specs=P())
        return f(p, x, y)

    g_want = jax.grad(loss_serial)(stacked)
    g_got = jax.jit(jax.grad(loss_pipe))(stacked)
    for k in g_want:
        np.testing.assert_allclose(np.asarray(g_got[k]), np.asarray(g_want[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_remat_matches_serial_forward_and_grad(comm):
    """remat=True (the 1F1B-memory-profile option) must be numerically
    invisible: same outputs, same gradients, only the backward recomputes."""
    n, d, b = comm.size, 6, 12
    stacked = _stacked_params(jax.random.PRNGKey(8), n, d)
    x = jax.random.normal(jax.random.PRNGKey(9), (b, d))
    y = jax.random.normal(jax.random.PRNGKey(10), (b, d))

    def loss_serial(p):
        return jnp.mean((_serial(p, x) - y) ** 2)

    def body(stacked, x, y):
        local = jax.tree_util.tree_map(lambda l: l[0], stacked)
        out = pipeline_apply(_stage, local, x, comm.axis_name, 4, remat=True)
        return jnp.mean((out - y) ** 2)

    def loss_pipe(p):
        f = comm.shard_map(body, in_specs=(comm.data_spec, P(), P()),
                           out_specs=P())
        return f(p, x, y)

    np.testing.assert_allclose(
        float(jax.jit(loss_pipe)(stacked)), float(loss_serial(stacked)),
        rtol=1e-5,
    )
    g_want = jax.grad(loss_serial)(stacked)
    g_got = jax.jit(jax.grad(loss_pipe))(stacked)
    for k in g_want:
        np.testing.assert_allclose(np.asarray(g_got[k]), np.asarray(g_want[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_rejects_bad_microbatch_count(comm):
    stacked = _stacked_params(jax.random.PRNGKey(7), comm.size, 4)
    x = jnp.zeros((10, 4))
    with pytest.raises(ValueError, match="divisible"):
        _pipelined(comm, n_micro=3)(stacked, x)
